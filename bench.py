#!/usr/bin/env python
"""Headline benchmark: ResNet-101 synthetic-data training throughput per chip.

Reproduces the reference's benchmark protocol
(/root/reference/docs/benchmarks.md:22-38: tf_cnn_benchmarks ResNet-101,
batch 64 per accelerator, synthetic ImageNet data) on one TPU chip.  The
reference's published number is 1656.82 images/sec on 16 Pascal GPUs =
103.55 images/sec/GPU; `vs_baseline` is our per-chip throughput over that.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Env knobs: BENCH_MODEL (resnet101|resnet50|mnist), BENCH_BATCH, BENCH_STEPS,
BENCH_WARMUP, BENCH_IMAGE (side length).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.md:22-38


def main() -> None:
    import jax

    # BENCH_PLATFORM=cpu forces the CPU backend even where a site hook
    # pre-registers a TPU platform through jax.config (test environments).
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu import models

    model_name = os.environ.get("BENCH_MODEL", "resnet101")
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    side = int(os.environ.get("BENCH_IMAGE", "224"))

    if model_name == "mnist":
        model = models.MnistCNN()
        side, classes = 28, 10
        shape = (batch, side, side, 1)
    else:
        cls = {"resnet50": models.ResNet50, "resnet101": models.ResNet101,
               "resnet18": models.ResNet18}[model_name]
        model = cls(num_classes=1000, dtype=jnp.bfloat16)
        classes = 1000
        shape = (batch, side, side, 3)

    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(np.random.RandomState(0).rand(*shape), jnp.float32)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, classes, batch),
                         jnp.int32)
    variables = model.init(rng, images, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    has_bn = bool(batch_stats)
    dropout_rng = jax.random.PRNGKey(2)

    def loss_fn(params, batch_stats, images, labels):
        variables = {"params": params}
        kwargs = {}
        if has_bn:
            variables["batch_stats"] = batch_stats
            kwargs["mutable"] = ["batch_stats"]
        else:
            kwargs["rngs"] = {"dropout": dropout_rng}
        out = model.apply(variables, images, train=True, **kwargs)
        logits, new_stats = out if has_bn else (out, batch_stats)
        new_stats = new_stats["batch_stats"] if has_bn else new_stats
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, new_stats

    @jax.jit
    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    for _ in range(warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    # Force completion by fetching a value: on remote-tunneled backends
    # block_until_ready can return before the computation has run.
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    # The final loss depends on every step's params, so one scalar fetch
    # drains the whole chain.
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), final_loss

    value = batch * steps / dt
    print(json.dumps({
        "metric": f"{model_name}_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / REFERENCE_IMG_PER_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    main()
