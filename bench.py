#!/usr/bin/env python
"""Headline benchmark: ResNet-101 synthetic-data training throughput per chip.

Reproduces the reference's benchmark protocol
(/root/reference/docs/benchmarks.md:22-38: tf_cnn_benchmarks ResNet-101,
batch 64 per accelerator, synthetic ImageNet data) on one TPU chip.  The
reference's published number is 1656.82 images/sec on 16 Pascal GPUs =
103.55 images/sec/GPU; `vs_baseline` is our per-chip throughput over that.

Prints the headline JSON line FIRST:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
then (default resnet101 invocation) re-prints it enriched with the
transformer LM and long-context (seq 8192) tokens/sec folded into
"extra_metrics" — a second line, so an extra that fails (or floods stderr
with a compiler error) can never erase the already-printed headline.
Extra errors are clipped to one short line.  BENCH_EXTRA=0 disables,
BENCH_EXTRA_CONFIGS="seq:batch,..." overrides the sweep.

Env knobs: BENCH_MODEL (resnet101|resnet50|resnet18|vgg16|inception_v3|
mnist|transformer|allreduce|small_allreduce|big_allreduce|hier_allreduce|
negotiation_scale|serve_decode|checkpoint|scaling|pipeline), BENCH_BATCH,
BENCH_STEPS, BENCH_WARMUP, BENCH_IMAGE (side
length); transformer adds BENCH_SEQ/BENCH_VOCAB/BENCH_D_MODEL/BENCH_LAYERS/
BENCH_HEADS; allreduce adds BENCH_NP/BENCH_BYTES/BENCH_ITERS;
small_allreduce (the negotiation-bound cache microbench) adds
BENCH_NP/BENCH_TENSORS/BENCH_STEPS; big_allreduce (the bandwidth-bound
wire-compression sweep, docs/performance.md#wire-compression) adds
BENCH_NP/BENCH_BYTES/BENCH_ITERS; negotiation_scale (the simulated-scale
control-plane bench, docs/performance.md#control-plane-scaling) adds
BENCH_SCALE_RANKS/BENCH_OPS/BENCH_WARM_CYCLES/BENCH_STEADY_CYCLES;
serve_decode (the serving-plane continuous-batching bench,
docs/inference.md) adds BENCH_NP/BENCH_REQUESTS; pipeline (the 1F1B
pipeline-parallel sweep, docs/pipeline.md) adds BENCH_NP/BENCH_STAGES/
BENCH_CHUNKS/BENCH_MICROBATCHES plus the transformer size knobs.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.md:22-38


def bench_transformer(seq: int = None, batch: int = None,
                      steps: int = None, report: bool = True) -> float:
    """LM training throughput (tokens/sec/chip), flash attention + bf16."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import TransformerLM, next_token_loss

    # Batch 16 is the measured single-chip sweet spot on v5e (batch 8
    # under-fills the MXU; batch 32 pressures HBM with the f32 logits).
    if batch is None:
        batch = int(os.environ.get("BENCH_BATCH", "16"))
    if seq is None:
        seq = int(os.environ.get("BENCH_SEQ", "1024"))
    if steps is None:
        steps = int(os.environ.get("BENCH_STEPS", "20"))
    # Multi-step dispatch, as the resnet headline (r5: 305k -> 320k
    # tok/s at seq 1024 going 1 -> 8); default 4 balances the gain
    # against the ~unroll-fold compile time across the extras sweep.
    unroll = max(1, int(os.environ.get("BENCH_UNROLL", "4")))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    vocab = int(os.environ.get("BENCH_VOCAB", "32768"))
    # bf16 logits STORAGE (f32 accumulation and f32 loss internals): the
    # logits tensor dominates the step's HBM traffic; see TransformerLM.
    logits_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_LOGITS_DTYPE", "bfloat16")]
    model = TransformerLM(
        vocab_size=vocab,
        d_model=int(os.environ.get("BENCH_D_MODEL", "512")),
        n_layers=int(os.environ.get("BENCH_LAYERS", "8")),
        n_heads=int(os.environ.get("BENCH_HEADS", "8")),
        logits_dtype=logits_dtype)

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, vocab, (batch, seq + 1)))
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inputs[:, :128])["params"]
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    def one_step(params, opt_state, inputs, targets):
        def loss_fn(p):
            return next_token_loss(
                model.apply({"params": p}, inputs), targets)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Donation lets XLA update params/opt state in place (no fresh HBM
    # buffers per step), same as the image-model step below.  inputs/
    # targets MUST thread through as traced jit arguments — closed-over
    # arrays would bake into the executable as constants, letting XLA
    # specialize the program in ways impossible in real training.
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, inputs, targets):
        for _ in range(unroll):
            params, opt_state, loss = one_step(params, opt_state,
                                               inputs, targets)
        return params, opt_state, loss

    for _ in range(max(warmup, 1)):
        params, opt_state, loss = step(params, opt_state, inputs, targets)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, inputs, targets)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), final_loss
    value = batch * seq * steps * unroll / dt
    if report:
        print(json.dumps({
            "metric": "transformer_train_tokens_per_sec_per_chip",
            "value": round(value, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": None,  # the reference has no LM benchmark
        }))
    return value


def bench_scaling() -> None:
    """DP scaling efficiency: ResNet-50 shard_map step at 1 vs N devices.

    The BASELINE.md tracked metric (scaling efficiency 8->256 chips on a
    v5e pod) measured with the same methodology on whatever mesh is
    available: efficiency = throughput(N) / (throughput(1) * N) with the
    per-device batch held constant.  On a single-chip or CPU environment
    this exercises the harness on a virtual device mesh.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import models
    from horovod_tpu.jax.train import build_train_step
    from horovod_tpu.parallel import (data_parallel_mesh, replicate,
                                      shard_batch)

    per_dev_batch = int(os.environ.get("BENCH_BATCH", "16"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    side = int(os.environ.get("BENCH_IMAGE", "96"))
    n_dev = len(jax.devices())

    def throughput(devices):
        n = len(devices)
        mesh = data_parallel_mesh(devices, axis_name="hvd")
        model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                                axis_name="hvd")
        batch = per_dev_batch * n
        images = np.random.RandomState(0).rand(
            batch, side, side, 3).astype(np.float32)
        labels = np.random.RandomState(1).randint(0, 1000, batch)
        variables = model.init(jax.random.PRNGKey(0), images[:2],
                               train=False)
        params, stats = variables["params"], variables["batch_stats"]

        def loss_fn(params, b):
            imgs, labs, stats = b
            logits, upd = model.apply(
                {"params": params, "batch_stats": stats}, imgs,
                train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labs).mean()
            return loss, upd["batch_stats"]

        tx = optax.sgd(0.1, momentum=0.9)
        step = build_train_step(loss_fn, tx, mesh, axis_name="hvd",
                                has_aux=True,
                                batch_spec=(P("hvd"), P("hvd"), P()))
        params = replicate(mesh, params)
        opt_state = replicate(mesh, tx.init(params))
        b = (shard_batch(mesh, images),
             shard_batch(mesh, jnp.asarray(labels, jnp.int32)),
             replicate(mesh, stats))
        for _ in range(max(warmup, 1)):
            params, opt_state, loss, stats2 = step(params, opt_state, b)
            b = (b[0], b[1], stats2)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss, stats2 = step(params, opt_state, b)
            b = (b[0], b[1], stats2)
        float(loss)
        return batch * steps / (time.perf_counter() - t0)

    # Baseline at the smallest addressable granularity: one device in
    # single-process jobs, this process's devices on a multi-host slice
    # (a 1-global-device mesh would be non-addressable from other hosts).
    local = jax.local_devices()
    base_devices = local[:1] if jax.process_count() == 1 else local
    base = throughput(base_devices)
    full = throughput(jax.devices())
    n_base = len(base_devices)
    efficiency = full / (base * n_dev / n_base)
    # Regression guard (BENCH_SCALING_FLOOR): on the virtual CPU mesh all
    # N devices share the host cores, so the meaningful floor is against
    # the core-normalized ceiling 1/N (e.g. 0.10 at N=8 = 83% of the
    # 1-core ceiling); on a real pod slice compare against 0.88.
    floor = os.environ.get("BENCH_SCALING_FLOOR")
    if floor is not None:
        assert efficiency >= float(floor), (
            f"scaling efficiency {efficiency:.4f} fell below the floor "
            f"{float(floor):.4f}")
    # Core-normalized floor, portable across virtual-mesh hosts: with C
    # cores shared by N virtual devices the compute-bound ceiling is C/N,
    # so efficiency * N / min(C, N) isolates sharding+collective overhead
    # from host core count (docs/benchmarks.md, scaling harness).
    norm_floor = os.environ.get("BENCH_SCALING_FLOOR_NORM")
    if norm_floor is not None:
        try:  # respects taskset/cgroup pinning, unlike os.cpu_count()
            cores = len(os.sched_getaffinity(0))
        except AttributeError:  # pragma: no cover - non-Linux
            cores = os.cpu_count() or 1
        normalized = efficiency * n_dev / min(cores, n_dev)
        assert normalized >= float(norm_floor), (
            f"core-normalized scaling efficiency {normalized:.4f} "
            f"(raw {efficiency:.4f} x {n_dev}/{min(cores, n_dev)}) fell "
            f"below the floor {float(norm_floor):.4f}")
    if jax.process_index() == 0:  # one JSON line per job, not per host
        print(json.dumps({
            "metric": f"resnet50_dp_scaling_efficiency_{n_base}_to_{n_dev}",
            "value": round(efficiency, 4),
            "unit": "fraction",
            "vs_baseline": round(efficiency / 0.88, 3),  # target >= 0.88
        }))


def bench_allreduce() -> None:
    """Engine eager ring-allreduce bandwidth over NP local ranks."""
    import subprocess
    import sys

    np_ = int(os.environ.get("BENCH_NP", "2"))
    nbytes = int(os.environ.get("BENCH_BYTES", str(64 * 1024 * 1024)))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    code = f"""
import json, time, numpy as np, horovod_tpu as hvd
hvd.init()
x = np.ones({nbytes} // 4, np.float32)
hvd.allreduce(x, average=False, name="warmup")
t0 = time.perf_counter()
for i in range({iters}):
    hvd.allreduce(x, average=False, name=f"bench.{{i}}")
dt = time.perf_counter() - t0
if hvd.rank() == 0:
    # Ring allreduce moves 2*(N-1)/N * nbytes per rank per iteration.
    n = hvd.size()
    algo_bytes = 2 * (n - 1) / n * {nbytes} * {iters}
    print("BW_GBPS", algo_bytes / dt / 1e9, flush=True)
    # Collective-layer health alongside throughput (docs/metrics.md):
    # the launcher env enables the registry, so the snapshot carries the
    # op/byte/stall counters for this rank's run.
    snap = hvd.metrics_snapshot()
    at = snap["autotune"]
    print("METRICS_JSON " + json.dumps({{
        "collective_ops": sum(sum(v.values()) for v in snap["ops"].values()),
        "collective_bytes_in": sum(v["in"] for v in snap["bytes"].values()),
        "collective_bytes_out": sum(v["out"] for v in snap["bytes"].values()),
        "stall_events": snap["stalls"]["count"],
        "autotune": {{k: at[k] for k in ("enabled", "frozen", "windows",
                                         "fusion_threshold",
                                         "cycle_time_ms")}},
    }}), flush=True)
"""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    # Metrics ride along in extra_metrics (docs/metrics.md); an explicit
    # HVD_TPU_METRICS=0 in the caller's env still wins.
    env.setdefault("HVD_TPU_METRICS", "1")
    if os.environ.get("BENCH_AUTOTUNE", "0") != "0":
        # Autotune ride-along (docs/performance.md#autotuning): tune while
        # the bandwidth bench runs and fold the applied params into
        # extra_metrics.  Small windows — the bench only runs
        # BENCH_ITERS collectives.
        env["HVD_TPU_AUTOTUNE"] = "1"
        env.setdefault("HVD_TPU_AUTOTUNE_WINDOW", "4")
        env.setdefault("HVD_TPU_AUTOTUNE_WARMUP", "1")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    bw = next(float(line.split()[1]) for line in out.stdout.splitlines()
              if line.startswith("BW_GBPS"))
    floor = os.environ.get("BENCH_ALLREDUCE_FLOOR_GBPS")
    if floor is not None:
        assert bw >= float(floor), (
            f"engine ring-allreduce bandwidth {bw:.3f} GB/s at np={np_} "
            f"fell below the floor {float(floor):.3f} GB/s")
    record = {
        "metric": f"engine_ring_allreduce_bandwidth_np{np_}",
        "value": round(bw, 3),
        "unit": "GB/s",
        "vs_baseline": None,  # the reference published no allreduce number
    }
    # Fold rank 0's metrics snapshot in so BENCH rounds track collective-
    # layer health (ops, bytes, stalls) alongside the bandwidth headline.
    for line in out.stdout.splitlines():
        if line.startswith("METRICS_JSON "):
            record["extra_metrics"] = json.loads(
                line[len("METRICS_JSON "):])
    print(json.dumps(record))


def bench_small_allreduce() -> None:
    """Negotiation-bound microbench (docs/performance.md): BENCH_TENSORS
    tiny named allreduces repeated steady-state for BENCH_STEPS steps over
    BENCH_NP local ranks.  The payload is 32 bytes, so throughput here is
    pure control plane: coordinator roundtrips, string (de)serialization,
    and the engine tick — exactly what the response cache and adaptive
    tick attack.  Runs twice (cache on, then HVD_TPU_RESPONSE_CACHE=0) and
    folds the comparison, rank 0's cache hit/miss counters, and the
    negotiation_sec p50 into extra_metrics.

    BENCH_AUTOTUNE=1 adds a third run: online autotuning from
    deliberately bad initial params (fusion threshold 1024 B, cycle 50 ms
    — the docs/performance.md#autotuning acceptance shape), training
    until the search freezes, then measuring steady-state throughput.
    extra_metrics gains the tuned ops/sec, tuned-vs-default ratio,
    windows-to-convergence, and the frozen params."""
    import subprocess
    import sys

    # 256 tensors/step puts the run squarely in the regime the cache
    # targets: with a handful of tensors the frame round trip dominates
    # and cache on/off measure within noise of each other.
    np_ = int(os.environ.get("BENCH_NP", "4"))
    tensors = int(os.environ.get("BENCH_TENSORS", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "50"))
    repo = os.path.dirname(os.path.abspath(__file__))
    code = f"""
import json, os, sys, time, numpy as np, horovod_tpu as hvd
sys.path.insert(0, {repo!r})
from tools.metrics_dump import quantile
hvd.init()
K, S = {tensors}, {steps}
# Realistic gradient-style names: string volume on the wire and at the
# coordinator is what the cache removes, and production tensor names are
# long ("model/layer_42/attention/query/kernel_grad"), not "t3".
names = [f"model.layer_{{k:04d}}.attention.query.kernel.grad"
         for k in range(K)]
xs = [np.ones(8, np.float32) for _ in range(K)]
def step():
    hs = [hvd.allreduce_async(xs[k], average=False, name=names[k])
          for k in range(K)]
    for h in hs:
        h.wait()
step()  # warm: full negotiation populates the cache
if os.environ.get("HVD_TPU_AUTOTUNE"):
    # Autotune mode: train through the search (bad initial params) until
    # it freezes, so the timed window below measures the TUNED steady
    # state, not the climb.  The break is decided COLLECTIVELY: ranks
    # observe the freeze broadcast at different wall times, and a
    # rank-local break would leave the others' last step unmatched.
    for s in range(4000):
        step()
        f = np.asarray([int(hvd.autotune_report()["frozen"])], np.int32)
        if int(hvd.allreduce(f, average=False,
                             name="at.poll")[0]) == hvd.size():
            break
t0 = time.perf_counter()
for s in range(S - 1):
    step()
dt = time.perf_counter() - t0
if hvd.rank() == 0:
    snap = hvd.metrics_snapshot()
    p50 = quantile(snap["histograms"]["negotiation_sec"], 0.5)
    print("SMALL_JSON " + json.dumps({{
        "ops_per_sec": K * (S - 1) / dt,
        "cache": snap["cache"]["engine"],
        "negotiation_p50_us": round((p50 or 0.0) * 1e6, 1),
        "autotune": snap["autotune"],
    }}), flush=True)
"""

    def run(cache_on: bool, autotune: bool = False) -> dict:
        env = dict(os.environ,
                   PYTHONPATH=repo + os.pathsep +
                   os.environ.get("PYTHONPATH", ""),
                   HVD_TPU_RESPONSE_CACHE="1" if cache_on else "0")
        env.setdefault("HVD_TPU_METRICS", "1")
        if autotune:
            # The acceptance shape (docs/performance.md#autotuning):
            # deliberately bad initial params the search must climb out
            # of before the timed window runs.
            env["HVD_TPU_AUTOTUNE"] = "1"
            env["HVD_TPU_FUSION_THRESHOLD"] = "1024"
            env["HVD_TPU_CYCLE_TIME_MS"] = "50"
            env.setdefault("HVD_TPU_AUTOTUNE_WINDOW", "256")
        else:
            env.pop("HVD_TPU_AUTOTUNE", None)
            # A tight idle cycle keeps the (cache-independent) co-arrival
            # alignment window from drowning the negotiation-work delta
            # this bench exists to measure; override to probe other
            # regimes.
            env.setdefault("HVD_TPU_CYCLE_TIME_MS", "1")
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
             "--", sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=600)
        assert out.returncode == 0, out.stderr[-2000:]
        return next(json.loads(line[len("SMALL_JSON "):])
                    for line in out.stdout.splitlines()
                    if line.startswith("SMALL_JSON "))

    on = run(True)
    off = run(False)
    hits, misses = on["cache"]["hits"], on["cache"]["misses"]
    record = {
        "metric": f"small_allreduce_ops_per_sec_np{np_}",
        "value": round(on["ops_per_sec"], 1),
        "unit": "ops/sec",
        "vs_baseline": None,  # the reference published no such number
        "extra_metrics": {
            "cache_off_ops_per_sec": round(off["ops_per_sec"], 1),
            "cache_speedup": round(on["ops_per_sec"]
                                   / max(off["ops_per_sec"], 1e-9), 3),
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": round(hits / max(hits + misses, 1), 4),
            "negotiation_p50_us_cached": on["negotiation_p50_us"],
            "negotiation_p50_us_uncached": off["negotiation_p50_us"],
        },
    }
    if os.environ.get("BENCH_AUTOTUNE", "0") != "0":
        tuned = run(True, autotune=True)
        at = tuned.get("autotune", {})
        record["extra_metrics"].update({
            "autotune_ops_per_sec": round(tuned["ops_per_sec"], 1),
            # >= 0.9 is the acceptance bar: starting from deliberately
            # bad params the tuner must recover (nearly) the hand-tuned
            # default throughput.
            "autotune_vs_default": round(
                tuned["ops_per_sec"] / max(on["ops_per_sec"], 1e-9), 3),
            "autotune_windows_to_convergence": at.get("windows"),
            "autotune_frozen": at.get("frozen"),
            "autotune_fusion_threshold": at.get("fusion_threshold"),
            "autotune_cycle_time_ms": at.get("cycle_time_ms"),
        })
    print(json.dumps(record))


def bench_big_allreduce() -> None:
    """Bandwidth-bound large-tensor allreduce with the wire-compression
    sweep (docs/performance.md#wire-compression): BENCH_BYTES of fp32
    repeated steady-state over BENCH_NP local ranks, once per
    HVD_TPU_COMPRESSION mode (off, bf16, fp8).  Headline is the bf16-mode
    ops/sec; extra_metrics carries each mode's ops/sec and wire bytes
    (`_bytes` extras gate lower-is-better in tools/bench_compare.py), the
    off/compressed byte ratios (>= 1.8x for bf16 is the acceptance bar),
    each mode's max relative error vs the fp32 result, and the bf16
    -payload wire inflation (1.0 = native width; 2.0 was the old f32
    staging)."""
    import subprocess
    import sys

    np_ = int(os.environ.get("BENCH_NP", "4"))
    nbytes = int(os.environ.get("BENCH_BYTES", str(32 * 1024 * 1024)))
    iters = int(os.environ.get("BENCH_ITERS", "8"))
    repo = os.path.dirname(os.path.abspath(__file__))
    code = f"""
import json, time, numpy as np, ml_dtypes, horovod_tpu as hvd
hvd.init()
n = {nbytes} // 4
x = np.random.RandomState(hvd.rank()).rand(n).astype(np.float32) - 0.5
want = np.zeros(n, np.float32)
for i in range(hvd.size()):
    want += np.random.RandomState(i).rand(n).astype(np.float32) - 0.5
want /= hvd.size()
out = hvd.allreduce(x, average=True, name="big.steady")  # warm: negotiate
mark = hvd.compression_report()["engine"]
t0 = time.perf_counter()
for i in range({iters}):
    out = hvd.allreduce(x, average=True, name="big.steady")
dt = time.perf_counter() - t0
rep = hvd.compression_report()["engine"]
err = float(np.max(np.abs(out - want)) / max(float(np.max(np.abs(want))),
                                             1e-9))
# bf16-payload inflation probe: native-width wire means delta wire ==
# delta payload (the old f32 staging paid 2x).
xb = (np.random.RandomState(7).rand(1 << 18).astype(np.float32)
      / 4).astype(ml_dtypes.bfloat16)
b0 = hvd.compression_report()["engine"]
hvd.allreduce(xb, average=False, name="big.half")
b1 = hvd.compression_report()["engine"]
if hvd.rank() == 0:
    print("BIG_JSON " + json.dumps({{
        "ops_per_sec": {iters} / dt,
        "gbps": 2 * (hvd.size() - 1) / hvd.size() * {nbytes} * {iters}
                / dt / 1e9,
        "wire_bytes": rep["wire_bytes"] - mark["wire_bytes"],
        "payload_bytes": rep["payload_bytes"] - mark["payload_bytes"],
        "max_rel_err": err,
        "half_wire_inflation": (b1["wire_bytes"] - b0["wire_bytes"])
                               / max(b1["payload_bytes"]
                                     - b0["payload_bytes"], 1),
    }}), flush=True)
"""

    def run(mode: str) -> dict:
        env = dict(os.environ,
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   HVD_TPU_COMPRESSION=mode)
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
             "--", sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=600)
        assert out.returncode == 0, (mode, out.stderr[-2000:])
        return next(json.loads(line[len("BIG_JSON "):])
                    for line in out.stdout.splitlines()
                    if line.startswith("BIG_JSON "))

    off = run("off")
    b16 = run("bf16")
    f8 = run("fp8")
    ratio16 = off["wire_bytes"] / max(b16["wire_bytes"], 1)
    ratio8 = off["wire_bytes"] / max(f8["wire_bytes"], 1)
    floor = float(os.environ.get("BENCH_BIG_ALLREDUCE_MIN_RATIO", "1.8"))
    assert ratio16 >= floor, (
        f"bf16 wire mode moved only {ratio16:.2f}x fewer bytes than the "
        f"fp32 baseline (want >= {floor:.1f}x): "
        f"{b16['wire_bytes']} vs {off['wire_bytes']}")
    print(json.dumps({
        "metric": f"big_allreduce_ops_per_sec_np{np_}",
        "value": round(b16["ops_per_sec"], 2),
        "unit": "ops/sec",
        "vs_baseline": None,  # the reference published no such number
        "extra_metrics": {
            "off_ops_per_sec": round(off["ops_per_sec"], 2),
            "fp8_ops_per_sec": round(f8["ops_per_sec"], 2),
            "bf16_gbps_effective": round(b16["gbps"], 3),
            "off_wire_bytes": off["wire_bytes"],
            "bf16_wire_bytes": b16["wire_bytes"],
            "fp8_wire_bytes": f8["wire_bytes"],
            "bf16_compression_ratio": round(ratio16, 3),
            "fp8_compression_ratio": round(ratio8, 3),
            "bf16_max_rel_err": round(b16["max_rel_err"], 6),
            "fp8_max_rel_err": round(f8["max_rel_err"], 6),
            "half_wire_inflation": round(off["half_wire_inflation"], 3),
        },
    }))


def bench_hier_allreduce() -> None:
    """Two-level topology bench (docs/performance.md#two-level-topology):
    flat-ring vs two-level allreduce at BENCH_NP ranks as
    local_size-2 nodes, BENCH_BYTES fp32 steady-state.  Headline is the
    two-level ops/sec; extra_metrics carries the flat baseline, the
    per-phase mean times (``_ms`` extras gate lower-is-better in
    tools/bench_compare.py), the per-hop wire bytes (``_bytes`` extras,
    same convention), the bf16 cross-hop run and its DCN byte reduction
    (asserted >= 1.8x in-bench), the flat-vs-two-level bit identity
    with compression off (exact integer payloads; the kill-switch
    identity bar PR 9 set), and the shared-memory transport cells
    (docs/performance.md#transport): the two-level run repeated with
    HVD_TPU_SHM=force vs the HVD_TPU_SHM=0 kill switch — asserted
    bit-identical and reported as shm_transport_speedup with both
    transports' local-hop phase times."""
    import subprocess
    import sys

    np_ = int(os.environ.get("BENCH_NP", "4"))
    nbytes = int(os.environ.get("BENCH_BYTES", str(8 * 1024 * 1024)))
    iters = int(os.environ.get("BENCH_ITERS", "16"))
    repo = os.path.dirname(os.path.abspath(__file__))
    code = f"""
import hashlib, json, os, time, numpy as np
rank = int(os.environ["HVD_TPU_RANK"])
if os.environ.get("BENCH_HIER") == "1":
    os.environ["HVD_TPU_LOCAL_SIZE"] = "2"
    os.environ["HVD_TPU_LOCAL_RANK"] = str(rank % 2)
    os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
import horovod_tpu as hvd
hvd.init()
n = {nbytes} // 4
# Integer-valued fp32: sums are exact, so flat and two-level results can
# bit-compare (association order cannot change bits).
x = (np.arange(n) % 251 + hvd.rank()).astype(np.float32)
out = hvd.allreduce(x, average=False, name="hier.steady")  # warm
snap0 = hvd.metrics_snapshot()
t0 = time.perf_counter()
for i in range({iters}):
    out = hvd.allreduce(x, average=False, name="hier.steady")
dt = time.perf_counter() - t0
snap1 = hvd.metrics_snapshot()
topo0, topo1 = snap0["topology"], snap1["topology"]

def phase_ms(name):
    h0 = snap0["histograms"].get(name, {{"sum": 0.0, "count": 0}})
    h1 = snap1["histograms"].get(name, {{"sum": 0.0, "count": 0}})
    cnt = h1["count"] - h0["count"]
    return 1e3 * (h1["sum"] - h0["sum"]) / cnt if cnt else 0.0

if hvd.rank() == 0:
    print("HIER_JSON " + json.dumps({{
        "ops_per_sec": {iters} / dt,
        "digest": hashlib.sha256(out.tobytes()).hexdigest(),
        "local_transport": topo1.get("local_transport", "tcp"),
        "local_bytes": topo1["bytes"]["local"] - topo0["bytes"]["local"],
        "cross_bytes": topo1["bytes"]["cross"] - topo0["bytes"]["cross"],
        "local_rs_ms": round(phase_ms("topology_local_rs_sec"), 3),
        "cross_ms": round(phase_ms("topology_cross_sec"), 3),
        "local_ag_ms": round(phase_ms("topology_local_ag_sec"), 3),
    }}), flush=True)
hvd.shutdown()
"""

    def run(hier: bool, mode: str, shm: str = "0") -> dict:
        env = dict(os.environ,
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   BENCH_HIER="1" if hier else "0",
                   HVD_TPU_COMPRESSION=mode,
                   HVD_TPU_SHM=shm)
        env.pop("HOROVOD_HIERARCHICAL_ALLREDUCE", None)
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
             "--", sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=600)
        assert out.returncode == 0, (hier, mode, out.stderr[-2000:])
        return next(json.loads(line[len("HIER_JSON "):])
                    for line in out.stdout.splitlines()
                    if line.startswith("HIER_JSON "))

    flat = run(False, "off")
    hier = run(True, "off")
    hier16 = run(True, "bf16")
    shm = run(True, "off", shm="force")
    # Kill-switch identity: flat and two-level agree BITWISE with
    # compression off (exact payloads).
    assert flat["digest"] == hier["digest"], (
        "flat vs two-level results diverged bitwise with compression off")
    # Transport identity: the shm rings carry the same bits the sockets
    # did (force, so a silent TCP demotion cannot fake the pass).
    assert shm["local_transport"] == "shm", shm
    assert hier["local_transport"] == "tcp", hier
    assert shm["digest"] == hier["digest"], (
        "shm vs TCP two-level results diverged bitwise with compression "
        "off")
    ratio16 = hier["cross_bytes"] / max(hier16["cross_bytes"], 1)
    floor = float(os.environ.get("BENCH_HIER_MIN_CROSS_RATIO", "1.8"))
    assert ratio16 >= floor, (
        f"bf16 cross hop moved only {ratio16:.2f}x fewer DCN bytes than "
        f"full width (want >= {floor:.1f}x): {hier16['cross_bytes']} vs "
        f"{hier['cross_bytes']}")
    speedup = hier["ops_per_sec"] / max(flat["ops_per_sec"], 1e-9)
    speed_floor = float(os.environ.get("BENCH_HIER_MIN_SPEEDUP", "0.9"))
    assert speedup >= speed_floor, (
        f"two-level ran {speedup:.2f}x the flat ring at "
        f"{nbytes >> 20} MiB (want >= {speed_floor:.2f}x)")
    print(json.dumps({
        "metric": f"hier_allreduce_ops_per_sec_np{np_}",
        "value": round(hier["ops_per_sec"], 2),
        "unit": "ops/sec",
        "vs_baseline": None,  # the reference published no such number
        "extra_metrics": {
            "flat_ops_per_sec": round(flat["ops_per_sec"], 2),
            "bf16_ops_per_sec": round(hier16["ops_per_sec"], 2),
            "two_level_speedup": round(speedup, 3),
            "local_wire_bytes": hier["local_bytes"],
            "cross_wire_bytes": hier["cross_bytes"],
            "cross_wire_bytes_bf16": hier16["cross_bytes"],
            "cross_compression_ratio": round(ratio16, 3),
            "local_rs_ms": hier["local_rs_ms"],
            "cross_ms": hier["cross_ms"],
            "local_ag_ms": hier["local_ag_ms"],
            "shm_ops_per_sec": round(shm["ops_per_sec"], 2),
            "shm_transport_speedup": round(
                shm["ops_per_sec"] / max(hier["ops_per_sec"], 1e-9), 3),
            "shm_local_rs_ms": shm["local_rs_ms"],
            "shm_local_ag_ms": shm["local_ag_ms"],
        },
    }))


def bench_negotiation_scale() -> None:
    """Simulated-scale control-plane bench (docs/performance.md
    #control-plane-scaling): hundreds of engine-plane ranks IN ONE
    PROCESS over loopback (the C++ simscale harness — every rank a full
    Engine with its own sockets and background thread), driving OP_NOOP
    negotiation cycles so the measured latency is pure control plane.

    Six measured cells: {small, large} ranks x {star baseline,
    tree+steady} plus the large tree cell rerun twice — once with the
    heartbeat detector disabled, once with the perf-introspection plane
    (link accounting + anomaly detector) disabled.  The headline is steady-state cycles/sec at the
    LARGE size; extras carry the per-cell p50s, the steady-vs-small
    flatness ratio (the acceptance bar: within 1.5x of the small size,
    where the star grows superlinearly), the steady-window control-frame
    delta (the zero-frames-per-cycle contract, asserted via the same
    counters metrics_snapshot()["control"] exposes), the heartbeat
    on-vs-off steady p50 inflation (asserted <
    BENCH_HB_MAX_OVERHEAD_PCT, default 5% — the detector must be
    unmeasurable in the steady state,
    docs/fault-tolerance.md#failure-detection), and rank 0's init
    clock-sync fan-in (asserted O(hosts) on the tree — the sub-
    coordinator relay, not the O(ranks) star probe).

    BENCH_SCALE_RANKS="16,256" overrides the sizes; BENCH_OPS /
    BENCH_WARM_CYCLES / BENCH_STEADY_CYCLES the per-cycle shape."""
    import ctypes
    import resource

    from horovod_tpu.common import _load_lib

    lib = _load_lib()
    sizes = [int(s) for s in os.environ.get(
        "BENCH_SCALE_RANKS", "16,256").split(",") if s]
    small, large = sizes[0], sizes[-1]
    ops = int(os.environ.get("BENCH_OPS", "2"))
    warm = int(os.environ.get("BENCH_WARM_CYCLES", "40"))
    steady = int(os.environ.get("BENCH_STEADY_CYCLES", "30"))
    threshold = 8
    # The harness opens ~5 fds per simulated rank (listener, ring pair,
    # control, transient rendezvous); lift the soft NOFILE limit so the
    # large cell fits.
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = max(soft, 8 * large + 512)
    if soft < want:
        resource.setrlimit(resource.RLIMIT_NOFILE,
                           (min(hard, want), hard))

    def local_size(n: int) -> int:
        # ~n/16 ranks per simulated host, floored at 2 so the tree has
        # real fan-in at the small size too.
        for cand in (max(2, n // 16), 4, 2):
            if n % cand == 0 and cand >= 2:
                return cand
        return 1

    def run(size: int, use_tree: bool, use_steady: bool, port: int,
            hb_ms: int = 100, introspection: bool = True) -> dict:
        # The simulated engines read the heartbeat / introspection knobs
        # from the real environment at Init (same contract as launched
        # ranks), so the on/off cells toggle them via os.environ —
        # putenv makes the change visible to the in-process C++ getenv.
        # introspection=False turns off the whole perf-introspection
        # plane: link accounting (HVD_TPU_LINK_STATS=0) and the anomaly
        # detector thread (HVD_TPU_ANOMALY_SIGMA=0).
        saved = {k: os.environ.get(k)
                 for k in ("HVD_TPU_HEARTBEAT_MS", "HVD_TPU_LINK_STATS",
                           "HVD_TPU_ANOMALY_SIGMA")}
        os.environ["HVD_TPU_HEARTBEAT_MS"] = str(hb_ms)
        if not introspection:
            os.environ["HVD_TPU_LINK_STATS"] = "0"
            os.environ["HVD_TPU_ANOMALY_SIGMA"] = "0"
        buf = ctypes.create_string_buffer(2048)
        try:
            for attempt in range(3):  # port collisions retry on a new base
                rc = lib.hvd_tpu_simscale_run(
                    size, local_size(size), ops, warm, steady,
                    threshold if use_steady else 0, int(use_tree),
                    port + attempt * (size + 16), 60.0, buf, 2048)
                rep = json.loads(buf.value.decode() or "{}")
                if rc == 0 and rep.get("ok"):
                    return rep
            raise RuntimeError(f"simscale run failed: {rep}")
        finally:
            for key, value in saved.items():
                if value is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = value

    base_port = 45000 + (os.getpid() % 400) * 16
    cells = {}
    for size in (small, large):
        cells[(size, "star")] = run(size, False, False, base_port)
        base_port += size + 64
        cells[(size, "tree")] = run(size, True, True, base_port)
        base_port += size + 64
    hb_off = run(large, True, True, base_port, hb_ms=0)
    base_port += large + 64
    intro_off = run(large, True, True, base_port, introspection=False)
    base_port += large + 64

    t_small, t_large = cells[(small, "tree")], cells[(large, "tree")]
    s_small, s_large = cells[(small, "star")], cells[(large, "star")]
    steady_p50 = t_large["steady_p50_us"]
    value = 1e6 / steady_p50 if steady_p50 > 0 else 0.0
    # Heartbeat overhead must be unmeasurable: the beat threads wake at
    # 10 Hz off the engine tick and never touch the steady-state replay
    # path, so steady p50 with the detector on stays within
    # BENCH_HB_MAX_OVERHEAD_PCT of the detector-off run.  The same 300µs
    # floor as the flatness ratio absorbs the co-located simulator's
    # thread-wake quantum; the frame counters prove each cell really ran
    # in its regime.
    assert t_large["hb_frames_sent"] > 0, t_large
    assert hb_off["hb_frames_sent"] == 0, hb_off
    hb_max_pct = float(os.environ.get("BENCH_HB_MAX_OVERHEAD_PCT", "5"))
    hb_inflation = (t_large["steady_p50_us"]
                    / max(hb_off["steady_p50_us"], 300.0))
    assert hb_inflation <= 1.0 + hb_max_pct / 100.0, (
        f"heartbeat detector inflated steady p50 at {large} ranks by "
        f"{100.0 * (hb_inflation - 1.0):.1f}% (want <= {hb_max_pct:g}%): "
        f"{hb_off['steady_p50_us']:.1f}us off -> "
        f"{t_large['steady_p50_us']:.1f}us on")
    # Perf-introspection overhead must be unmeasurable too: link
    # accounting is one short mutex hold per transport call and the
    # anomaly detector wakes off the tick, so steady p50 with the plane
    # on stays within BENCH_LINK_MAX_OVERHEAD_PCT (default 5%, the same
    # bar as the heartbeat detector) of the plane-off run.  link_sends
    # is process-cumulative across cells, so the off cell is proven by
    # ZERO GROWTH over the cell that ran just before it, and the on
    # cells by a nonzero total.
    assert t_large["link_sends"] > 0, t_large
    assert intro_off["link_sends"] == hb_off["link_sends"], (
        f"link accounting grew while HVD_TPU_LINK_STATS=0: "
        f"{hb_off['link_sends']} -> {intro_off['link_sends']}")
    link_max_pct = float(os.environ.get(
        "BENCH_LINK_MAX_OVERHEAD_PCT", "5"))
    link_inflation = (t_large["steady_p50_us"]
                      / max(intro_off["steady_p50_us"], 300.0))
    assert link_inflation <= 1.0 + link_max_pct / 100.0, (
        f"perf-introspection plane inflated steady p50 at {large} ranks "
        f"by {100.0 * (link_inflation - 1.0):.1f}% (want <= "
        f"{link_max_pct:g}%): {intro_off['steady_p50_us']:.1f}us off -> "
        f"{t_large['steady_p50_us']:.1f}us on")
    # Init clock-sync fan-in at rank 0 is O(hosts) on the tree: the
    # sub-coordinator relay probes only direct children (own-host ranks
    # + one sub-coordinator per other host), never the O(ranks) star.
    hosts_large = large // local_size(large)
    fanin = t_large["clock_fanin"]
    assert 0 < fanin <= hosts_large + local_size(large), (
        f"rank-0 clock-sync fan-in {fanin} at {large} ranks exceeds "
        f"O(hosts): want <= {hosts_large} hosts + {local_size(large)} "
        f"local ranks")
    assert s_large["clock_fanin"] == large - 1, s_large  # the star probe
    extras = {
        "ranks_small": small,
        "ranks_large": large,
        f"star_p50_us_{small}": s_small["steady_p50_us"],
        f"star_p50_us_{large}": s_large["steady_p50_us"],
        f"steady_p50_us_{small}": t_small["steady_p50_us"],
        f"steady_p50_us_{large}": t_large["steady_p50_us"],
        f"warm_tree_p50_us_{large}": t_large["warm_p50_us"],
        # The acceptance bar: steady-state cost flat in ranks, against
        # the star's growth in the same run.  The 300µs floor absorbs
        # the co-located simulator's thread-wake quantum (the real
        # signal is µs-scale local replay — docs/performance.md
        # #control-plane-scaling).  "inflation" keys gate
        # lower-is-better in tools/bench_compare.py.
        "steady_scale_inflation": (
            t_large["steady_p50_us"] / max(t_small["steady_p50_us"], 300.0)),
        "star_scale_inflation": (
            s_large["steady_p50_us"] / s_small["steady_p50_us"]
            if s_small["steady_p50_us"] > 0 else 0.0),
        "steady_entered": int(t_small["steady_entered"]
                              and t_large["steady_entered"]),
        # Control frames sent during the steady window (max over ranks):
        # the decentralized steady state's contract is ZERO.
        "steady_frames_delta": max(t_small["steady_frames_delta"],
                                   t_large["steady_frames_delta"]),
        f"coord_children_{large}": t_large["coord_children"],
        # "inflation" keys gate lower-is-better in tools/bench_compare.py.
        f"hb_off_steady_p50_us_{large}": hb_off["steady_p50_us"],
        "hb_overhead_inflation": round(hb_inflation, 4),
        f"hb_frames_sent_{large}": t_large["hb_frames_sent"],
        f"intro_off_steady_p50_us_{large}": intro_off["steady_p50_us"],
        "link_overhead_inflation": round(link_inflation, 4),
        f"link_sends_{large}": t_large["link_sends"],
        f"clock_fanin_tree_{large}": fanin,
        f"clock_fanin_star_{large}": s_large["clock_fanin"],
    }
    print(json.dumps({
        "metric": "negotiation_scale_steady_cycles_per_sec",
        "value": round(value, 1),
        "unit": "cycles/sec",
        "vs_baseline": round(value / (1e6 / s_large["steady_p50_us"]), 2)
        if s_large["steady_p50_us"] > 0 else 0.0,
        "extra_metrics": extras,
    }), flush=True)


def bench_serve_decode() -> None:
    """Serving-plane bench (docs/inference.md): a synthetic multi-tenant
    request stream against the continuous-batching engine over BENCH_NP
    ranks.  Headline is generated tokens/sec; extra_metrics carries p50/
    p99 time-to-first-token and per-token latency (lower-is-better: the
    ``_ms`` suffix tells tools/bench_compare.py to gate them in that
    direction), mean batch occupancy, and the steady-state negotiation-
    cache hit rate measured over the serve window only (init-time param
    broadcasts are legitimate misses) — asserted >= 0.9, the number that
    proves decode steps pay zero coordinator roundtrips."""
    import subprocess
    import sys

    np_ = int(os.environ.get("BENCH_NP", "2"))
    n_requests = int(os.environ.get("BENCH_REQUESTS", "24"))
    repo = os.path.dirname(os.path.abspath(__file__))
    code = f"""
import json, threading, time, numpy as np, horovod_tpu as hvd
from tools.metrics_dump import quantile
from horovod_tpu.serving.engine import (ModelSpec, ServingEngine,
                                        broadcast_params, init_params)
from horovod_tpu.serving.scheduler import Scheduler, ServeConfig
hvd.init()
spec = ModelSpec(vocab=211, d_model=64, n_layers=2, n_heads=2)
cfg = ServeConfig(max_batch=8, prefill_chunk=8, block_tokens=8,
                  num_blocks=192, max_blocks_per_seq=12)
params = broadcast_params(init_params(spec))
rank0 = hvd.rank() == 0
sch = Scheduler(cfg) if rank0 else None
engine = ServingEngine(spec, cfg, params, sch)
if not rank0:
    engine.run()
    hvd.shutdown()
    raise SystemExit(0)
loop = threading.Thread(target=engine.run, daemon=True)
loop.start()
base = hvd.metrics_snapshot()["cache"]["engine"]
rng = np.random.RandomState(0)
reqs = []
t0 = time.perf_counter()
# Mixed tenants/lengths arriving while earlier requests decode: the
# continuous-batching shape (joins and retirements at step boundaries).
for i in range({n_requests}):
    tenant = ("acme", "beta", "gamma")[i % 3]
    prompt = rng.randint(0, 211, int(rng.randint(4, 40))).tolist()
    reqs.append(sch.submit(tenant, prompt, int(rng.randint(8, 32))))
    time.sleep(0.002)
for r in reqs:
    assert r.event.wait(300), f"request {{r.id}} hung"
dt = time.perf_counter() - t0
engine.request_stop()
loop.join(60)
snap = hvd.metrics_snapshot()
cache = snap["cache"]["engine"]
hits = cache["hits"] - base["hits"]
misses = cache["misses"] - base["misses"]
hit_rate = hits / max(hits + misses, 1)
assert hit_rate >= 0.9, (
    f"steady-state negotiation cache hit rate {{hit_rate:.3f}} < 0.9 "
    f"({{hits}} hits / {{misses}} misses over the serve window)")
serving = snap["serving"]
hists = snap["histograms"]
tokens = sum(len(r.generated) for r in reqs)
print("SERVE_JSON " + json.dumps({{
    "tokens_per_sec": tokens / dt,
    "requests": len(reqs),
    "ttft_p50_ms": round((quantile(hists["serving_ttft_sec"], 0.5)
                          or 0.0) * 1e3, 2),
    "ttft_p99_ms": round((quantile(hists["serving_ttft_sec"], 0.99)
                          or 0.0) * 1e3, 2),
    "token_p50_ms": round((quantile(hists["serving_token_sec"], 0.5)
                           or 0.0) * 1e3, 2),
    "token_p99_ms": round((quantile(hists["serving_token_sec"], 0.99)
                           or 0.0) * 1e3, 2),
    "occupancy": round(serving["occupancy"], 4),
    "steps": serving["steps"],
    "cache_hit_rate": round(hit_rate, 4),
}}), flush=True)
hvd.shutdown()
"""
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    env.setdefault("HVD_TPU_METRICS", "1")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    stats = next(json.loads(line[len("SERVE_JSON "):])
                 for line in out.stdout.splitlines()
                 if line.startswith("SERVE_JSON "))
    print(json.dumps({
        "metric": f"serve_decode_tokens_per_sec_np{np_}",
        "value": round(stats.pop("tokens_per_sec"), 2),
        "unit": "tokens/sec",
        "vs_baseline": None,  # the reference serves nothing
        "extra_metrics": stats,
    }))


def bench_checkpoint() -> None:
    """State-plane bench (docs/fault-tolerance.md#state-plane): three
    questions, one record.  (1) Async snapshot overhead: steps/sec over
    BENCH_NP ranks, snapshots on vs off measured as interleaved windows
    of ONE job (two launches would compare different transient host
    load) — the overlap must keep overhead under
    BENCH_CKPT_MAX_OVERHEAD_PCT (default 5%).
    (2) Durable save wall time: sharded ``ckpt-<step>/rank-N.pkl`` vs the
    legacy rank-0 pickle for the same BENCH_BYTES state (``_ms`` extras
    gate lower-is-better in tools/bench_compare.py).  (3) Elastic resync:
    peer-copy restore vs PR-6 root broadcast after an injected crash,
    measured by a custom reshape driver (``_ms`` extras again).  Headline
    is the sharded save throughput in MB/s."""
    import subprocess
    import sys
    import tempfile

    np_ = int(os.environ.get("BENCH_NP", "2"))
    nbytes = int(os.environ.get("BENCH_BYTES", str(8 * 1024 * 1024)))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    repo = os.path.dirname(os.path.abspath(__file__))
    snap_code = f"""
import json, os, time, numpy as np, horovod_tpu as hvd
from horovod_tpu.jax.train import save_checkpoint
hvd.init()
n = {nbytes} // 4 // 4
state = hvd.ElasticState(
    weights=np.random.RandomState(0).rand(n).astype(np.float32),
    mu=np.zeros(n, np.float32), nu=np.zeros(n, np.float32),
    extra=np.zeros(n, np.float32), step=0)
plane = hvd.state.arm()
plane.exchange_peers()  # ring-neighbor mirroring without run_elastic
# The step's gradient allreduce moves the FULL state size — the real
# data-parallel proportion (gradient bytes == model bytes per step) the
# snapshot's O(model/size) capture must hide behind.  Snapshot cadence
# (BENCH_SNAP_EVERY, default 4) is the CheckFreq knob: on a CPU bench
# host the mirror's copy competes with the CPU-summed ring for CORES —
# not just for the step path — so per-step snapshots would measure
# resource contention, not fence overhead; recovery loss stays bounded
# at cadence steps (the plane retains the last two commits either way).
every = max(1, int(os.environ.get("BENCH_SNAP_EVERY", "4")))
g = np.ones({nbytes} // 4, np.float32)
snapping = False
def step():
    state.weights += hvd.allreduce(g, average=True,
                                   name="grad")[: state.weights.size]
    state.step += 1
    if snapping and state.step % every == 0:
        plane.snapshot(state)
step()  # warm: negotiate
# Snapshots-on vs snapshots-off measured as INTERLEAVED windows of one
# job (off, on, off, on, ...), best-of-3 each: two separate launches
# would compare different engine warmup and transient host load (the
# run-to-run spread exceeds the overhead being measured); alternating
# windows in one process pair cancels it.
best = {{False: 0.0, True: 0.0}}
for trial in range(6):
    snapping = trial % 2 == 1
    if snapping:
        plane.snapshot(state)  # warm the snapshot path before its window
        plane.wait()
    t0 = time.perf_counter()
    for _ in range({steps}):
        step()
    best[snapping] = max(best[snapping],
                         {steps} / (time.perf_counter() - t0))
    plane.wait()
# Durable-save timing rides the snapshot-on run (state already built).
tree = {{"weights": state.weights, "mu": state.mu, "nu": state.nu,
         "extra": state.extra}}
with tempfile_dir() as d:
    hvd.allreduce(np.ones(1, np.int32), average=False, name="save.align")
    t1 = time.perf_counter()
    save_checkpoint(os.path.join(d, "sharded"), 1, tree, sharded=True)
    sharded_sec = time.perf_counter() - t1
    legacy_sec = 0.0
    if hvd.rank() == 0:
        t2 = time.perf_counter()
        save_checkpoint(os.path.join(d, "legacy"), 1, tree, sharded=False)
        legacy_sec = time.perf_counter() - t2
    hvd.allreduce(np.ones(1, np.int32), average=False, name="save.done")
if hvd.rank() == 0:
    st = hvd.metrics_snapshot()["state"]
    print("SNAP_JSON " + json.dumps({{
        "on_steps_per_sec": best[True],
        "off_steps_per_sec": best[False],
        "overlap_ratio": st["overlap_ratio"],
        "snapshots": st["snapshots"],
        "sharded_save_sec": sharded_sec,
        "legacy_save_sec": legacy_sec,
    }}), flush=True)
"""
    # tempfile_dir: inlined helper so the rank script has no repo import
    # beyond horovod_tpu itself.
    snap_code = ("import contextlib, tempfile\n"
                 "@contextlib.contextmanager\n"
                 "def tempfile_dir():\n"
                 "    import shutil\n"
                 "    d = tempfile.mkdtemp()\n"
                 "    try:\n"
                 "        yield d\n"
                 "    finally:\n"
                 "        shutil.rmtree(d, ignore_errors=True)\n"
                 + snap_code)

    def run_snap() -> dict:
        env = dict(os.environ,
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
             "--", sys.executable, "-c", snap_code],
            capture_output=True, text=True, env=env, timeout=600)
        assert out.returncode == 0, out.stderr[-2000:]
        return next(json.loads(line[len("SNAP_JSON "):])
                    for line in out.stdout.splitlines()
                    if line.startswith("SNAP_JSON "))

    resync_code = f"""
import json, os, time, numpy as np, horovod_tpu as hvd
from horovod_tpu import common as _common
hvd.init()
lib = _common._load_lib()
n = {nbytes} // 4
state = hvd.ElasticState(weights=np.zeros(n, np.float32), step=0)
plane = hvd.state.arm() if os.environ.get("BENCH_PEER") == "1" else None
synced, resync_ms = -1, None
while True:
    try:
        epoch = int(lib.hvd_tpu_membership_epoch())
        if epoch != synced:
            lib.hvd_tpu_membership_ack()
            t0 = time.perf_counter()
            if plane is None or not plane.restore(state, epoch):
                state.sync(root=0, key=epoch)
            if epoch:
                resync_ms = (time.perf_counter() - t0) * 1e3
            synced = epoch
        while state.step < 12:
            s = state.step
            state.weights = state.weights + hvd.allreduce(
                np.ones(n, np.float32), average=True, name=f"g.{{s}}")
            state.step = s + 1
            if plane is not None:
                plane.snapshot(state)
        break
    except hvd.MembershipChangedError:
        deadline = time.monotonic() + 60.0
        while int(lib.hvd_tpu_membership_epoch()) == synced:
            assert time.monotonic() < deadline
            time.sleep(0.02)
if hvd.rank() == 0:
    print("RESYNC_JSON " + json.dumps({{
        "resync_ms": resync_ms,
        "peer_restores": hvd.metrics_snapshot()["state"]["peer_restores"],
    }}), flush=True)
"""

    def run_resync(peer: bool) -> dict:
        env = dict(os.environ,
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   BENCH_PEER="1" if peer else "0",
                   HVD_TPU_KILL_GRACE_SEC="3",
                   HVD_TPU_COLLECTIVE_TIMEOUT_SEC="30",
                   HVD_TPU_FAULT_SPEC="rank=1:crash@op=8")
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
             "--min-np", "1", "--", sys.executable, "-c", resync_code],
            capture_output=True, text=True, env=env, timeout=600)
        assert out.returncode == 0, (peer, out.stderr[-2000:])
        return next(json.loads(line[len("RESYNC_JSON "):])
                    for line in out.stdout.splitlines()
                    if line.startswith("RESYNC_JSON "))

    snap = run_snap()
    overhead_pct = 100.0 * (snap["off_steps_per_sec"]
                            / snap["on_steps_per_sec"] - 1.0)
    max_overhead = float(os.environ.get(
        "BENCH_CKPT_MAX_OVERHEAD_PCT", "5"))
    assert overhead_pct <= max_overhead, (
        f"async snapshots cost {overhead_pct:.1f}% of step throughput "
        f"(want <= {max_overhead:g}%): {snap['off_steps_per_sec']:.2f} "
        f"-> {snap['on_steps_per_sec']:.2f} steps/sec")
    peer = run_resync(True)
    root = run_resync(False)
    assert peer["peer_restores"] >= 1, peer
    mb = nbytes / 1e6
    print(json.dumps({
        "metric": f"checkpoint_sharded_save_mb_per_sec_np{np_}",
        "value": round(mb / max(snap["sharded_save_sec"], 1e-9), 2),
        "unit": "MB/s",
        "vs_baseline": None,  # the reference has no checkpoint story
        "extra_metrics": {
            "snap_on_steps_per_sec": round(snap["on_steps_per_sec"], 2),
            "snap_off_steps_per_sec": round(snap["off_steps_per_sec"], 2),
            "snapshot_overhead_pct": round(overhead_pct, 2),
            "snapshot_overlap_ratio": round(snap["overlap_ratio"], 4),
            "sharded_save_ms": round(snap["sharded_save_sec"] * 1e3, 2),
            "legacy_save_ms": round(snap["legacy_save_sec"] * 1e3, 2),
            "peer_restore_ms": round(peer["resync_ms"], 2),
            "root_broadcast_restore_ms": round(root["resync_ms"], 2),
        },
    }))


def bench_pipeline() -> None:
    """Pipeline-parallel 1F1B training throughput over the engine's p2p
    plane (docs/pipeline.md): a BENCH_STAGES x DP grid (world BENCH_NP)
    trains the stage-partitioned transformer LM with BENCH_MICROBATCHES
    micro-batches per step, activations crossing stage boundaries as
    send/recv buckets and gradients DP-averaging inside each stage group.

    Headline is end-to-end tokens/sec across the whole grid.  Extras
    carry the schedule's bubble fraction (config-determined:
    (S-1)/(S-1+M*V), informational), the per-stage p2p wire bytes for
    the timed window (``_bytes`` extras gate lower-is-better in
    tools/bench_compare.py), and the steady-state response-cache hit
    rate measured AFTER the warmup steps (the >= 0.9 acceptance bar of
    docs/pipeline.md#steady-state; a rate extra gates higher-is-better).
    BENCH_CHUNKS > 1 switches to the interleaved schedule."""
    import subprocess
    import sys

    np_ = int(os.environ.get("BENCH_NP", "4"))
    stages = int(os.environ.get("BENCH_STAGES", "2"))
    chunks = int(os.environ.get("BENCH_CHUNKS", "1"))
    micro = int(os.environ.get("BENCH_MICROBATCHES", "4"))
    steps = int(os.environ.get("BENCH_STEPS", "6"))
    warmup = int(os.environ.get("BENCH_WARMUP", "1"))
    seq = int(os.environ.get("BENCH_SEQ", "32"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    d_model = int(os.environ.get("BENCH_D_MODEL", "64"))
    n_layers = int(os.environ.get("BENCH_LAYERS", "4"))
    n_heads = int(os.environ.get("BENCH_HEADS", "4"))
    vocab = int(os.environ.get("BENCH_VOCAB", "256"))
    repo = os.path.dirname(os.path.abspath(__file__))
    code = f"""
import json, time, numpy as np
import jax, jax.numpy as jnp, optax
import horovod_tpu as hvd
from horovod_tpu.jax.train import run_pipeline
from horovod_tpu.models import TransformerLM, next_token_loss
from horovod_tpu.parallel import (PipelineGrid, partition_params,
                                  partition_transformer)
hvd.init()
S, V, M, B, SEQ = {stages}, {chunks}, {micro}, {batch}, {seq}
grid = PipelineGrid(S, hvd.size(), hvd.rank())
full = TransformerLM(
    vocab_size={vocab}, d_model={d_model}, n_layers={n_layers},
    n_heads={n_heads}, dtype=jnp.float32, use_flash=False).init(
    jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32))["params"]
modules = partition_transformer(
    {vocab}, {d_model}, {n_layers}, {n_heads}, n_stages=S, n_chunks=V,
    dtype=jnp.float32, use_flash=False)[grid.stage]
params = partition_params(full, {n_layers}, S, n_chunks=V)[grid.stage]
tokens = np.random.RandomState(grid.dp_index).randint(
    0, {vocab}, (B, SEQ + 1)).astype(np.int32)
inputs, targets = tokens[:, :-1], tokens[:, 1:]
tx = optax.adamw(1e-3)
batches = [(inputs, targets)]
params, _, _ = run_pipeline(modules, params, tx, batches * {warmup},
                            n_stages=S, n_microbatches=M,
                            loss_fn=next_token_loss)
snap0 = hvd.metrics_snapshot()
t0 = time.perf_counter()
params, _, losses = run_pipeline(modules, params, tx, batches * {steps},
                                 n_stages=S, n_microbatches=M,
                                 loss_fn=next_token_loss)
dt = time.perf_counter() - t0
snap1 = hvd.metrics_snapshot()
p0, p1 = snap0["p2p"], snap1["p2p"]
print("PIPE_RANK_JSON " + json.dumps({{
    "rank": hvd.rank(), "stage": grid.stage,
    "p2p_bytes_out": p1["bytes"]["out"] - p0["bytes"]["out"],
    "p2p_bytes_in": p1["bytes"]["in"] - p0["bytes"]["in"],
    "sends": p1["sends"] - p0["sends"],
    "recvs": p1["recvs"] - p0["recvs"]}}), flush=True)
if hvd.rank() == 0:
    c0 = snap0["cache"]["engine"]
    c1 = snap1["cache"]["engine"]
    dh = c1["hits"] - c0["hits"]
    dm = c1["misses"] - c0["misses"]
    print("PIPE_JSON " + json.dumps({{
        "tokens_per_sec": B * grid.dp * SEQ * {steps} / dt,
        "steady_cache_hit_rate": round(dh / max(dh + dm, 1), 4),
        "steady_cache_hits": dh, "steady_cache_misses": dm}}), flush=True)
hvd.shutdown()
"""
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.setdefault("HVD_TPU_METRICS", "1")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]

    def _scan(marker):
        # Rank stdout merges without line discipline: two ranks' prints
        # can land on one line, so find every marker and raw_decode from
        # it rather than trusting startswith + whole-line json.loads.
        dec = json.JSONDecoder()
        for line in out.stdout.splitlines():
            start = 0
            while True:
                idx = line.find(marker, start)
                if idx < 0:
                    break
                obj, start = dec.raw_decode(line, idx + len(marker))
                yield obj

    head = next(_scan("PIPE_JSON "))
    from horovod_tpu.parallel import bubble_fraction
    extras = {
        "bubble_fraction": round(bubble_fraction(stages, micro, chunks), 4),
        "steady_cache_hit_rate": head["steady_cache_hit_rate"],
        "steady_cache_hits": head["steady_cache_hits"],
        "steady_cache_misses": head["steady_cache_misses"],
    }
    # Per-stage wire volume for the timed window: sum the stage's DP
    # ranks so the extra is stable under BENCH_NP changes at fixed S.
    per_stage = {}
    for r in _scan("PIPE_RANK_JSON "):
        agg = per_stage.setdefault(r["stage"], {"out": 0, "in": 0})
        agg["out"] += r["p2p_bytes_out"]
        agg["in"] += r["p2p_bytes_in"]
    for stage, agg in sorted(per_stage.items()):
        extras[f"stage{stage}_p2p_bytes_out"] = agg["out"]
        extras[f"stage{stage}_p2p_bytes_in"] = agg["in"]
    print(json.dumps({
        "metric": (f"pipeline_train_tokens_per_sec_s{stages}"
                   f"x{np_ // stages}dp"),
        "value": round(head["tokens_per_sec"], 2),
        "unit": "tokens/sec",
        "vs_baseline": None,  # the reference has no pipeline benchmark
        "extra_metrics": extras,
    }))


def main() -> None:
    import jax

    # BENCH_PLATFORM=cpu forces the CPU backend even where a site hook
    # pre-registers a TPU platform through jax.config (test environments).
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu import models

    model_name = os.environ.get("BENCH_MODEL", "resnet101")
    if model_name == "transformer":
        return bench_transformer()
    if model_name == "allreduce":
        return bench_allreduce()
    if model_name == "small_allreduce":
        return bench_small_allreduce()
    if model_name == "big_allreduce":
        return bench_big_allreduce()
    if model_name == "hier_allreduce":
        return bench_hier_allreduce()
    if model_name == "negotiation_scale":
        return bench_negotiation_scale()
    if model_name == "serve_decode":
        return bench_serve_decode()
    if model_name == "checkpoint":
        return bench_checkpoint()
    if model_name == "pipeline":
        return bench_pipeline()
    if model_name == "scaling":
        return bench_scaling()
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    side = int(os.environ.get("BENCH_IMAGE", "224"))

    kwargs = {}
    if model_name == "mnist":
        model = models.MnistCNN()
        side, classes = 28, 10
        shape = (batch, side, side, 1)
    else:
        cls = {"resnet50": models.ResNet50, "resnet101": models.ResNet101,
               "resnet18": models.ResNet18, "vgg16": models.VGG16,
               "inception_v3": models.InceptionV3}[model_name]
        if model_name.startswith("resnet"):
            # Step-level fused BN running-stats EMA (models/norm.py): same
            # math as per-layer flax BN, ~1.4 ms/step less tiny-op
            # overhead; the train step applies models.ema_batch_stats.
            kwargs["fused_ema"] = True
        model = cls(num_classes=1000, dtype=jnp.bfloat16, **kwargs)
        if model_name == "inception_v3" and "BENCH_IMAGE" not in os.environ:
            side = 299
        classes = 1000
        shape = (batch, side, side, 3)

    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(np.random.RandomState(0).rand(*shape), jnp.float32)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, classes, batch),
                         jnp.int32)
    variables = model.init(rng, images, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    # Tiny-leaf packing (models/packing.py): the ~420 1-D tensors of a
    # BN model's train state (scale/bias/mean/var + momentum mirrors)
    # each pay a ~40 us memory-space-assignment copy per step — 11% of
    # the r3 ResNet-101 step.  Carrying them as one flat vector removes
    # all but two of those buffers; numerics pinned float32-tight by
    # tests/test_models.py::test_packed_train_step_bit_identical.
    packed = os.environ.get("BENCH_PACKED", "1") != "0"
    if packed:
        from horovod_tpu.models.packing import TreePacker
        p_packer = TreePacker(params)
        params = p_packer.pack(params)
        if has_bn := bool(batch_stats):
            s_packer = TreePacker(batch_stats)
            batch_stats = s_packer.pack(batch_stats)
    else:
        has_bn = bool(batch_stats)
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    dropout_rng = jax.random.PRNGKey(2)

    def loss_fn(params, batch_stats, images, labels):
        if packed:
            params = p_packer.unpack(params)
            if has_bn:
                batch_stats = s_packer.unpack(batch_stats)
        variables = {"params": params}
        # Unused rngs are fine in flax; models mixing BN and dropout
        # (inception_v3) need both the rng and the mutable stats.
        kwargs = {"rngs": {"dropout": dropout_rng}}
        if has_bn:
            variables["batch_stats"] = batch_stats
            kwargs["mutable"] = ["batch_stats"]
        out = model.apply(variables, images, train=True, **kwargs)
        logits, new_stats = out if has_bn else (out, batch_stats)
        new_stats = new_stats["batch_stats"] if has_bn else new_stats
        if packed and has_bn:
            new_stats = s_packer.pack(new_stats)  # one concatenate
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, new_stats

    fused_ema = bool(kwargs.get("fused_ema"))

    def one_step(params, batch_stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        if fused_ema and has_bn:
            new_stats = models.ema_batch_stats(batch_stats, new_stats, 0.9)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    # BENCH_UNROLL=K dispatches K optimizer steps per executable (python
    # -level unroll, NOT lax.scan — the scan body loses ~2 ms/step of
    # memory-space-assignment quality, r3 tuning log): the ~2.7 ms
    # per-execute tunnel overhead amortizes K-fold while the per-step HLO
    # stays identical.  Default 8 for the resnet101 headline (measured
    # r5 over the full 240-step window: 1717/1723 -> 1843/1839 img/s,
    # +7%; short windows under-report the gain — see docs/benchmarks.md.
    # Compile time grows ~K-fold, so other image models keep 1; the
    # transformer bench has its own default of 4, and an explicit
    # BENCH_UNROLL overrides BOTH (the extras sweep inherits it).
    # Donating params/stats/opt_state lets XLA update
    # in place instead of allocating fresh HBM buffers every step (~1.5%
    # on resnet101).
    unroll = max(1, int(os.environ.get(
        "BENCH_UNROLL", "8" if model_name == "resnet101" else "1")))

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, images, labels):
        for _ in range(unroll):
            params, batch_stats, opt_state, loss = one_step(
                params, batch_stats, opt_state, images, labels)
        return params, batch_stats, opt_state, loss

    for _ in range(warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    # Force completion by fetching a value: on remote-tunneled backends
    # block_until_ready can return before the computation has run.
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    # The final loss depends on every step's params, so one scalar fetch
    # drains the whole chain.
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), final_loss

    value = batch * steps * unroll / dt
    # The reference published an absolute throughput only for ResNet-101
    # (1656.82 img/s on 16 GPUs); other models have no comparable number.
    vs = (round(value / REFERENCE_IMG_PER_SEC_PER_DEVICE, 3)
          if model_name == "resnet101" else None)
    record = {
        "metric": f"{model_name}_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": vs,
    }
    # Print the headline NOW, before any extra runs: round 4 lost its whole
    # recorded result because an extra's compile failure bloated the final
    # (only) JSON line past the driver's capture window.  The headline must
    # be on stdout before anything else can go wrong.
    print(json.dumps(record), flush=True)
    if model_name == "resnet101" and os.environ.get("BENCH_EXTRA", "1") != "0":
        # Fold the LM and long-context headline numbers into a second,
        # enriched JSON line so the driver's default invocation records
        # them too (VERDICT r2 #8: these were builder-attested only).
        # Failures of the extras must not cost the headline metric — and
        # error strings are clipped to one short line so the enriched
        # record can never outgrow the driver's output tail (the r4
        # failure mode: a 20 KB Mosaic error inside the JSON).
        extras = {}
        # Round records track which wire-compression mode the run was
        # configured with (a config row, not a measurement: the
        # single-chip transformer sweep moves no collective bytes).
        extras["wire_compression"] = os.environ.get(
            "HVD_TPU_COMPRESSION", "off")
        # seq:batch pairs, token-constant (16k tokens/step — the
        # long-context protocol of docs/benchmarks.md); the full
        # documented sweep so each round's driver record carries it.
        cfgs = os.environ.get("BENCH_EXTRA_CONFIGS",
                              "1024:16,4096:4,8192:2,16384:1")
        for cfg in cfgs.split(","):
            try:  # a malformed config must not cost the headline metric
                s, b = (int(v) for v in cfg.split(":"))
            except ValueError:
                s = None
                extras[f"bad_config:{cfg.strip()}"] = "error: want seq:batch"
            if s is not None:
                key = ("transformer_train_tokens_per_sec_per_chip"
                       if s == 1024 else
                       f"transformer_seq{s}_tokens_per_sec_per_chip")
                try:
                    if os.environ.get("BENCH_EXTRA_INJECT_FAIL"):
                        # Test hook: the headline-survives-a-failing-extra
                        # property is load-bearing (see r4 post-mortem
                        # above) and must stay verifiable end-to-end.
                        raise RuntimeError(
                            "injected failure (BENCH_EXTRA_INJECT_FAIL)")
                    # Full default step count: steps cost ~1s while
                    # compile dominates the extras' runtime, and short
                    # windows under-report by several percent.
                    extras[key] = round(
                        bench_transformer(seq=s, batch=b, report=False), 2)
                except Exception as exc:  # record, don't fail the headline
                    first = (str(exc).splitlines()[0] if str(exc)
                             else repr(exc))
                    extras[key] = f"error: {first[:160]}"
            # Cumulative re-print after EVERY config (incl. malformed):
            # if the driver kills the process mid-sweep, the last
            # parseable line still carries the headline plus every extra
            # completed so far.
            record["extra_metrics"] = dict(extras)
            print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
