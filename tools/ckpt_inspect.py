#!/usr/bin/env python
"""Inspect checkpoint directories: manifests, shards, torn-state detection.

Reads BOTH checkpoint formats ``horovod_tpu.jax.train.save_checkpoint``
produces (docs/fault-tolerance.md#state-plane):

* legacy — one atomic ``ckpt-<step>.pkl`` pickle;
* sharded — ``ckpt-<step>/rank-N.pkl`` per rank plus a rank-0
  ``manifest.json`` committed after the shard barrier.

For every checkpoint under a directory it prints the step, format, total
bytes, and (sharded) the per-shard files with their recorded step/size
and owned leaf names from the manifest.  Torn or partial checkpoints are
DETECTED, not hidden: a sharded directory without a committed manifest
(the writer died before the commit point), a manifest whose shard file
is missing, and a shard whose recorded step/size disagrees with the
manifest all print as ``TORN`` with the reason, and the tool exits 1 —
so a CI step or an operator can gate on checkpoint-set health:

    python tools/ckpt_inspect.py /ckpts            # whole directory
    python tools/ckpt_inspect.py /ckpts/ckpt-00000040   # one checkpoint
    python tools/ckpt_inspect.py --leaves /ckpts   # per-leaf detail

State-plane snapshot spools (``snap-rank*.pkl`` under
``HVD_TPU_STATE_DIR`` / ``hvdrun --state-dir``) are reported too: which
step each rank last snapshotted — the "how much would a death here
cost?" postmortem question.
"""

from __future__ import annotations

import os
import pickle
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.metrics_dump import _fmt_bytes  # noqa: E402  (shared formatter)


def inspect_legacy(path: str, lines: list) -> bool:
    """Append the report for one legacy pickle; True when healthy."""
    try:
        with open(path, "rb") as f:
            payload = pickle.load(f)
        step = int(payload["step"])
    except Exception as exc:
        lines.append(f"{os.path.basename(path)}  TORN legacy pickle: "
                     f"{type(exc).__name__}: {exc}")
        return False
    lines.append(f"{os.path.basename(path)}  legacy  step {step}  "
                 f"{_fmt_bytes(os.path.getsize(path))}")
    return True


def inspect_sharded(path: str, lines: list, leaves: bool = False) -> bool:
    """Append the report for one sharded directory; True when healthy."""
    from horovod_tpu.state import checkpoint as ckpt

    name = os.path.basename(path.rstrip(os.sep))
    try:
        manifest = ckpt.read_manifest(path)
    except ValueError as exc:
        present = sorted(n for n in os.listdir(path)
                         if n.startswith("rank-"))
        lines.append(f"{name}  TORN: {exc}")
        if present:
            lines.append(f"  shards present anyway: {', '.join(present)}")
        return False
    size, step = manifest["size"], manifest["step"]
    total = 0
    healthy = True
    shard_lines = []
    for entry in manifest["shards"]:
        spath = os.path.join(path, entry["file"])
        try:
            doc = ckpt._read_shard(path, manifest, entry["rank"])
        except ValueError as exc:
            shard_lines.append(f"  {entry['file']}: TORN: {exc}")
            healthy = False
            continue
        nbytes = os.path.getsize(spath)
        total += nbytes
        owned = [m for m in manifest["leaves"]
                 if m["shard"] == entry["rank"] and not m.get("object")]
        shard_lines.append(
            f"  {entry['file']}: step {doc['step']} size {doc['size']}, "
            f"{len(owned)} array leaf(s) "
            f"(+{len(doc.get('objects', {}))} replicated object(s)), "
            f"{_fmt_bytes(nbytes)}")
        if leaves:
            for m in owned:
                shard_lines.append(
                    f"    [{m['index']:>4}] {m['name']}  "
                    f"{tuple(m['shape'])} {m['dtype']} "
                    f"{_fmt_bytes(m['nbytes'])}")
    state = "" if healthy else "  TORN (see shards)"
    lines.append(f"{name}  sharded  step {step}  {size} shard(s)  "
                 f"{manifest['leaf_count']} leaf(s)  "
                 f"{_fmt_bytes(total)}{state}")
    lines.extend(shard_lines)
    return healthy


def inspect_spool(path: str, names: list, lines: list) -> bool:
    """Report ``snap-rank*.pkl`` state-plane spill files (the
    ``HVD_TPU_STATE_DIR`` artifact): which step each rank last
    snapshotted — the postmortem question "how much work would a death
    here cost?".  True when every spool file is readable."""
    healthy = True
    spools = sorted(n for n in names
                    if n.startswith("snap-rank") and n.endswith(".pkl"))
    if spools:
        lines.append("state-plane snapshot spool:")
    for nm in spools:
        full = os.path.join(path, nm)
        try:
            with open(full, "rb") as f:
                doc = pickle.load(f)
            lines.append(
                f"  {nm}: step {doc['step']} (rank {doc['rank']} of "
                f"{doc['size']}), {len(doc['leaves'])} leaf(s), "
                f"{_fmt_bytes(os.path.getsize(full))}")
        except Exception as exc:
            lines.append(f"  {nm}: TORN spool file "
                         f"({type(exc).__name__}: {exc})")
            healthy = False
    return healthy


def inspect(path: str, leaves: bool = False) -> int:
    """Print the report for a checkpoint directory (or one checkpoint);
    returns the exit code (1 when anything is torn)."""
    from horovod_tpu.state import checkpoint as ckpt

    lines: list = []
    healthy = True
    base = os.path.basename(path.rstrip(os.sep))
    if os.path.isdir(path) and base.startswith("ckpt-"):
        healthy = inspect_sharded(path, lines, leaves=leaves)
    elif os.path.isfile(path):
        healthy = inspect_legacy(path, lines)
    else:
        entries = ckpt.scan_checkpoints(path)
        seen = {os.path.basename(p) for _, p, _ in entries}
        for _, cpath, kind in entries:
            ok = (inspect_sharded(cpath, lines, leaves=leaves)
                  if kind == "sharded" else inspect_legacy(cpath, lines))
            healthy = healthy and ok
        # scan_checkpoints hides torn sharded directories by design (no
        # committed manifest); an INSPECTOR must surface them instead.
        try:
            names = sorted(os.listdir(path))
        except OSError as exc:
            print(f"ckpt_inspect: {exc}", file=sys.stderr)
            return 2
        for nm in names:
            full = os.path.join(path, nm)
            if (nm.startswith("ckpt-") and os.path.isdir(full)
                    and nm not in seen):
                healthy = inspect_sharded(full, lines, leaves=leaves) \
                    and healthy
        healthy = inspect_spool(path, names, lines) and healthy
        if not lines:
            lines.append("(no checkpoints found)")
    for line in lines:
        print(line)
    if not healthy:
        print("ckpt_inspect: TORN/partial checkpoint(s) detected",
              file=sys.stderr)
    return 0 if healthy else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    leaves = "--leaves" in argv
    if leaves:
        argv.remove("--leaves")
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 2
    return inspect(argv[0], leaves=leaves)


if __name__ == "__main__":
    sys.exit(main())
