#!/usr/bin/env bash
# One-shot local CI gate (docs/contributing.md#running-the-gate): the
# same three checks a PR must pass, in the order that fails fastest —
#
#   1. hvdlint   — repo-contract static checks (metrics/env/c_api/wire
#                  coverage, a few seconds)
#   2. hvdmodel  — control-plane protocol model checker, --quick tier
#   3. tier-1    — the full not-slow pytest suite (~2-5 min; the same
#                  command ROADMAP.md pins, minus the log scraping)
#
# Run it from the repo root before pushing:
#
#   bash tools/ci.sh            # everything
#   bash tools/ci.sh --fast     # hvdlint + hvdmodel only (skip pytest)
#
# Exits non-zero on the first failing stage.
set -o pipefail

cd "$(dirname "$0")/.." || exit 1

fast=0
[ "$1" = "--fast" ] && fast=1

echo "== ci: hvdlint =="
python -m tools.hvdlint || exit 1

echo "== ci: hvdmodel --quick =="
python -m tools.hvdmodel --quick || exit 1

if [ "$fast" = "1" ]; then
    echo "== ci: OK (fast mode — tier-1 pytest skipped) =="
    exit 0
fi

echo "== ci: tier-1 pytest =="
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly || exit 1

echo "== ci: OK =="
