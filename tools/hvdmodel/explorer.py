"""Bounded exhaustive breadth-first exploration of the protocol model.

BFS guarantees the first counterexample found for each violation class
is a *shortest* failing interleaving, which keeps the rendered traces
readable.  States are hashed structurally (they are plain tuples);
parent pointers reconstruct traces on demand.
"""

from collections import deque

from . import invariants, model


class Result:
    def __init__(self, cfg):
        self.cfg = cfg
        self.states = 0
        self.transitions = 0
        self.terminals = 0
        self.truncated = False
        self.coverage = set()
        self.xfails = {}          # tag -> count
        self.violations = []      # (code, detail, trace) shortest-first

    @property
    def ok(self):
        return not self.violations


def _trace(parents, key):
    steps = []
    while key is not None:
        key, label, line = parents[key]
        if label is not None:
            steps.append((label, line))
    steps.reverse()
    return steps


def explore(cfg, max_states=500000):
    """Exhaustively explore ``cfg`` up to ``max_states`` expansions."""
    res = Result(cfg)
    init = model.initial_state(cfg)
    parents = {init: (None, None, None)}
    seen_violation = set()
    frontier = deque([init])
    while frontier:
        if res.states >= max_states:
            res.truncated = True
            break
        st = frontier.popleft()
        res.states += 1
        for code, detail in invariants.check_state(cfg, st):
            if code not in seen_violation:
                seen_violation.add(code)
                res.violations.append((code, detail,
                                       _trace(parents, st)))
        succ = model.successors(cfg, st)
        if not succ:
            res.terminals += 1
            ok, xfail, detail = invariants.classify_terminal(cfg, st)
            if xfail:
                res.xfails[xfail] = res.xfails.get(xfail, 0) + 1
            if not ok and "deadlock" not in seen_violation:
                seen_violation.add("deadlock")
                res.violations.append(("deadlock", detail,
                                       _trace(parents, st)))
            continue
        for label, line, nst, events in succ:
            res.transitions += 1
            res.coverage |= events
            if nst not in parents:
                parents[nst] = (st, label, line)
                frontier.append(nst)
    return res
