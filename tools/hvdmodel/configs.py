"""Bounded configurations for the explorer.

QUICK runs in tier-1 (<60 s, >=50k states) and must cover steady
enter/exit, reshape shrink+grow, and crash/freeze faults.  DEEP widens
to three hosts and a two-fault budget (crash at every reachable state)
and runs in the slow tier.  Elastic configs use the star topology — the
engine forces ``coord_tree=false`` under elastic (engine.cc Init).
"""

from .model import BUGS, Config


def quick():
    return [
        # Coordinator tree, 2 hosts x 2 ranks, non-elastic: steady
        # enter/exit through the sub-coordinator relay, EOF cascade,
        # frozen-rank timeout, pattern miss on a new tensor.
        Config("quick-tree", hosts=((0, 1), (2, 3)),
               threshold=2, ticks=4, fault_budget=1,
               faults=("crash:1", "crash:3", "freeze:3", "newt")),
        # Elastic star + one standby: shrink, grow, steady x elastic
        # revocation, stale-epoch machinery, undersized abort.
        Config("quick-elastic", hosts=((0,), (1,), (2,), (3,)),
               elastic=True, min_size=2, standby=(3,),
               threshold=2, ticks=4, fault_budget=1,
               faults=("crash:1", "crash:2", "freeze:2", "join",
                       "newt")),
        # Same protocol with the data-plane group timeout disabled: a
        # crash mid-steady must be resolved by the revocation broadcast
        # ALONE (MaybeRevokeSteadyForReshape) — the control plane may
        # not lean on the backstop for liveness.
        Config("quick-revoke-only", hosts=((0,), (1,), (2,)),
               elastic=True, min_size=1, threshold=1, ticks=3,
               fault_budget=1, faults=("crash:1", "crash:2"),
               group_timeout=False),
        # HVD_TPU_HEARTBEAT_MS=0: with the detector off a frozen rank is
        # only ever caught by the exchange-silence timeout — the legacy
        # ST_TIMEOUT contract must survive the ISSUE 17 detector landing.
        Config("quick-hb-off", hosts=((0,), (1,)),
               threshold=2, ticks=3, fault_budget=1,
               faults=("freeze:1",), heartbeat=False),
        # Point-to-point plane (docs/pipeline.md): a cross-host pair
        # announced through the coordinator tree — announce/match/execute
        # on the healthy path, and a crash/freeze of the receiver mid-
        # negotiation must end in the existing typed aborts (the blocked
        # sender, R_P2P, is released by the abort broadcast, never
        # stranded).  Steady is disabled (threshold=0) to bound the
        # product space — the steady x p2p interplay is covered by the
        # engine's tier-1 replay tests, not the model.
        Config("quick-p2p", hosts=((0, 1), (2, 3)),
               threshold=0, ticks=3, fault_budget=1,
               faults=("crash:2", "freeze:2"), p2p=(1, 2), p2p_tick=1),
        # Paired-readiness liveness: the recv is NEVER posted (the peer
        # stays alive and beating, invisible to EOF and heartbeat), so
        # the only legal outcome is the collective-timeout sweep firing
        # ST_TIMEOUT — act_p2p_timeout — on every rank.
        Config("quick-p2p-lost", hosts=((0,), (1,), (2,)),
               threshold=0, ticks=3, fault_budget=0,
               p2p=(1, 2), p2p_tick=1, p2p_lost_recv=True),
    ]


def deep():
    return [
        Config("deep-tree", hosts=((0, 1), (2, 3), (4, 5)),
               threshold=2, ticks=5, fault_budget=2,
               faults=("crash:1", "crash:3", "crash:5", "freeze:1",
                       "freeze:5", "newt")),
        Config("deep-elastic", hosts=((0,), (1,), (2,), (3,), (4,)),
               elastic=True, min_size=1, standby=(4,),
               threshold=2, ticks=5, fault_budget=2,
               faults=("crash:1", "crash:2", "crash:3", "freeze:3",
                       "join", "newt")),
    ]


def seeded(bug):
    """A small elastic config with one engine defense disabled; the
    explorer must find a violation for every seeded bug.

    ``skip-revoke`` runs with the group-timeout backstop off: with the
    timeout on, survivors eventually exit steady on their own and the
    coordinator's AllSteadyExited hold keeps the reshape safe — the
    revocation's whole job is that the control plane does not DEPEND on
    the data-plane timeout, so that is the environment in which its
    removal must (and does) deadlock.

    ``drop-heartbeat-revoke`` injects a FREEZE instead of a crash and
    severs the detector's escalation path (monitor flag -> hb_report ->
    MarkRankDead): with the detector nominally on, the exchange-silence
    timeout defers to it, so the frozen rank is never evicted and the
    survivors stall forever — the missed-eviction trace the detector
    exists to prevent (ISSUE 17).

    ``p2p-unmatched-send`` severs the paired-readiness backstop
    (act_p2p_timeout, i.e. CheckCollectiveTimeout skipping p2p entries):
    with the recv never posted, the announced send strands its rank in
    R_P2P, the coordinator's shutdown gate holds forever, and the whole
    job silently stalls — the shortest trace is send-announce, tick
    close without a counterpart, everyone else finishing, stall."""
    assert bug in BUGS, bug
    if bug == "p2p-unmatched-send":
        return Config("seeded-%s" % bug, hosts=((0,), (1,), (2,)),
                      threshold=0, ticks=3, fault_budget=0, bug=bug,
                      p2p=(1, 2), p2p_tick=1, p2p_lost_recv=True)
    fault = ("freeze:2" if bug == "drop-heartbeat-revoke" else "crash:2")
    return Config("seeded-%s" % bug, hosts=((0,), (1,), (2,)),
                  elastic=True, min_size=1, threshold=1, ticks=4,
                  fault_budget=1, faults=(fault,), bug=bug,
                  group_timeout=(bug != "skip-revoke"
                                 and bug != "drop-heartbeat-revoke"))
