"""Invariants checked over every reachable state / terminal state.

 1. no-deadlock: a state with no enabled action must be a *completed*
    terminal (classified below), never a silent stall;
 2. agreement: at quiesced boundaries (no frames in flight, no partial
    gatherings, nobody blocked on a response) every live rank's
    membership epoch equals the coordinator's, and any rank still in
    steady mode holds a pattern negotiated at the current epoch;
 3. fault resolution: every injected fault ends in a typed abort or a
    completed reshape + normal completion — which one is dictated by
    the fault kind and the elastic configuration (strict per-fault
    rules for single-fault runs);
 4. no stale-epoch frame is ever accepted by the coordinator.

The former ``xfail_freeze_eviction`` limitation is GONE (ISSUE 17): the
data-plane heartbeat detector (``act_hb_detect``) distinguishes a frozen
peer from a slow one, so a freeze under an elastic config with enough
survivors must now end in evict-and-reshape + completion — anything else
(including the old ST_TIMEOUT abort) is a violation.  Heartbeat-off
configs (HVD_TPU_HEARTBEAT_MS=0) keep the legacy timeout contract.
"""

from .model import (R_ABORT, R_CRASH, R_DONE, R_FROZEN, R_P2P, R_RUN,
                    R_STANDBY, R_STEADY, R_STUCK, R_WAIT, STATUS)

TYPED = {STATUS[k] for k in
         ("ST_ABORTED", "ST_RANKS_DOWN", "ST_TIMEOUT")}


def quiesced(cfg, st):
    ranks, subs, coord, up, down, newt, fb, stale = st
    if up or any(down[r] for r in range(cfg.nranks)):
        return False
    if coord[1] or any(g for g, _ in subs):
        return False
    return not any(ranks[r][0] == R_WAIT for r in coord[7])


def check_state(cfg, st):
    """Safety invariants evaluated on every reachable state."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    if stale:
        out.append(("stale-accept",
                    "coordinator merged a frame older than its epoch"))
    if quiesced(cfg, st):
        cep, alive, dead = coord[0], coord[7], coord[4]
        for r in alive:
            mode, epoch, tick, exitm, pat = ranks[r]
            if mode in (R_CRASH, R_FROZEN, R_STANDBY) or r in dead:
                continue
            if epoch != cep:
                out.append(("epoch-divergence",
                            "rank %d at epoch %d, coordinator at %d"
                            % (r, epoch, cep)))
            if mode == R_STEADY and pat != cep:
                out.append(("steady-divergence",
                            "rank %d replays a pattern negotiated at "
                            "epoch %d under membership epoch %d"
                            % (r, pat, cep)))
    return out


def _derived_faults(cfg, st):
    ranks, subs, coord, up, down, newt, fb, stale = st
    used = set()
    if any(m == R_CRASH for m, *_ in ranks):
        used.add("crash")
    if any(m == R_FROZEN for m, *_ in ranks):
        used.add("freeze")
    if newt >= 0:
        used.add("newt")
    if coord[9] or any(s in coord[7] for s in cfg.standby):
        used.add("join")
    if cfg.p2p and cfg.p2p_lost_recv:
        # The application-level mismatch (recv never posted) is a
        # configured fault: a terminal must resolve it through the
        # paired-readiness timeout sweep, never a silent hang.
        used.add("p2p-lost")
    return used


def classify_terminal(cfg, st):
    """Classify a state with no enabled actions.

    Returns (ok, xfail_tag_or_None, detail).  ``ok=False`` is a
    deadlock / wrong-outcome violation.
    """
    ranks, subs, coord, up, down, newt, fb, stale = st
    cep, alive, abort = coord[0], coord[7], coord[8]
    live = [r for r in alive
            if ranks[r][0] not in (R_CRASH, R_FROZEN)]
    modes = {r: ranks[r][0] for r in live}
    used = _derived_faults(cfg, st)
    all_done = live and all(m == R_DONE for m in modes.values())
    all_exited = live and all(m in (R_DONE, R_ABORT)
                              for m in modes.values())
    if any(m == R_STUCK for m in modes.values()):
        return (False, None,
                "rank(s) %s stranded with a dropped op"
                % [r for r, m in modes.items() if m == R_STUCK])
    if not all_exited:
        if any(m == R_P2P for m in modes.values()):
            return (False, None,
                    "rank(s) %s blocked forever on an unmatched p2p "
                    "announce (paired-readiness deadlock: the send "
                    "never reached the timeout sweep)"
                    % [r for r, m in modes.items() if m == R_P2P])
        return (False, None,
                "stalled with live ranks in modes %s, abort=%d"
                % (sorted(modes.values()), abort))
    if any(m == R_ABORT for m in modes.values()) and abort not in TYPED:
        return (False, None,
                "ranks aborted without a typed status (abort=%d)" % abort)
    shut_latched = st[2][2]
    if all_done:
        # Completed program.  A completed run justifies any fault that
        # is either absent or was absorbed by a reshape.  A fault that
        # raced the final shutdown broadcast (coordinator already
        # latched shut) needs no resolution: the job ended, and the
        # faulty rank's teardown is the exchange layer's EOF, outside
        # the control plane.
        if shut_latched:
            return (True, None, "completed")
        if "crash" in used:
            crashed = [r for r in range(cfg.nranks)
                       if ranks[r][0] == R_CRASH]
            if any(c in alive for c in crashed):
                return (False, None,
                        "completed with crashed rank(s) %s still in the "
                        "membership (no reshape, no abort)" % crashed)
        if "freeze" in used:
            frozen = [r for r in range(cfg.nranks)
                      if ranks[r][0] == R_FROZEN]
            if any(f in alive for f in frozen):
                return (False, None,
                        "completed with frozen rank(s) %s still in the "
                        "membership (never detected, never evicted)"
                        % frozen)
        return (True, None, "completed")
    # Typed abort terminal: must be justified by the faults on the path.
    if not used:
        return (False, None,
                "typed abort %d with no injected fault" % abort)
    if used == {"crash"} and cfg.elastic:
        survivors = [r for r in alive if ranks[r][0] != R_CRASH]
        if len(survivors) >= cfg.min_size:
            return (False, None,
                    "elastic crash with %d >= min_size=%d survivors must "
                    "reshape and complete, not abort (%d)"
                    % (len(survivors), cfg.min_size, abort))
        if abort != STATUS["ST_RANKS_DOWN"]:
            return (False, None,
                    "undersized elastic crash must abort ST_RANKS_DOWN, "
                    "got %d" % abort)
        return (True, None, "typed ST_RANKS_DOWN")
    if used == {"crash"}:
        if abort != STATUS["ST_ABORTED"]:
            return (False, None,
                    "non-elastic crash must abort ST_ABORTED, got %d"
                    % abort)
        return (True, None, "typed ST_ABORTED")
    if used == {"freeze"}:
        if cfg.heartbeat:
            # The detector owns freezes (act_hb_detect preempts the
            # exchange-silence timeout): elastic jobs with enough
            # survivors must EVICT and complete — they never reach this
            # typed-abort branch — and every abort that remains is the
            # coordinated RanksDownError.
            if cfg.elastic:
                survivors = [r for r in alive if ranks[r][0] != R_FROZEN]
                if len(survivors) >= cfg.min_size:
                    return (False, None,
                            "elastic freeze with %d >= min_size=%d "
                            "survivors must evict via reshape and "
                            "complete, not abort (%d)"
                            % (len(survivors), cfg.min_size, abort))
            if abort != STATUS["ST_RANKS_DOWN"]:
                return (False, None,
                        "heartbeat-detected freeze must abort "
                        "ST_RANKS_DOWN, got %d" % abort)
            return (True, None, "typed ST_RANKS_DOWN")
        if abort != STATUS["ST_TIMEOUT"]:
            return (False, None,
                    "freeze without the heartbeat detector must abort "
                    "ST_TIMEOUT, got %d" % abort)
        return (True, None, "typed ST_TIMEOUT")
    if used == {"p2p-lost"}:
        # Paired-readiness invariant: the peer is alive and beating, so
        # the ONLY legal resolution for the unmatched announce is the
        # coordinator's collective-timeout sweep (ST_TIMEOUT naming the
        # tensor and the absent peer).
        if abort != STATUS["ST_TIMEOUT"]:
            return (False, None,
                    "unmatched p2p announce must reach the timeout "
                    "sweep (ST_TIMEOUT), got abort=%d" % abort)
        return (True, None, "typed ST_TIMEOUT (paired-readiness)")
    # Multi-fault (deep configs): any typed abort is acceptable.
    return (True, None, "typed abort %d under faults %s"
            % (abort, sorted(used)))
