"""hvdmodel: bounded exhaustive model checker for the control-plane protocol.

An executable abstract model of the engine's control plane — coordinator
tick, announce aggregation through the PR-13 sub-coordinator tree,
cache-bit agreement and steady-state replay, the elastic reshape barrier
(shrink, grow, standby admission), and the abort/timeout cascade — plus
a breadth-first explorer that enumerates every interleaving of frame
delivery, tick boundaries, and injected faults up to a bound, checking:

  1. no deadlock (every non-terminal state has an enabled action);
  2. live ranks agree on membership epoch and steady pattern at
     quiesced boundaries;
  3. every injected fault reaches a *typed* abort or a completed
     reshape (never a silent stall), modulo documented xfails;
  4. no stale-epoch frame is ever accepted by the coordinator.

The model is kept in sync with the C++ by hvdlint checker #7
(``model_check``): the coverage sets in ``coverage.py`` must match the
``ST_*`` enum and the steady/reshape wire fields in
``engine/cc/wire.h`` bidirectionally.

Run ``python -m tools.hvdmodel --quick`` (tier-1) or ``--deep``.
"""

__all__ = ["model", "invariants", "explorer", "coverage", "configs", "trace"]
