"""Abstract model of the control-plane protocol.

Each model state is an immutable tuple; ``successors()`` enumerates every
enabled transition (frame send/delivery, tick boundary, steady replay,
injected fault, time-abstracted timeout).  The mapping to the C++ is
documented per action and in WIRE_BINDING below; hvdlint checker #7
(``model_check``) keeps the binding honest against ``wire.h``.

Abstractions (deliberate, documented):
  * Tensor payloads, fusion, and slot *contents* are abstracted away;
    agreement is tracked through the membership epoch and the steady
    pattern's negotiation epoch.  One implicit tensor list per tick.
  * Time is abstracted to enabledness: a timeout that WOULD eventually
    fire is an always-enabled action once its trigger condition holds
    (frozen rank blocking progress, partial steady group starved).
  * TCP gives per-connection FIFO: the up channel delivers the oldest
    frame per sender; down channels are per-rank FIFOs.  Cross-rank
    delivery order is fully interleaved (models delay + reorder).
  * Elastic jobs run the star topology (engine Init forces
    ``coord_tree=false`` under elastic); the coordinator tree is
    explored in non-elastic configurations.
  * Rank 0 / sub-coordinator crash is out of scope (host failure is the
    state plane's job, PR 11); crash/freeze faults target leaf ranks.
  * Point-to-point plane (docs/pipeline.md): one send/recv pair per
    config (``cfg.p2p``), announced on the participants' regular tick
    frames at ``cfg.p2p_tick``.  The announce bit is stamped into the
    coordinator state when the frame is BUILT (monotone early stamp:
    the engine stamps its message table on frame receipt, but a tick
    cannot close without that same frame, so the two are
    indistinguishable at every tick boundary).  A participant whose
    announce is still unmatched at its tick close blocks in
    ``handle.wait()`` — mode ``R_P2P`` — and its engine thread's
    subsequent EMPTY frames are folded away (the gatherings stop
    expecting it) rather than enumerated.  ``cfg.p2p_lost_recv`` models
    the application-level mismatch (the recv is never posted): the
    counterpart stays alive and beating, so only the coordinator's
    collective-timeout sweep can catch it — the paired-readiness
    invariant (an unmatched send must reach ST_TIMEOUT, never a silent
    hang).
"""

# Rank modes.
R_RUN = "R"      # has work for the current tick, will send a frame
R_WAIT = "W"     # frame sent, blocked on the response (RunLoopOnce)
R_STEADY = "S"   # self-clocked replay, zero frames (SteadyLoopOnce)
R_CRASH = "C"    # process died; parent sees EOF
R_FROZEN = "F"   # alive socket, no progress (no EOF, no frames)
R_ABORT = "A"    # consumed a typed abort broadcast
R_DONE = "D"     # consumed the shutdown broadcast
R_STUCK = "X"    # bug mode only: dropped a pending op (no-requeue bug)
R_STANDBY = "B"  # standby: connected but not yet admitted by a reshape
R_P2P = "P"      # announced a send/recv, blocked on the unmatched handle

# Typed status codes mirrored from engine/cc/wire.h (model_check enforces
# the full ST_* enum is listed here; see also coverage.py).  The protocol
# transitions below use the abort-family codes; the request/response
# plumbing codes are bound to model concepts they abstract.
STATUS = {
    "ST_OK": 0,            # normal tick response (act_coord_tick)
    "ST_UNKNOWN": 1,       # unmodeled internal error (no transition)
    "ST_PRECONDITION": 2,  # API misuse; pre-protocol, no transition
    "ST_ABORTED": 3,       # EOF cascade, non-elastic (act_coord_abort)
    "ST_INVALID": 4,       # malformed frame; abstracted away (parser)
    "ST_PENDING": 5,       # the R_WAIT rank state is this code's dual
    "ST_RANKS_DOWN": 6,    # alive < min_size at the barrier
    "ST_TIMEOUT": 7,       # frozen rank, exchange-silence timeout
    "ST_RESHAPE": 8,       # in-flight poison at ApplyReshape ('reshape')
}

# Wire-field binding: every steady/reshape field of RequestList and
# ResponseList (wire.h) and the model concept that covers it.  hvdlint
# model_check cross-checks these names against the struct definitions.
WIRE_BINDING = {
    # RequestList
    "steady_exit": "exitm flag carried by act_send after a steady exit",
    "steady_exits": "relayed exit set in 'agg' frames / coord exits",
    "steady_epoch": "abstracted into the rank tick counter (replay pos)",
    "steady_pos": "abstracted into the rank tick counter (replay pos)",
    "dead_ranks": "sub dead set piggybacked on 'agg' frames",
    "hb_report": "out-of-band dead-rank report; folded into act_hb_detect "
                 "(the escalation path from monitor flag to MarkRankDead)",
    "membership_epoch": "frame epoch; stale guard in act_coord_recv",
    # ResponseList
    "steady_present": "'steady' broadcast kind (enter self-clocked mode)",
    "steady_pattern": "pattern identity == negotiation epoch (rank pat)",
    "steady_groups": "abstracted: one replay group per cycle",
    "steady_revoke": "'revoke' broadcast kind (resume / reshape-revoke)",
    "reshape_present": "'reshape' broadcast kind (barrier commit)",
    "reshape_lost": "alive-set delta carried by the 'reshape' frame",
    "member_old_ranks": "new alive tuple carried by the 'reshape' frame",
    "member_endpoints": "abstracted: rewiring is instantaneous",
    "reshape_cache_capacity": "abstracted: autotune payload reset",
    "reshape_fusion_threshold": "abstracted: autotune payload reset",
    "reshape_cycle_time_us": "abstracted: autotune payload reset",
    "reshape_compression": "abstracted: autotune payload reset",
    "reshape_compression_min_bytes": "abstracted: autotune payload reset",
    "reshape_cross_algo_threshold": "abstracted: autotune payload reset",
}

# Point-to-point wire binding (hvdlint model_check FAMILIES "Request" /
# "Response"): the per-item pairing fields and the model concept each is
# abstracted into.  The model tracks announce ROLES, not payloads: the
# coordinator's paired-readiness check (exactly one send + one recv,
# mutual peers, equal tag/dims/dtype) collapses to the two-bit coord
# `p2p` field, and a validation mismatch is a RESP_ERROR the engine
# surfaces pre-protocol (like ST_PRECONDITION, no transition).
P2P_WIRE_BINDING = {
    # Request
    "p2p_peer": "participant identity: cfg.p2p = (src, dst)",
    "p2p_tag": "abstracted: one pair per config, tag agreement implicit",
    "stage_ranks": "abstracted: group scoping narrows the announce count "
                   "the same way the pair does (act_coord_tick match)",
    # Response
    "p2p_src": "the matched pair broadcast: ('resp', ep, 'p2p') frame",
    "p2p_dst": "the matched pair broadcast: ('resp', ep, 'p2p') frame",
    "p2p_dtype": "abstracted: slot metadata for the lockstep cache Put",
    "p2p_dims": "abstracted: slot metadata for the lockstep cache Put",
}

# Seeded-bug switches (each disables one of the engine's defenses so the
# explorer demonstrably catches the class of bug it guards against).
# ``drop-heartbeat-revoke`` severs the monitor-to-coordinator escalation
# (flag -> hb_report -> MarkRankDead): the frozen rank is never evicted
# and, with the detector owning freeze detection (act_timeout defers to
# it), the job stalls forever — the missed-eviction trace of ISSUE 17.
BUGS = ("skip-revoke", "stale-epoch", "no-requeue",
        "drop-heartbeat-revoke", "p2p-unmatched-send")


class Config:
    """Bounded model configuration (immutable after construction)."""

    def __init__(self, name, hosts, elastic=False, min_size=1, standby=(),
                 threshold=2, ticks=4, fault_budget=0, faults=(), bug=None,
                 group_timeout=True, heartbeat=True, p2p=None, p2p_tick=1,
                 p2p_lost_recv=False):
        self.name = name
        self.hosts = tuple(tuple(h) for h in hosts)
        self.elastic = elastic
        self.min_size = min_size
        self.standby = tuple(standby)     # rank ids living in their own host
        self.threshold = threshold        # identical ticks before steady
        self.ticks = ticks                # program length per rank
        self.fault_budget = fault_budget
        self.faults = tuple(faults)       # subset of ('crash:N','freeze:N','join','newt')
        # The data-plane group timeout (HVD_TPU_STEADY_GROUP_TIMEOUT) is
        # a backstop, not part of the control plane: configs with
        # ``group_timeout=False`` prove the protocol stays live when the
        # backstop never fires (the revocation broadcast alone must
        # unblock every survivor).
        self.group_timeout = group_timeout
        # The data-plane heartbeat detector (HVD_TPU_HEARTBEAT_MS, ISSUE
        # 17): on by default like the engine.  ``heartbeat=False`` models
        # HVD_TPU_HEARTBEAT_MS=0 — frozen ranks are then only caught by
        # the exchange-silence timeout (act_timeout).
        self.heartbeat = heartbeat
        # One send/recv pair per config: (src, dst) announcing on their
        # tick-`p2p_tick` frames.  Rank 0 is excluded as a participant —
        # a blocked rank 0 APP is engine-legal (its engine thread keeps
        # ticking) but the model folds blocked ranks' empty frames away,
        # and rank 0's in-process merge is the tick anchor.
        self.p2p = tuple(p2p) if p2p else None
        self.p2p_tick = p2p_tick
        self.p2p_lost_recv = p2p_lost_recv
        if self.p2p is not None:
            assert len(self.p2p) == 2 and 0 not in self.p2p, p2p
            assert self.p2p[0] != self.p2p[1], p2p
        self.bug = bug
        assert bug in (None,) + BUGS, bug
        self.nranks = max(max(h) for h in self.hosts) + 1
        self.host_of = {}
        for h, members in enumerate(self.hosts):
            for r in members:
                self.host_of[r] = h
        self.leaders = tuple(h[0] for h in self.hosts)

    def initial_alive(self):
        return tuple(sorted(r for h in self.hosts for r in h
                            if r not in self.standby))


def initial_state(cfg):
    ranks = tuple(
        (R_STANDBY if r in cfg.standby else R_RUN, 0, 0, 0, -1)
        for r in range(cfg.nranks))
    subs = tuple(((), ()) for _ in cfg.hosts)
    coord = (0, (), False, (), (), 0, False, cfg.initial_alive(), 0, False,
             ())
    down = tuple(() for _ in range(cfg.nranks))
    return (ranks, subs, coord, (), down, -1, cfg.fault_budget, False)


# -- tuple accessors (kept as plain indices for hashing speed) ----------
# rank: (mode, epoch, tick, exitm, pat)
# coord: (epoch, got, shut, exits, dead, hist, steady, alive, abort,
#         joinp, p2p) — p2p is the announce-role latch for cfg.p2p:
#         () nothing announced, ("r",)/("s",) partial, ("r","s")
#         both in (matched at the next tick close), ("M",) matched.

def _rank(ranks, r, **kw):
    m, e, t, x, p = ranks[r]
    vals = {"mode": m, "epoch": e, "tick": t, "exitm": x, "pat": p}
    vals.update(kw)
    out = list(ranks)
    out[r] = (vals["mode"], vals["epoch"], vals["tick"], vals["exitm"],
              vals["pat"])
    return tuple(out)


def _coord(c, **kw):
    keys = ("epoch", "got", "shut", "exits", "dead", "hist", "steady",
            "alive", "abort", "joinp", "p2p")
    vals = dict(zip(keys, c))
    vals.update(kw)
    return tuple(vals[k] for k in keys)


def _push_down(down, r, frame):
    out = list(down)
    out[r] = out[r] + (frame,)
    return tuple(out)


def _live_members(cfg, h, alive, dead_known, ranks):
    """Host members the gatherer still expects a frame from.  A rank
    blocked on an unmatched p2p handle (R_P2P) keeps its engine thread
    ticking but contributes only empty frames — folded away here rather
    than enumerated (see the module docstring)."""
    return tuple(r for r in cfg.hosts[h]
                 if r in alive and r not in dead_known
                 and ranks[r][0] != R_P2P)


# -- frame application on a rank (response consumption) -----------------

def _apply_down(cfg, ranks, r, frame, events):
    """Model of the worker side of ProcessResponseList + the
    broadcast-resumed branches (SteadyLoopOnce / SubRelayPass)."""
    kind, fep, payload = frame
    mode, epoch, tick, exitm, pat = ranks[r]
    if mode in (R_CRASH, R_ABORT, R_DONE):
        return ranks  # dropped on the floor; this rank is gone
    if kind == "abort":
        return _rank(ranks, r, mode=R_ABORT)
    if kind == "shut":
        if mode == R_P2P:
            # Shutdown with a pending p2p handle: the op is stranded
            # (the gate in act_coord_tick makes this unreachable; kept
            # so a gate regression screams instead of "completing").
            return _rank(ranks, r, mode=R_STUCK)
        return _rank(ranks, r, mode=R_DONE)
    if mode == R_P2P:
        if kind == "resp" and payload == "p2p":
            # The counterpart finally announced and the coordinator
            # matched the pair: the blocked handle completes and the
            # program resumes (ExecuteSendRecv + CompleteEntry).
            events.add("p2p_execute")
            return _rank(ranks, r, mode=R_RUN, tick=tick + 1)
        return ranks  # empty tick / straggler while the app is blocked
    if mode == R_WAIT:
        if kind == "resp":
            if (cfg.p2p and r in cfg.p2p and tick == cfg.p2p_tick
                    and payload != "p2p"
                    and not (r == cfg.p2p[1] and cfg.p2p_lost_recv)):
                # This rank's tick-`p2p_tick` frame announced its half
                # of the pair, but the tick closed without the
                # counterpart: the app blocks in handle.wait() and this
                # rank stops contributing work (R_P2P).
                events.add("p2p_blocked")
                return _rank(ranks, r, mode=R_P2P)
            if (cfg.p2p and r in cfg.p2p and tick == cfg.p2p_tick
                    and payload == "p2p"):
                events.add("p2p_execute")
            return _rank(ranks, r, mode=R_RUN, tick=tick + 1)
        if kind == "steady":
            events.add("steady_enter")
            return _rank(ranks, r, mode=R_STEADY, tick=tick + 1, pat=fep)
        if kind == "revoke":
            # Bare revocation consumed as an empty-tick response: the
            # pending op is requeued and resent (ticks_done_ stays
            # symmetric: every rank consumes exactly one revoke).
            return _rank(ranks, r, mode=R_RUN)
        if kind == "reshape":
            events.add("reshape_adopt")
            return _rank(ranks, r, mode=R_RUN, epoch=fep)
    if mode == R_STEADY:
        if kind == "revoke":
            events.add("steady_exit")
            if cfg.bug == "no-requeue":
                # Seeded bug: the drained-but-unreplayed partial group is
                # dropped instead of requeued -> the op is stranded.
                return _rank(ranks, r, mode=R_STUCK, pat=-1)
            return _rank(ranks, r, mode=R_RUN, pat=-1)
        if kind == "reshape":
            # Only reachable with bug == 'skip-revoke': the barrier fired
            # while the pattern was armed.  The rank keeps replaying a
            # pattern negotiated under the old membership.
            events.add("reshape_under_steady")
            return ranks
    if mode == R_RUN and kind == "revoke":
        return ranks  # straggler revoke: empty-tick, op resent anyway
    # Anything else is a protocol error surfaced by the invariants.
    events.add("unexpected_frame:%s:%s" % (mode, kind))
    return ranks


# -- broadcast helper (rank 0 consumes its own response in-process) -----

def _broadcast(cfg, ranks, down, alive, frame, events, skip=()):
    for r in alive:
        if r in skip or ranks[r][0] == R_CRASH:
            continue
        if r == 0:
            ranks = _apply_down(cfg, ranks, 0, frame, events)
        else:
            down = _push_down(down, r, frame)
    return ranks, down


# -- coordinator gathering merge (CoordinatorHandle) --------------------

def _coord_merge(cfg, st, agg, events):
    """Merge an aggregate into rank 0's gathering.  The stale-epoch guard
    and the duplicate-host guard live here (engine: CoordinatorHandle)."""
    ranks, subs, coord, up, down, newt, fb, stale = st
    (cep, got, shut, exits, dead, hist, steady, alive, abort, joinp,
     p2p) = coord
    _, h, fep, fshut, fexits, fdead = agg
    if fep < cep and cfg.bug != "stale-epoch":
        events.add("stale_drop")
        return st
    if fep < cep:
        events.add("stale_accept")
        stale = True
    if h in got:
        # Duplicate aggregate for this gathering cycle (a post-revocation
        # resend racing its own original, one tick of pipeline lag).  The
        # engine still PARSES the frame — shutdown/exit/dead markers are
        # persistent latches updated by every frame (CoordinatorHandle),
        # only the per-tick frame accounting ignores it.  Dropping the
        # latches too would lose a steady-exit marker carried by the
        # lagged frame and hold the resume barrier forever.
        events.add("dup_latch")
        coord = _coord(coord, shut=shut or fshut,
                       exits=tuple(sorted(set(exits) | set(fexits))),
                       dead=tuple(sorted(set(dead) | set(fdead))))
        return (ranks, subs, coord, up, down, newt, fb, stale)
    members = set(r for r in cfg.hosts[h] if r in alive)
    if not members:
        events.add("stray_drop")  # post-shrink straggler from a lost host
        return st
    coord = _coord(coord, got=tuple(sorted(got + (h,))),
                   shut=shut or fshut,
                   exits=tuple(sorted(set(exits) | set(fexits))),
                   dead=tuple(sorted(set(dead) | set(fdead))))
    return (ranks, subs, coord, up, down, newt, fb, stale)


# ======================================================================
# Actions.  Each act_* returns a list of (label, newstate, events).
# ======================================================================

def act_send(cfg, st):
    """A rank with work builds and sends its frame (RunLoopOnce): announce
    + shutdown bit at end-of-program + steady_exit marker if it just left
    steady.  Leaders merge in-process; leaves put a frame on the wire."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    alive = coord[7]
    for r in range(cfg.nranks):
        mode, epoch, tick, exitm, pat = ranks[r]
        if mode != R_RUN or r not in alive or coord[8]:
            continue
        if tick > cfg.ticks:
            # The shutdown-signaling frame (tick == cfg.ticks) was sent
            # and answered; further frames are empty keepalives the
            # model folds away.  Unreachable while the shutdown
            # broadcast is prompt; reachable when the p2p gate in
            # act_coord_tick holds the shutdown back (seeded
            # p2p-unmatched-send: the job must visibly STALL, not spin).
            continue
        h = cfg.host_of[r]
        fshut = tick >= cfg.ticks
        nranks = _rank(ranks, r, mode=R_WAIT, exitm=0)
        ev = set()
        ncoord = coord
        if cfg.p2p and r in cfg.p2p and tick == cfg.p2p_tick:
            role = "s" if r == cfg.p2p[0] else "r"
            if (not (role == "r" and cfg.p2p_lost_recv)
                    and role not in coord[10] and "M" not in coord[10]):
                # The announce rides this frame; the bit is stamped at
                # build time (monotone early stamp, module docstring).
                ev.add("p2p_announce")
                ncoord = _coord(coord, p2p=tuple(sorted(
                    set(coord[10]) | {role})))
        if r == cfg.leaders[h]:
            gathered, sdead = subs[h]
            if any(g[0] == r for g in gathered):
                continue  # already merged (shouldn't happen; guard)
            nsubs = list(subs)
            nsubs[h] = (tuple(sorted(gathered
                                     + ((r, epoch, fshut, exitm),))),
                        sdead)
            out.append(("send(%d)" % r,
                        (nranks, tuple(nsubs), ncoord, up, down, newt, fb,
                         stale), ev))
        else:
            frame = ("leaf", h, r, epoch, fshut, exitm)
            out.append(("send(%d)" % r,
                        (nranks, subs, ncoord, up + (frame,), down, newt,
                         fb, stale), ev))
    return out


def act_deliver_up(cfg, st):
    """Deliver the oldest in-flight frame per sender: leaf frames merge
    into the host sub-coordinator's gathering, aggregates into rank 0's
    (per-connection FIFO; cross-sender order is free)."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    seen = set()
    for i, frame in enumerate(up):
        key = (frame[0], frame[2] if frame[0] == "leaf" else frame[1])
        if key in seen:
            continue
        seen.add(key)
        nup = up[:i] + up[i + 1:]
        ev = set()
        if frame[0] == "leaf":
            _, h, r, fep, fshut, fexitm = frame
            gathered, sdead = subs[h]
            if r in sdead or ranks[r][0] == R_CRASH and r in sdead:
                ev.add("dead_drop")
                out.append(("deliver_up(leaf:%d)" % r,
                            (ranks, subs, coord, nup, down, newt, fb,
                             stale), ev))
                continue
            if any(g[0] == r for g in gathered):
                ev.add("dup_drop")
                out.append(("deliver_up(leaf:%d)" % r,
                            (ranks, subs, coord, nup, down, newt, fb,
                             stale), ev))
                continue
            nsubs = list(subs)
            nsubs[h] = (tuple(sorted(gathered
                                     + ((r, fep, fshut, fexitm),))),
                        sdead)
            out.append(("deliver_up(leaf:%d)" % r,
                        (ranks, tuple(nsubs), coord, nup, down, newt, fb,
                         stale), ev))
        else:
            nst = _coord_merge(cfg, (ranks, subs, coord, nup, down, newt,
                                     fb, stale), frame, ev)
            out.append(("deliver_up(agg:h%d)" % frame[1], nst, ev))
    return out


def act_sub_flush(cfg, st):
    """A sub-coordinator whose gathering covers every live local rank
    flushes the aggregate upward (MergeFrameIntoAggregate + relay).  The
    rank-0 host's aggregate merges in-process."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    alive = coord[7]
    for h in range(len(cfg.hosts)):
        gathered, sdead = subs[h]
        if not gathered:
            continue
        need = _live_members(cfg, h, alive, sdead, ranks)
        have = tuple(g[0] for g in gathered)
        if not need or set(have) != set(need):
            continue
        fshut = any(g[2] for g in gathered)
        fexits = tuple(sorted(r for r, _, _, x in gathered if x))
        # The aggregate's epoch is stamped when the sub BUILDS it
        # (membership_epoch_.load() at the agg sites), i.e. the sub's
        # epoch when its own frame joined the gathering — captured at
        # send time, never restamped in flight.
        leader_ep = max((e for r, e, _, _ in gathered
                         if r == cfg.leaders[h]),
                        default=max(e for _, e, _, _ in gathered))
        agg = ("agg", h, leader_ep, fshut, fexits, sdead)
        nsubs = list(subs)
        nsubs[h] = ((), sdead)
        nst = (ranks, tuple(nsubs), coord, up, down, newt, fb, stale)
        ev = set()
        if cfg.leaders[h] == 0:
            nst = _coord_merge(cfg, nst, agg, ev)
        else:
            nst = nst[:3] + (nst[3] + (agg,),) + nst[4:]
        out.append(("sub_flush(h%d)" % h, nst, ev))
    return out


def act_coord_tick(cfg, st):
    """Rank 0 has every live host's aggregate: close the tick.  Branch
    order mirrors ProcessResponseList/CoordinatorMaybeReshape: reshape
    barrier first, then shutdown, then steady entry / normal response."""
    ranks, subs, coord, up, down, newt, fb, stale = st
    (cep, got, shut, exits, dead, hist, steady, alive, abort, joinp,
     p2p) = coord
    if abort:
        return []
    if ranks[0][0] != R_WAIT:
        # The tick is computed on rank 0's own thread, after it merged
        # its in-process frame and finished the per-child recv loop —
        # never while rank 0 is between passes (RunLoopOnce structure).
        return []
    need_hosts = set(cfg.host_of[r] for r in alive
                     if r not in dead and ranks[r][0] != R_P2P)
    if not need_hosts or not set(got) >= need_hosts:
        return []
    live = tuple(r for r in alive if r not in dead)
    if steady and not set(exits) >= set(live):
        return []  # CoordinatorSteadyPoll: hold until AllSteadyExited
    ev = set()
    label = "coord_tick"
    if cfg.elastic and (dead or joinp):
        survivors = tuple(r for r in alive if r not in dead)
        if len(survivors) < cfg.min_size:
            ev.add("abort:ST_RANKS_DOWN")
            ncoord = _coord(coord, abort=STATUS["ST_RANKS_DOWN"],
                            got=(), steady=False, exits=())
            nranks, ndown = _broadcast(cfg, ranks, down, alive,
                                       ("abort", cep, "ST_RANKS_DOWN"),
                                       ev)
            return [(label + "(ranks_down)",
                     (nranks, subs, ncoord, up, ndown, newt, fb, stale),
                     ev)]
        newalive = survivors
        nranks = ranks
        if joinp:
            j = cfg.standby[0]
            newalive = tuple(sorted(newalive + (j,)))
            # Joiner adopts the survivors' program position (elastic
            # state broadcast; abstracted to the tick counter).
            jtick = max((ranks[r][2] for r in survivors), default=0)
            nranks = _rank(ranks, j, mode=R_RUN, epoch=cep + 1,
                           tick=jtick)
            ev.add("reshape_grow")
        if dead:
            ev.add("reshape_shrink")
        ncoord = _coord(coord, epoch=cep + 1, got=(), shut=False,
                        exits=(), dead=(), hist=0, steady=False,
                        alive=newalive, joinp=False, p2p=())
        # Sub dead-marks are consumed by the barrier (membership reset).
        nsubs = tuple(((), ()) for _ in cfg.hosts)
        frame = ("reshape", cep + 1, newalive)
        # The joiner does NOT also get the frame queued: the admitting
        # broadcast IS the standby's admission message, consumed while
        # it blocks in the rejoin wait (SetupRejoinSockets) — modeled by
        # the _rank() adoption above.  Queueing it again would wedge a
        # later abort behind an undeliverable frame (found by the deep
        # config: freeze after a grow left the joiner stranded in 'R'
        # behind its own admission frame while everyone else aborted
        # ST_TIMEOUT).
        skip = {cfg.standby[0]} if joinp else set()
        nranks, ndown = _broadcast(cfg, nranks, down, newalive, frame,
                                   ev, skip=skip)
        return [(label + "(reshape)",
                 (nranks, nsubs, ncoord, up, ndown, newt, fb, stale), ev)]
    if not cfg.elastic and dead:
        return []  # handled by act_coord_abort (EOF cascade)
    if shut and p2p not in ((), ("M",)):
        # An announced-but-unmatched pair sits in the message table: the
        # coordinator refuses to take the shutdown branch while entries
        # are outstanding (the op must resolve — match, typed abort, or
        # the timeout sweep — before the job may end).  Fall through to
        # a normal tick response.
        pass
    elif shut:
        ev.add("shutdown")
        ncoord = _coord(coord, got=(), steady=False, exits=(), shut=True)
        nranks, ndown = _broadcast(cfg, ranks, down, alive,
                                   ("shut", cep, 0), ev)
        return [(label + "(shutdown)",
                 (nranks, subs, ncoord, up, ndown, newt, fb, stale), ev)]
    # Paired-readiness match: both halves announced and both alive —
    # this tick's response carries the RESP_SENDRECV (BuildResponse's
    # exactly-two-complementary-requests arm).
    if (cfg.p2p and set(p2p) == {"r", "s"}
            and all(pr in alive and pr not in dead
                    and ranks[pr][0] not in (R_CRASH, R_FROZEN)
                    for pr in cfg.p2p)):
        ev.add("p2p_match")
        ncoord = _coord(coord, got=(), hist=0, steady=False, exits=(),
                        p2p=("M",))
        nranks, ndown = _broadcast(cfg, ranks, down, alive,
                                   ("resp", cep, "p2p"), ev)
        return [(label + "(p2p_match)",
                 (nranks, subs, ncoord, up, ndown, newt, fb, stale), ev)]
    resumed = steady
    nhist = 0 if resumed else hist + 1
    if (cfg.threshold and not resumed and nhist >= cfg.threshold):
        ev.add("steady_enter")
        ncoord = _coord(coord, got=(), hist=0, steady=True, exits=())
        nranks, ndown = _broadcast(cfg, ranks, down, alive,
                                   ("steady", cep, 0), ev)
        return [(label + "(steady)",
                 (nranks, subs, ncoord, up, ndown, newt, fb, stale), ev)]
    if resumed:
        ev.add("steady_resume")
    ncoord = _coord(coord, got=(), hist=nhist, steady=False, exits=())
    nranks, ndown = _broadcast(cfg, ranks, down, alive, ("resp", cep, 0),
                               ev)
    return [(label, (nranks, subs, ncoord, up, ndown, newt, fb, stale),
             ev)]


def act_deliver_down(cfg, st):
    """Deliver the head of a rank's response FIFO.  Frozen ranks never
    read their socket; crashed ranks drop frames on the floor.  A rank
    in R_RUN is between ticks — it sends its next frame BEFORE reading,
    so ordinary responses wait in the FIFO until it blocks again (only
    the abort/shutdown cascade reaches it out of band)."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    for r in range(cfg.nranks):
        if not down[r]:
            continue
        mode = ranks[r][0]
        if mode == R_FROZEN:
            continue
        head = down[r][0][0]
        if (mode in (R_RUN, R_STANDBY, R_STUCK)
                and head not in ("abort", "shut")
                and not (mode == R_RUN and head == "revoke")):
            # A straggler revoke IS deliverable to a running rank: the
            # engine drains and discards it at the rank's next socket
            # read no matter what it sent first.  Leaving it queued
            # would head-block the abort cascade behind an undeliverable
            # frame (found by the deep config: a rank that exited steady
            # via group-timeout just before the revoke broadcast).
            continue
        frame, rest = down[r][0], down[r][1:]
        ndown = list(down)
        ndown[r] = rest
        ev = set()
        nranks = _apply_down(cfg, ranks, r, frame, ev)
        out.append(("deliver_down(%d:%s)" % (r, frame[0]),
                    (nranks, subs, coord, up, tuple(ndown), newt, fb,
                     stale), ev))
    return out


def act_steady_replay(cfg, st):
    """Self-clocked replay of one pattern cycle (SteadyLoopOnce): no
    frames.  Data-plane coupling: a cycle cannot complete while a
    crashed/frozen member never reaches it."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    alive = coord[7]
    for r in alive:
        mode, epoch, tick, exitm, pat = ranks[r]
        if mode != R_STEADY or tick >= cfg.ticks:
            continue
        if newt >= 0 and tick >= newt:
            continue  # the new tensor is a miss, not a replay
        blocked = any(ranks[p][0] in (R_CRASH, R_FROZEN)
                      and ranks[p][2] <= tick
                      for p in alive if p != r)
        if blocked:
            continue
        ev = {"steady_replay"}
        out.append(("steady_replay(%d)" % r,
                    (_rank(ranks, r, tick=tick + 1), subs, coord, up,
                     down, newt, fb, stale), ev))
    return out


def act_steady_exit(cfg, st):
    """Leave self-clocked mode and fall back to negotiation
    (ExitSteadyLocal + requeue): on a pattern miss (new tensor), at end
    of program, or when the data plane starves the group (2s group
    timeout) because a member is dead/frozen."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    alive = coord[7]
    for r in alive:
        mode, epoch, tick, exitm, pat = ranks[r]
        if mode != R_STEADY:
            continue
        reason = None
        if newt >= 0 and tick >= newt:
            reason = "miss"
        elif tick >= cfg.ticks:
            reason = "shutdown"
        elif (cfg.group_timeout
              and any(ranks[p][0] in (R_CRASH, R_FROZEN)
                      and ranks[p][2] <= tick
                      for p in alive if p != r)):
            reason = "group-timeout"
        if reason is None:
            continue
        ev = {"steady_exit"}
        out.append(("steady_exit(%d:%s)" % (r, reason),
                    (_rank(ranks, r, mode=R_RUN, exitm=1, pat=-1), subs,
                     coord, up, down, newt, fb, stale), ev))
    return out


def act_coord_revoke_reshape(cfg, st):
    """Rank 0, steady, elastic, reshape pending (death or standby):
    broadcast a bare revocation so every survivor falls back to
    negotiation, then let the barrier fire on the next regular tick
    (MaybeRevokeSteadyForReshape)."""
    ranks, subs, coord, up, down, newt, fb, stale = st
    (cep, got, shut, exits, dead, hist, steady, alive, abort, joinp,
     p2p) = coord
    if (not cfg.elastic or not steady or abort
            or cfg.bug == "skip-revoke"):
        return []
    if not dead and not joinp:
        return []
    ev = {"steady_revoke_reshape"}
    # The gathering resets: announces/shutdown/exit markers already
    # latched persist (shut/exits/dead fields), but the next regular
    # tick needs one FRESH liveness frame from every live rank — the
    # engine's next RunLoopOnce pass runs a full per-child recv round,
    # and every revoked rank resends after consuming the revocation.
    # An old frame still in flight counts toward the new round and the
    # resend lags one tick (frames carry deltas, so that is harmless);
    # the model's dup-drop at merge is the same abstraction.  Rank 0's
    # own frame is different: it is an in-process merge rebuilt on every
    # RunLoopOnce pass, so the revocation discards the current one.
    ncoord = _coord(coord, steady=False, exits=(), hist=0, got=())
    h0 = cfg.host_of[0]
    gathered, sdead = subs[h0]
    nsubs = list(subs)
    nsubs[h0] = (tuple(g for g in gathered if g[0] != 0), sdead)
    frame = ("revoke", cep, 0)
    nranks, ndown = _broadcast(cfg, ranks, down, alive, frame, ev,
                               skip=set(dead))
    return [("coord_revoke_reshape",
             (nranks, tuple(nsubs), ncoord, up, ndown, newt, fb, stale),
             ev)]


def act_eof_detect(cfg, st):
    """A crashed rank's parent observes EOF and marks it dead: the sub
    excludes it from gathering and piggybacks dead_ranks on the next
    aggregate; rank 0's own children mark straight into the barrier
    bookkeeping (MarkRankDead)."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    for r in range(cfg.nranks):
        if ranks[r][0] != R_CRASH or r not in coord[7]:
            continue
        h = cfg.host_of[r]
        gathered, sdead = subs[h]
        if r in sdead:
            continue
        ev = {"eof"}
        nsubs = list(subs)
        # The dead rank's queued frames die with the connection.
        ngathered = tuple(g for g in gathered if g[0] != r)
        nsubs[h] = (ngathered, tuple(sorted(sdead + (r,))))
        nst = (ranks, tuple(nsubs), coord, up, down, newt, fb, stale)
        if r == cfg.leaders[h] or cfg.leaders[h] == 0:
            # Leaders (every rank, in the star) hold a connection to
            # rank 0 itself, so their EOF lands straight in the barrier
            # bookkeeping; a leaf's EOF is seen by its sub-coordinator
            # and piggybacks on the next aggregate's dead_ranks.
            ncoord = _coord(coord,
                            dead=tuple(sorted(set(coord[4]) | {r})))
            nst = nst[:2] + (ncoord,) + nst[3:]
        out.append(("eof_detect(%d)" % r, nst, ev))
    return out


def act_hb_detect(cfg, st):
    """The data-plane heartbeat detector (HeartbeatLoop + hb_report,
    ISSUE 17): a frozen rank stops beating, its beat-ring neighbours
    count the misses past HVD_TPU_HEARTBEAT_MISS and the escalation
    reaches rank 0 — directly (rank 0's own monitor), as an hb_report
    frame between ticks, or through the steady poll (the tentpole case:
    zero control frames flowing).  Time-abstracted to an always-enabled
    action; the effect is exactly MarkRankDead — the frozen rank joins
    the coordinator's dead set and its host's gathering excludes it, so
    the existing reshape/abort machinery resolves it."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    if not cfg.heartbeat or cfg.bug == "drop-heartbeat-revoke":
        return []
    if coord[8]:
        return []
    for r in range(cfg.nranks):
        if ranks[r][0] != R_FROZEN or r not in coord[7] or r in coord[4]:
            continue
        h = cfg.host_of[r]
        gathered, sdead = subs[h]
        ev = {"hb_detect"}
        nsubs = list(subs)
        # A frame the frozen rank sent BEFORE freezing may already be
        # gathered or in flight; like the EOF path, the dead-mark drops
        # it from the gathering and dead_drop swallows stragglers.
        nsubs[h] = (tuple(g for g in gathered if g[0] != r),
                    tuple(sorted(set(sdead) | {r})))
        ncoord = _coord(coord, dead=tuple(sorted(set(coord[4]) | {r})))
        out.append(("hb_detect(%d)" % r,
                    (ranks, tuple(nsubs), ncoord, up, down, newt, fb,
                     stale), ev))
    return out


def act_coord_abort(cfg, st):
    """Non-elastic death cascade: a dead peer is unrecoverable, so rank 0
    broadcasts a typed abort every survivor exits with.  EOF deaths keep
    the model's ST_ABORTED binding; a heartbeat-detected freeze carries
    the engine's actual RanksDownError status (MarkRankDead always
    raises ST_RANKS_DOWN — 'ranks down: N (no data-plane heartbeats
    ...)') so the invariant can tell the two detectors apart."""
    ranks, subs, coord, up, down, newt, fb, stale = st
    (cep, got, shut, exits, dead, hist, steady, alive, abort, joinp,
     p2p) = coord
    if cfg.elastic or not dead or abort:
        return []
    code = ("ST_RANKS_DOWN"
            if any(ranks[r][0] == R_FROZEN for r in dead) else "ST_ABORTED")
    ev = {"abort:" + code}
    ncoord = _coord(coord, abort=STATUS[code], got=(),
                    steady=False, exits=())
    nranks, ndown = _broadcast(cfg, ranks, down, alive,
                               ("abort", cep, code), ev,
                               skip=set(dead))
    return [("coord_abort(%s)" % code.lower(),
             (nranks, subs, ncoord, up, ndown, newt, fb, stale), ev)]


def act_timeout(cfg, st):
    """Time-abstracted exchange-silence timeout: a frozen rank blocks
    progress (no frame, no EOF) until CheckCollectiveTimeout fires a
    typed ST_TIMEOUT.  With the heartbeat detector on (the default) this
    action defers to act_hb_detect: the miss window is configured far
    below the collective timeout, so the detector always wins the race
    — the former ``xfail_freeze_eviction`` limitation is gone.  The
    timeout remains the only freeze detector when HVD_TPU_HEARTBEAT_MS=0
    (``heartbeat=False`` configs)."""
    ranks, subs, coord, up, down, newt, fb, stale = st
    (cep, got, shut, exits, dead, hist, steady, alive, abort, joinp,
     p2p) = coord
    if abort or cfg.heartbeat:
        return []
    if not any(ranks[r][0] == R_FROZEN for r in alive):
        return []
    ev = {"abort:ST_TIMEOUT"}
    ncoord = _coord(coord, abort=STATUS["ST_TIMEOUT"], got=(),
                    steady=False, exits=())
    nranks, ndown = _broadcast(cfg, ranks, down, alive,
                               ("abort", cep, "ST_TIMEOUT"), ev)
    return [("timeout_fire",
             (nranks, subs, ncoord, up, ndown, newt, fb, stale), ev)]


def act_p2p_timeout(cfg, st):
    """Paired-readiness backstop (CheckCollectiveTimeout over p2p
    entries): an announced send whose counterpart recv is NEVER posted —
    the peer is alive and beating, so neither EOF nor the heartbeat
    detector can see anything wrong — must reach the coordinator's
    timeout sweep as a typed ST_TIMEOUT naming the tensor and the absent
    peer.  Time-abstracted like act_timeout; enabled only for the
    application-level lost-recv config (a crashed/frozen counterpart is
    the EOF/heartbeat detectors' job and this sweep defers to them).
    The ``p2p-unmatched-send`` seeded bug severs exactly this action:
    the unmatched send then strands its rank in R_P2P, the shutdown
    gate holds, and the whole job stalls — the silent-hang trace the
    invariant exists to forbid."""
    ranks, subs, coord, up, down, newt, fb, stale = st
    (cep, got, shut, exits, dead, hist, steady, alive, abort, joinp,
     p2p) = coord
    if abort or not cfg.p2p or not cfg.p2p_lost_recv:
        return []
    if cfg.bug == "p2p-unmatched-send":
        return []
    if "s" not in p2p or "M" in p2p or "r" in p2p:
        return []
    ev = {"p2p_timeout", "abort:ST_TIMEOUT"}
    ncoord = _coord(coord, abort=STATUS["ST_TIMEOUT"], got=(),
                    steady=False, exits=())
    nranks, ndown = _broadcast(cfg, ranks, down, alive,
                               ("abort", cep, "ST_TIMEOUT"), ev)
    return [("p2p_timeout_fire",
             (nranks, subs, ncoord, up, ndown, newt, fb, stale), ev)]


def act_fault(cfg, st):
    """Inject one fault from the configured set (budget-bounded)."""
    out = []
    ranks, subs, coord, up, down, newt, fb, stale = st
    if fb <= 0 or coord[8]:
        return out
    alive = coord[7]
    for spec in cfg.faults:
        ev = {spec.split(":")[0]}
        if spec.startswith("crash:") or spec.startswith("freeze:"):
            kind, r = spec.split(":")
            r = int(r)
            if r not in alive or ranks[r][0] not in (R_RUN, R_WAIT,
                                                     R_STEADY, R_P2P):
                continue
            nmode = R_CRASH if kind == "crash" else R_FROZEN
            out.append(("fault(%s)" % spec,
                        (_rank(ranks, r, mode=nmode), subs, coord, up,
                         down, newt, fb - 1, stale), ev))
        elif spec == "join":
            if (not cfg.elastic or coord[9] or not cfg.standby
                    or cfg.standby[0] in alive):
                continue
            ncoord = _coord(coord, joinp=True)
            out.append(("fault(join)",
                        (ranks, subs, ncoord, up, down, newt, fb - 1,
                         stale), ev))
        elif spec == "newt":
            if newt >= 0:
                continue
            steady_ticks = [ranks[r][2] for r in alive
                            if ranks[r][0] == R_STEADY]
            if not steady_ticks:
                continue
            at = max(steady_ticks) + 1
            if at >= cfg.ticks:
                continue
            out.append(("fault(newt@%d)" % at,
                        (ranks, subs, coord, up, down, at, fb - 1,
                         stale), ev))
    return out


ACTIONS = (act_send, act_deliver_up, act_sub_flush, act_coord_tick,
           act_deliver_down, act_steady_replay, act_steady_exit,
           act_coord_revoke_reshape, act_eof_detect, act_hb_detect,
           act_coord_abort, act_timeout, act_p2p_timeout, act_fault)


def successors(cfg, st):
    """Every enabled transition from ``st``: (label, line, state, events)."""
    out = []
    for act in ACTIONS:
        line = act.__code__.co_firstlineno
        for label, nst, ev in act(cfg, st):
            out.append((label, line, nst, ev))
    return out
