"""Render counterexample traces as readable event scripts.

Each step points at the model action that produced it in
``tools/hvdmodel/model.py`` (file:line of the ``act_*`` function), so a
trace doubles as an index into the modeled protocol — and, through the
comments on each action, into the corresponding ``engine.cc`` code.
"""

_MODEL_FILE = "tools/hvdmodel/model.py"


def render(cfg, code, detail, steps):
    lines = [
        "VIOLATION %s in config '%s': %s" % (code, cfg.name, detail),
        "shortest failing interleaving (%d steps):" % len(steps),
    ]
    if not steps:
        lines.append("  (violated in the initial state)")
    for i, (label, line) in enumerate(steps, 1):
        lines.append("  %2d. %s:%-4d %s" % (i, _MODEL_FILE, line, label))
    return "\n".join(lines)


def summarize(res):
    cov = ", ".join(sorted(res.coverage)) or "(none)"
    lines = [
        "config '%s': %d states, %d transitions, %d terminals%s"
        % (res.cfg.name, res.states, res.transitions, res.terminals,
           " (truncated)" if res.truncated else ""),
        "  coverage: %s" % cov,
    ]
    for tag, n in sorted(res.xfails.items()):
        lines.append("  xfail %s: %d terminal(s) (documented in "
                     "invariants.py)" % (tag, n))
    return "\n".join(lines)
