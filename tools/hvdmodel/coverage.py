"""Declared model-coverage sets, cross-checked against the C++ by
hvdlint checker #7 (``model_check``).

The literals below are parsed with ``ast.literal_eval`` by the checker
and compared BIDIRECTIONALLY with ``engine/cc/wire.h``:

  * ``MODELED_STATUS_CODES`` must equal the ``StatusCode`` enum;
  * ``MODELED_REQUEST_FIELDS`` must equal the steady/membership family
    of ``RequestList`` fields (``steady_*``, ``dead_ranks``,
    ``hb_report``, ``membership_epoch``);
  * ``MODELED_RESPONSE_FIELDS`` must equal the steady/reshape family of
    ``ResponseList`` fields (``steady_*``, ``reshape_*``, ``member_*``,
    ``membership_epoch``);
  * ``MODELED_P2P_REQUEST_FIELDS`` / ``MODELED_P2P_RESPONSE_FIELDS``
    must equal the point-to-point/stage-group family (``p2p_*``,
    ``stage_*``) of per-item ``Request`` / ``Response`` fields — the
    paired-readiness negotiation the p2p states of the model abstract.

Every name must also be referenced somewhere in the model source (see
``model.STATUS`` / ``model.WIRE_BINDING``) — deleting a modeled status
or field here, or adding one to ``wire.h`` without extending the model,
fails ``python -m tools.hvdlint`` at the introducing PR.  The
``docs/contributing.md`` "Extending the protocol" section walks through
the required steps.
"""

MODELED_STATUS_CODES = {
    "ST_OK",
    "ST_UNKNOWN",
    "ST_PRECONDITION",
    "ST_ABORTED",
    "ST_INVALID",
    "ST_PENDING",
    "ST_RANKS_DOWN",
    "ST_TIMEOUT",
    "ST_RESHAPE",
}

MODELED_REQUEST_FIELDS = {
    "steady_exits",
    "steady_exit",
    "steady_epoch",
    "steady_pos",
    "dead_ranks",
    "hb_report",
    "membership_epoch",
}

MODELED_RESPONSE_FIELDS = {
    "steady_present",
    "steady_pattern",
    "steady_groups",
    "steady_revoke",
    "reshape_present",
    "membership_epoch",
    "reshape_cache_capacity",
    "reshape_fusion_threshold",
    "reshape_cycle_time_us",
    "reshape_compression",
    "reshape_compression_min_bytes",
    "reshape_cross_algo_threshold",
    "member_old_ranks",
    "member_endpoints",
    "reshape_lost",
}

MODELED_P2P_REQUEST_FIELDS = {
    "p2p_peer",
    "p2p_tag",
    "stage_ranks",
}

MODELED_P2P_RESPONSE_FIELDS = {
    "p2p_src",
    "p2p_dst",
    "p2p_tag",
    "p2p_dtype",
    "p2p_dims",
    "stage_ranks",
}
