"""CLI: ``python -m tools.hvdmodel --quick`` (tier-1) / ``--deep``.

Exit status 0 when every explored configuration satisfies the
invariants, 1 otherwise (shortest counterexample traces printed).
``--bug NAME`` runs a seeded-bug configuration that MUST fail — used by
the test-suite to prove the explorer actually catches each class of
bug the engine defends against.
"""

import argparse
import sys
import time

from . import configs, explorer, trace
from .model import BUGS

REQUIRED_QUICK_COVERAGE = (
    "steady_enter", "steady_exit", "reshape_shrink", "reshape_grow",
    "crash", "freeze", "stale_drop", "hb_detect", "abort:ST_TIMEOUT",
    # Point-to-point plane (docs/pipeline.md): the pair's full healthy
    # lifecycle, the blocked-sender state, and the paired-readiness
    # timeout sweep must all be reached by --quick.
    "p2p_announce", "p2p_match", "p2p_execute", "p2p_blocked",
    "p2p_timeout",
)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.hvdmodel",
        description="bounded exhaustive model checker for the "
                    "control-plane protocol")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--quick", action="store_true",
                      help="tier-1 bound (2 hosts x 2 ranks + elastic "
                           "star, <60s)")
    mode.add_argument("--deep", action="store_true",
                      help="slow-tier bound (3 hosts, 2-fault budget)")
    mode.add_argument("--bug", choices=BUGS,
                      help="run a seeded-bug config (expected to FAIL)")
    ap.add_argument("--max-states", type=int, default=None,
                    help="override the per-config expansion cap")
    args = ap.parse_args(argv)

    if args.bug:
        cfgs = [configs.seeded(args.bug)]
    elif args.deep:
        cfgs = configs.deep()
    else:
        cfgs = configs.quick()
    cap = args.max_states or (2000000 if args.deep else 500000)

    total_states = 0
    coverage = set()
    failed = False
    t0 = time.time()
    for cfg in cfgs:
        res = explorer.explore(cfg, max_states=cap)
        total_states += res.states
        coverage |= res.coverage
        print(trace.summarize(res))
        for code, detail, steps in res.violations:
            failed = True
            print(trace.render(cfg, code, detail, steps))
    dt = time.time() - t0
    print("total: %d states across %d config(s) in %.1fs"
          % (total_states, len(cfgs), dt))

    if args.quick and not failed:
        missing = [c for c in REQUIRED_QUICK_COVERAGE
                   if c not in coverage]
        if missing:
            failed = True
            print("COVERAGE GAP: --quick never exercised: %s"
                  % ", ".join(missing))
    if failed:
        print("FAIL")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
