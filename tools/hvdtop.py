#!/usr/bin/env python
"""Live terminal view of a running job's /cluster aggregation (hvdtop).

Polls rank 0's monitor (HVD_TPU_MONITOR_PORT; hvdrun arms /cluster on
rank 0 automatically) and renders one screen per interval: a per-rank
table (liveness, membership epoch, stalls/aborts, cache hit rate,
control-plane activity rate, serving occupancy), a per-link heat table
merged across every rank's telemetry (transport in use, worst-direction
send latency, heartbeat-echo RTT, shm handoff latency, backpressure,
bytes), and a scrolling feed of the
online anomaly detector's typed verdicts (docs/metrics.md#anomalies).

    python tools/hvdtop.py --port 9090                 # live view
    python tools/hvdtop.py --port 9090 --once          # one plain frame
    python tools/hvdtop.py --host tpu-host-0 --port 9090 --interval 2

``--once`` prints a single plain-text frame and exits — scriptable (the
chaos-localization test drives it) and safe for dumb terminals.  The
live view repaints with ANSI clear codes; Ctrl-C exits.

No dependencies beyond the standard library: the tool speaks plain HTTP
to the monitor, so it runs on a laptop far from the job.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request


def fetch_cluster(host: str, port: int, timeout: float = 3.0) -> dict:
    """The /cluster document, or a synthetic dead-job document when the
    monitor is unreachable (the view must render the outage, not
    crash)."""
    url = f"http://{host}:{port}/cluster"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception as exc:  # connection refused, timeout, bad JSON
        return {"ranks": {}, "launched": 0, "live": 0,
                "membership_epochs_agree": True,
                "anomalies": {"total": 0, "verdicts": {}, "recent": []},
                "error": f"{type(exc).__name__}: {exc}"}


def _fmt_us(us) -> str:
    if us is None or us < 0:
        return "-"
    if us >= 10000:
        return f"{us / 1000.0:.0f}ms"
    return f"{us}us"


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def merge_links(ranks: dict) -> dict:
    """Fold every rank's per-peer telemetry into undirected links keyed
    "lo-hi": worst-direction send latency and RTT (a slow direction must
    not hide behind a fast one), summed backpressure and bytes."""
    links: dict = {}
    for rank, entry in ranks.items():
        if not entry.get("live"):
            continue
        for peer, v in (entry.get("links") or {}).items():
            try:
                lo, hi = sorted((int(rank), int(peer)))
            except ValueError:
                continue
            key = f"{lo}-{hi}"
            agg = links.setdefault(key, {"send_mean_us": -1,
                                         "rtt_ewma_us": -1,
                                         "stalls": 0, "bytes": 0,
                                         "transport": "tcp",
                                         "shm_mean_us": -1})
            agg["send_mean_us"] = max(agg["send_mean_us"],
                                      v.get("send_mean_us", -1))
            agg["rtt_ewma_us"] = max(agg["rtt_ewma_us"],
                                     v.get("rtt_ewma_us", -1))
            agg["stalls"] += v.get("stalls", 0)
            agg["bytes"] += v.get("bytes", 0)
            # A link is shm once either endpoint moved bytes through the
            # rings; the handoff latency column shows the worst direction,
            # same policy as send/rtt.
            if v.get("transport") == "shm":
                agg["transport"] = "shm"
            agg["shm_mean_us"] = max(agg["shm_mean_us"],
                                     v.get("shm_handoff_mean_us", -1))
    return links


def render(doc: dict, prev: dict, now: float, target: str) -> str:
    """One frame of the view.  `prev` carries the previous poll's
    per-rank flight-event counts and timestamp, so the activity column
    is a rate (control-plane events per second since the last frame),
    not a lifetime total."""
    lines = []
    agree = "epochs agree" if doc.get("membership_epochs_agree") \
        else "EPOCHS DISAGREE"
    lines.append(f"hvdtop — {target}   live {doc.get('live', 0)}/"
                 f"{doc.get('launched', 0)}   {agree}   "
                 f"{time.strftime('%H:%M:%S', time.localtime(now))}")
    if doc.get("error"):
        lines.append(f"  monitor unreachable: {doc['error']}")
        return "\n".join(lines)

    ranks = doc.get("ranks", {})
    lines.append("")
    lines.append(f"{'rank':<6}{'state':<7}{'epoch':>6}{'stalls':>8}"
                 f"{'aborts':>8}{'cache%':>8}{'act/s':>8}{'occ%':>7}")
    prev_events = prev.get("events", {})
    prev_ts = prev.get("ts")
    dt = (now - prev_ts) if prev_ts else 0.0
    for rank in sorted(ranks, key=lambda r: int(r) if r.isdigit() else 0):
        entry = ranks[rank]
        if not entry.get("live"):
            lines.append(f"{rank:<6}{'DOWN':<7}"
                         f"  ({entry.get('error', 'no response')})")
            continue
        events = entry.get("flight_events", 0)
        rate = "-"
        if dt > 0 and rank in prev_events:
            rate = f"{max(events - prev_events[rank], 0) / dt:.0f}"
        occ = entry.get("serving_occupancy", 0.0)
        lines.append(
            f"{rank:<6}{'up':<7}{entry.get('membership_epoch', 0):>6}"
            f"{entry.get('stalls', 0):>8}{entry.get('aborts', 0):>8}"
            f"{100.0 * entry.get('cache_hit_rate', 0.0):>8.1f}"
            f"{rate:>8}"
            f"{f'{100.0 * occ:.0f}' if entry.get('serving_active') else '-':>7}")

    links = merge_links(ranks)
    if links:
        lines.append("")
        lines.append(f"{'link':<8}{'tpt':>5}{'send':>8}{'rtt':>8}"
                     f"{'shm':>8}{'stalls':>8}{'bytes':>10}")
        slow = {e.get("subject") for e in
                doc.get("anomalies", {}).get("recent", [])
                if e.get("kind") == "slow_link"}
        for key in sorted(links, key=lambda k: [int(x) for x in
                                                k.split("-")]):
            v = links[key]
            mark = "  << slow_link" if key in slow else ""
            lines.append(f"{key:<8}{v.get('transport', 'tcp'):>5}"
                         f"{_fmt_us(v['send_mean_us']):>8}"
                         f"{_fmt_us(v['rtt_ewma_us']):>8}"
                         f"{_fmt_us(v.get('shm_mean_us', -1)):>8}"
                         f"{v['stalls']:>8}"
                         f"{_fmt_bytes(v['bytes']):>10}{mark}")

    anomalies = doc.get("anomalies", {})
    lines.append("")
    lines.append(f"anomalies ({anomalies.get('total', 0)} verdict(s))")
    recent = anomalies.get("recent", [])
    if not recent:
        lines.append("  (none)")
    for e in recent[:10]:
        subject = f"({e.get('subject')})" if e.get("subject") else ""
        lines.append(f"  [rank {e.get('rank')}] "
                     f"{e.get('kind')}{subject}: {e.get('detail', '')} "
                     f"[{e.get('age_us', 0) / 1e6:.1f}s ago]")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="live cluster view over rank 0's /cluster monitor")
    parser.add_argument("--host", default="localhost",
                        help="rank 0 monitor host (default localhost)")
    parser.add_argument("--port", type=int,
                        default=int(os.environ.get(
                            "HVD_TPU_MONITOR_PORT") or 0),
                        help="rank 0 monitor port (default "
                             "$HVD_TPU_MONITOR_PORT)")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="poll cadence in seconds (default 1)")
    parser.add_argument("--once", action="store_true",
                        help="print one plain frame and exit")
    args = parser.parse_args(argv)
    if not args.port:
        parser.error("no monitor port: pass --port or set "
                     "HVD_TPU_MONITOR_PORT")
    target = f"{args.host}:{args.port}"
    prev: dict = {}
    try:
        while True:
            now = time.time()
            doc = fetch_cluster(args.host, args.port)
            frame = render(doc, prev, now, target)
            if args.once:
                print(frame)
                return 0 if not doc.get("error") else 1
            # Full-screen repaint: clear + home, like top(1).
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            prev = {"ts": now,
                    "events": {r: e.get("flight_events", 0)
                               for r, e in doc.get("ranks", {}).items()
                               if e.get("live")}}
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
