#!/usr/bin/env python
"""Diff two bench records and fail on throughput regressions.

Compares the headline ``value`` (and, with ``--extras``, every shared
numeric ``extra_metrics`` entry) of two bench results and exits non-zero
when the new run regressed by more than the threshold — usable locally
("did my change cost throughput?") and as a CI gate between rounds:

    python bench.py > /tmp/new.json
    python tools/bench_compare.py BENCH_r05.json /tmp/new.json --threshold 5

``--history`` renders the round-over-round trajectory instead of a gate:

    python tools/bench_compare.py --history BENCH_r0*.json

one line per round — headline value, vs_baseline ratio, and the delta
against the previous parseable round.  Rounds whose record failed to
parse (a driver crash leaves ``parsed`` empty) render as a gap line
rather than aborting the view.

Accepted file shapes (all produced in this repo):

* raw ``bench.py`` output — one or more JSON lines; the LAST line carrying
  a ``metric`` key wins (bench.py re-prints the headline enriched with
  extras, so the last parseable line is the most complete record);
* a driver round record (``BENCH_r*.json``) — a JSON object whose
  ``parsed`` field holds the bench record.

Headline metrics are throughputs (higher is better).  Extras ending in a
latency unit suffix (``_ms``/``_us``/``_sec`` — the serving bench's TTFT
and per-token latencies) are gated in the opposite direction: growth past
the threshold is the regression.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Tuple


def load_record(path: str) -> dict:
    """The bench record in `path` (see module docstring); raises
    ValueError when none is found."""
    with open(path) as f:
        text = f.read()
    record = None
    try:
        obj = json.loads(text)
        if isinstance(obj, dict):
            record = obj.get("parsed") if isinstance(obj.get("parsed"),
                                                     dict) else obj
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                record = obj
    if not isinstance(record, dict) or "metric" not in record:
        raise ValueError(f"{path}: no bench record found (want a JSON "
                         f"object with a 'metric' key, a driver record "
                         f"with 'parsed', or JSON lines)")
    return record


def _numeric(value) -> Optional[float]:
    # bool is an int subclass, but True/False extras are flags, not rates.
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    return None


# Sign convention for extras: every headline metric in this repo is a
# throughput (higher is better), but some extras are the opposite — a
# time-unit token marks latencies (`ttft_p99_ms`,
# `negotiation_p50_us_cached`), a `bytes` / `inflation` token marks
# wire-byte counters (`bf16_wire_bytes`, `half_wire_inflation` — the
# compression bench, docs/performance.md#wire-compression), and a
# `frames` token marks control-plane frame counts
# (`steady_frames_delta` — the negotiation_scale bench's
# zero-frames-per-steady-cycle contract,
# docs/performance.md#control-plane-scaling): growth past the threshold
# is the regression, not shrinkage.  The scale bench's `_inflation`
# ratios (`steady_scale_inflation` — the flat-in-ranks acceptance bar)
# gate the same way.  A unit preceded by "per" is a rate (`ops_per_sec`,
# `bytes_per_sec`), which stays higher-is-better.
LOWER_IS_BETTER_TOKENS = frozenset(
    ("ms", "us", "sec", "seconds", "bytes", "inflation", "frames"))


def lower_is_better(name: str) -> bool:
    # A token adjacent to "per" on either side is part of a rate
    # ("ops_per_sec", "bytes_per_sec") — rates stay higher-is-better.
    tokens = name.split("_")
    return any(t in LOWER_IS_BETTER_TOKENS
               and (i == 0 or tokens[i - 1] != "per")
               and (i + 1 >= len(tokens) or tokens[i + 1] != "per")
               for i, t in enumerate(tokens))


def compare(old: dict, new: dict, threshold_pct: float,
            extras: bool) -> Tuple[list, list]:
    """(regressions, report_lines) between two bench records.  Only pairs
    present in BOTH records compare; the headline compares only when the
    metric names match (diffing a resnet record against a transformer
    record is a usage error surfaced in the report)."""
    regressions = []
    lines = []

    def check(name: str, ov: float, nv: float) -> None:
        if ov <= 0:
            lines.append(f"  {name}: old={ov:g} (not comparable)")
            return
        delta_pct = (nv - ov) / ov * 100.0
        worse_pct = -delta_pct if lower_is_better(name) else delta_pct
        flag = ""
        if worse_pct < -threshold_pct:
            regressions.append((name, ov, nv, delta_pct))
            flag = "  << REGRESSION"
        lines.append(f"  {name}: {ov:g} -> {nv:g} "
                     f"({delta_pct:+.1f}%){flag}")

    if old["metric"] == new["metric"]:
        ov, nv = _numeric(old.get("value")), _numeric(new.get("value"))
        if ov is not None and nv is not None:
            check(old["metric"], ov, nv)
    else:
        lines.append(f"  headline metrics differ: {old['metric']} vs "
                     f"{new['metric']} (not compared)")
    if extras:
        oe = old.get("extra_metrics") or {}
        ne = new.get("extra_metrics") or {}
        for key in sorted(set(oe) & set(ne)):
            ov, nv = _numeric(oe[key]), _numeric(ne[key])
            if ov is not None and nv is not None:
                check(key, ov, nv)
    return regressions, lines


def render_history(paths: list) -> Tuple[list, int]:
    """(report lines, parseable-round count) for the --history view: one
    line per round file, in the order given (BENCH_r0*.json globs sort
    chronologically).  A round whose record cannot be parsed — e.g. a
    driver crash left ``parsed`` null — renders as a gap line; the
    trajectory deltas skip over it."""
    import os

    lines = [f"{'round':<18}{'value':>12}  {'unit':<18}"
             f"{'vs_baseline':>12}{'delta':>9}"]
    prev = None
    parsed_rounds = 0
    for path in paths:
        label = os.path.basename(path)[:17]
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            lines.append(f"{label:<18}(unreadable: "
                         f"{type(exc).__name__})")
            continue
        record = raw.get("parsed") if isinstance(raw, dict) else None
        if not isinstance(record, dict) or \
                _numeric(record.get("value")) is None:
            rc = raw.get("rc") if isinstance(raw, dict) else None
            lines.append(f"{label:<18}(no parsed record"
                         f"{f', rc {rc}' if rc is not None else ''})")
            continue
        parsed_rounds += 1
        value = _numeric(record["value"])
        vs_base = _numeric(record.get("vs_baseline"))
        delta = (f"{(value - prev) / prev * 100.0:+.1f}%"
                 if prev else "-")
        lines.append(
            f"{label:<18}{value:>12g}  {record.get('unit', ''):<18}"
            f"{vs_base:>11.2f}x{delta:>9}" if vs_base is not None else
            f"{label:<18}{value:>12g}  {record.get('unit', ''):<18}"
            f"{'-':>12}{delta:>9}")
        prev = value
    return lines, parsed_rounds


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--history" in argv:
        argv.remove("--history")
        paths = [a for a in argv if not a.startswith("-")]
        if not paths:
            print("bench_compare: --history wants one or more round "
                  "files (BENCH_r0*.json)", file=sys.stderr)
            return 2
        lines, parsed_rounds = render_history(paths)
        print(f"bench_compare: history over {len(paths)} round(s)")
        for line in lines:
            print(line)
        return 0 if parsed_rounds else 2

    parser = argparse.ArgumentParser(
        description="diff two bench records; exit 1 on a >threshold% "
                    "throughput regression")
    parser.add_argument("old", help="baseline bench/driver JSON file")
    parser.add_argument("new", help="candidate bench/driver JSON file")
    parser.add_argument("--threshold", type=float, default=10.0,
                        metavar="PCT",
                        help="regression tolerance in percent (default 10)")
    parser.add_argument("--extras", action="store_true",
                        help="also gate shared numeric extra_metrics")
    args = parser.parse_args(argv)
    try:
        old = load_record(args.old)
        new = load_record(args.new)
    except (OSError, ValueError) as exc:
        print(f"bench_compare: {exc}", file=sys.stderr)
        return 2
    regressions, lines = compare(old, new, args.threshold, args.extras)
    print(f"bench_compare: {args.old} -> {args.new} "
          f"(threshold {args.threshold:g}%)")
    for line in lines:
        print(line)
    if regressions:
        print(f"bench_compare: FAIL — {len(regressions)} metric(s) "
              f"regressed more than {args.threshold:g}%", file=sys.stderr)
        return 1
    print("bench_compare: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
