#!/usr/bin/env python
"""Profile one benchmark training step and print a device-time breakdown.

Captures a jax.profiler trace of a few steps of the same train step
bench.py measures, parses the XLA ``.xplane.pb`` with TensorFlow's
bundled xplane proto, and aggregates device busy-time by op category —
the tool behind the "where the step actually goes" tables in
docs/benchmarks.md.

Usage:  python tools/profile_step.py [trace_dir]
Env:    same BENCH_* knobs as bench.py (BENCH_MODEL, BENCH_BATCH, ...).
"""

from __future__ import annotations

import collections
import glob
import gzip
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(trace_dir: str, steps: int = 5) -> None:
    os.environ.setdefault("BENCH_STEPS", str(steps))
    os.environ.setdefault("BENCH_WARMUP", "3")
    os.environ.setdefault("BENCH_EXTRA", "0")
    import jax

    import bench

    # Warm up/compile outside the trace by running main once, then trace a
    # second, short run (cached executable).
    bench.main()
    with jax.profiler.trace(trace_dir):
        bench.main()


def load_xplanes(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = [p for pat in ("*.xplane.pb", "*.xplane.pb.gz")
             for p in glob.glob(os.path.join(trace_dir, "**", pat),
                                recursive=True)]
    if not paths:
        raise SystemExit(f"no .xplane.pb under {trace_dir}")
    path = max(paths, key=os.path.getmtime)
    data = open(path, "rb").read()
    if path.endswith(".gz"):
        data = gzip.decompress(data)
    space = xplane_pb2.XSpace()
    space.ParseFromString(data)
    return space


CATEGORIES = [
    ("conv", re.compile(r"convolution|conv[.\d]|cudnn", re.I)),
    ("matmul", re.compile(r"dot|einsum|gemm", re.I)),
    ("copy", re.compile(r"copy", re.I)),
    ("select-and-scatter", re.compile(r"select-and-scatter", re.I)),
    ("reduce-window", re.compile(r"reduce-window", re.I)),
    ("allreduce/collective", re.compile(r"all-reduce|collective|psum", re.I)),
    ("fusion/elementwise", re.compile(r"fusion|loop_|input_|wrapped", re.I)),
    ("reduce", re.compile(r"reduce", re.I)),
    ("transpose/reshape", re.compile(r"transpose|reshape|bitcast", re.I)),
]


def categorize(name: str) -> str:
    for cat, pat in CATEGORIES:
        if pat.search(name):
            return cat
    return "other"


def main() -> None:
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/hvd_tpu_trace"
    if not glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb",),
                     recursive=True):
        capture(trace_dir)
    space = load_xplanes(trace_dir)

    for plane in space.planes:
        # Device planes only (TPU/GPU/accelerator op streams).
        if not ("TPU" in plane.name or "GPU" in plane.name
                or "/device:" in plane.name):
            continue
        sm = {k: v.name for k, v in plane.stat_metadata.items()}
        ev_names, ev_cats, ev_flops = {}, {}, {}
        for k, v in plane.event_metadata.items():
            ev_names[k] = v.display_name or v.name
            for s in v.stats:
                stat = sm.get(s.metadata_id)
                if stat == "hlo_category":
                    ev_cats[k] = s.str_value
                elif stat == "flops":
                    ev_flops[k] = s.uint64_value
        by_cat = collections.Counter()
        by_name = collections.Counter()
        n_events = collections.Counter()
        flops_total = 0
        total = 0
        for line in plane.lines:
            # Steps/XLA Modules lines re-cover the same device time the
            # per-op line itemizes; count only the op events.
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = ev_names.get(ev.metadata_id, "?")
                # The profiler's own hlo_category (convolution, loop
                # fusion, copy, ...) beats name-regex guessing.
                cat = ev_cats.get(ev.metadata_id) or categorize(name)
                dur = ev.duration_ps / 1e6  # -> us
                total += dur
                by_cat[cat] += dur
                by_name[name] += dur
                n_events[name] += 1
                flops_total += ev_flops.get(ev.metadata_id, 0)
        if not total:
            continue
        print(f"\n=== {plane.name}  (total device busy "
              f"{total / 1e3:.2f} ms over trace, "
              f"{flops_total / max(total, 1) / 1e6:.1f} sustained "
              f"TFLOP/s) ===")
        print(f"{'category':<24}{'ms':>10}{'%':>7}")
        for cat, us in by_cat.most_common():
            print(f"{cat:<24}{us / 1e3:>10.2f}{100 * us / total:>6.1f}%")
        print("\ntop ops:")
        print(f"{'op':<56}{'ms':>9}{'n':>6}{'us/call':>9}")
        for name, us in by_name.most_common(25):
            n = n_events[name]
            print(f"{name[:55]:<56}{us / 1e3:>9.2f}{n:>6}{us / n:>9.1f}")


if __name__ == "__main__":
    main()
