#!/usr/bin/env python
"""Pretty-print / diff HVD_TPU_METRICS_FILE dumps (docs/metrics.md).

A dump is the JSON written at shutdown() when HVD_TPU_METRICS_FILE is set
(one file per rank: <path>.<rank>) — the same nested dict
hvd.metrics_snapshot() returns.

    python tools/metrics_dump.py run.json.0            # one dump
    python tools/metrics_dump.py before.json.0 after.json.0   # diff (B - A)
    python tools/metrics_dump.py --stragglers run.json.0      # skew view
    python tools/metrics_dump.py --tenants run.json.0  # serving tenants
    python tools/metrics_dump.py --links run.json.0    # per-link table

Prints the per-op table (ops and bytes per data plane), fusion-batch
counters, stall events, response-cache hit rates (docs/performance.md),
and per-histogram count/mean/p50/p99 estimated
from the fixed buckets (linear interpolation inside the bucket, the
standard Prometheus histogram_quantile estimate) — made for BENCH_* round
analysis next to bench.py's throughput numbers.

``--stragglers`` renders the straggler view instead: ranks ordered by
their share of ``last_to_announce`` (the coordinator's announce-order
accounting — use rank 0's dump) plus the announce-skew histogram's
estimated p50/p99 (docs/troubleshooting.md "Diagnosing stragglers").
"""

from __future__ import annotations

import json
import sys
from typing import Optional


def quantile(hist: dict, q: float) -> Optional[float]:
    """Estimate the q-quantile from fixed-bucket counts (linear
    interpolation within the bucket; the overflow bucket clamps to the
    last finite bound).  None for an empty histogram."""
    total = hist["count"]
    if not total:
        return None
    target = q * total
    cumulative = 0
    lo = 0.0
    for bound, n in zip(hist["buckets"], hist["counts"]):
        if cumulative + n >= target and n:
            return lo + (bound - lo) * (target - cumulative) / n
        cumulative += n
        lo = bound
    return hist["buckets"][-1]  # landed in the +Inf overflow bucket


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _fmt_sec(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v < 1e-3:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.2f}ms"
    return f"{v:.3f}s"


def _delta(b, a):
    return b - a


def render(snap: dict, base: Optional[dict] = None) -> str:
    """Render one dump, or the difference ``snap - base``."""
    lines = []
    tag = " (delta: B - A)" if base else ""
    lines.append(f"== collective ops{tag} ==")
    lines.append(f"{'plane':<8}{'op':<12}{'count':>10}")
    for plane, per_op in snap["ops"].items():
        for op, n in per_op.items():
            if base:
                n = _delta(n, base["ops"][plane][op])
            if n:
                lines.append(f"{plane:<8}{op:<12}{n:>10}")
    if len(lines) == 2:
        lines.append("(no ops)")

    lines.append("== bytes ==")
    for plane, per_dir in snap["bytes"].items():
        for direction, n in per_dir.items():
            if base:
                n = _delta(n, base["bytes"][plane][direction])
            lines.append(f"{plane:<8}{direction:<12}{_fmt_bytes(n):>12}")

    batches = dict(snap["batches"])
    stalls = snap["stalls"]["count"]
    if base:
        batches = {k: _delta(v, base["batches"][k])
                   for k, v in batches.items()}
        stalls = _delta(stalls, base["stalls"]["count"])
    lines.append("== fusion ==")
    lines.append(f"batches dispatched {batches['dispatched']}, "
                 f"tensors carried {batches['fused_tensors']}")
    lines.append(f"== stalls == {stalls}")
    for name, entry in snap["stalls"]["tensors"].items():
        count = entry["count"]
        if base and name in base["stalls"]["tensors"]:
            count = _delta(count, base["stalls"]["tensors"][name]["count"])
        if count:
            lines.append(f"  {name}: x{count} "
                         f"(last {entry['last_duration_sec']:.1f}s)")

    # Fault-tolerance counters (docs/fault-tolerance.md); .get() keeps
    # pre-fault-tolerance dumps readable.
    faults = snap.get("faults", {})
    base_faults = (base or {}).get("faults", {})
    injected = dict(faults.get("injected", {}))
    aborts = dict(faults.get("aborts", {}))
    if base:
        for k, v in base_faults.get("injected", {}).items():
            injected[k] = injected.get(k, 0) - v
        for k, v in base_faults.get("aborts", {}).items():
            aborts[k] = aborts.get(k, 0) - v
    lines.append("== faults ==")
    epoch = faults.get("restart_epoch", 0)
    parts = [f"restart epoch {epoch}"]
    parts.append("injected " + (
        ", ".join(f"{k}x{v}" for k, v in sorted(injected.items()) if v)
        or "none"))
    parts.append("aborts " + (
        ", ".join(f"{k}x{v}" for k, v in sorted(aborts.items()) if v)
        or "none"))
    lines.append("; ".join(parts))

    # Announce-order skew (coordinator dumps; .get keeps older dumps
    # readable).  Full detail lives behind --stragglers.
    skew = snap.get("skew", {})
    counts = dict(skew.get("last_to_announce", {}))
    if base:
        for k, v in (base or {}).get("skew", {}).get(
                "last_to_announce", {}).items():
            counts[k] = counts.get(k, 0) - v
    lines.append("== skew ==")
    nonzero = {k: v for k, v in counts.items() if v}
    if nonzero:
        worst = max(nonzero, key=nonzero.get)
        lines.append(f"negotiations {sum(nonzero.values())}; "
                     f"last_to_announce " +
                     ", ".join(f"rank{k}x{v}"
                               for k, v in sorted(nonzero.items())) +
                     f"; dominant rank {worst}")
    else:
        lines.append("(no negotiations recorded — single rank, or not the "
                     "coordinator's dump)")

    # Response cache (docs/performance.md); .get keeps pre-cache dumps
    # readable.  The hit-rate line is the first thing to look at when a
    # job's negotiation_sec p50 is higher than expected.
    cache = snap.get("cache", {})
    base_cache = (base or {}).get("cache", {})
    lines.append("== response cache ==")
    printed = False
    for plane in sorted(cache):
        c = {k: cache[plane].get(k, 0)
             for k in ("hits", "misses", "evictions")}
        if base:
            for k in c:
                c[k] -= base_cache.get(plane, {}).get(k, 0)
        total = c["hits"] + c["misses"]
        if not total and not c["evictions"]:
            continue
        printed = True
        rate = 100.0 * c["hits"] / total if total else 0.0
        size = "" if base else f", size {cache[plane].get('size', 0)}"
        lines.append(f"{plane:<8}hits {c['hits']}, misses {c['misses']}, "
                     f"evictions {c['evictions']}, "
                     f"hit-rate {rate:.1f}%{size}")
    if not printed:
        lines.append("(no cache traffic — disabled, single step, or a "
                     "pre-cache dump)")

    # Wire compression (docs/performance.md#wire-compression); .get keeps
    # pre-compression dumps readable.  Counters diff in two-file mode;
    # mode/min-bytes/residual gauges stay absolute (the B dump's state).
    comp = snap.get("compression", {})
    comp_planes = comp.get("planes", {})
    totals = {"wire": 0, "payload": 0, "compressed": 0}
    base_planes = (base or {}).get("compression", {}).get("planes", {})
    for plane, entry in comp_planes.items():
        wire, payload = entry.get("wire_bytes", 0), entry.get(
            "payload_bytes", 0)
        compressed = sum(n for m, n in entry.get("ops", {}).items()
                         if m != "none")
        if base:
            b = base_planes.get(plane, {})
            wire -= b.get("wire_bytes", 0)
            payload -= b.get("payload_bytes", 0)
            compressed -= sum(n for m, n in b.get("ops", {}).items()
                              if m != "none")
        totals["wire"] += wire
        totals["payload"] += payload
        totals["compressed"] += compressed
    if totals["payload"] or comp.get("mode", "off") != "off":
        ratio = (totals["payload"] / totals["wire"]
                 if totals["wire"] else 0.0)
        lines.append("== compression ==")
        lines.append(
            f"mode {comp.get('mode', 'off')} "
            f"(min {_fmt_bytes(comp.get('min_bytes', 0))}); wire "
            f"{_fmt_bytes(totals['wire'])} for "
            f"{_fmt_bytes(totals['payload'])} payload "
            f"({ratio:.2f}x); compressed buckets {totals['compressed']}; "
            f"residuals {_fmt_bytes(comp.get('residual_bytes', 0))} over "
            f"{comp.get('residual_tensors', 0)} tensor(s)")

    # Two-level topology (docs/performance.md#two-level-topology); only
    # rendered when the job ran hierarchical, so flat-ring dumps stay
    # unchanged.  Byte/op counters diff in two-file mode; the shape and
    # threshold stay absolute.
    topo = snap.get("topology", {})
    if topo.get("hierarchical"):
        ops = dict(topo.get("cross_ops", {}))
        tbytes = dict(topo.get("bytes", {}))
        if base:
            b = base.get("topology", {})
            for a in ops:
                ops[a] -= b.get("cross_ops", {}).get(a, 0)
            for h in tbytes:
                tbytes[h] -= b.get("bytes", {}).get(h, 0)
        lines.append("== topology ==")
        lines.append(
            f"two-level, {topo.get('nodes', 1)} node(s) x "
            f"{topo.get('local_size', 1)} local; cross algo ring "
            f"{ops.get('ring', 0)} / tree {ops.get('tree', 0)} "
            f"(boundary {_fmt_bytes(topo.get('cross_algo_threshold', 0))}); "
            f"wire local {_fmt_bytes(tbytes.get('local', 0))}, cross "
            f"{_fmt_bytes(tbytes.get('cross', 0))}")

    # Control plane (docs/performance.md#control-plane-scaling); only
    # rendered when the job ran the coordinator tree or entered the
    # decentralized steady state, so plain star dumps stay unchanged.
    # Frame/cycle counters diff in two-file mode; the shape stays
    # absolute.
    ctrl = snap.get("control", {})
    steady = ctrl.get("steady", {})
    if ctrl.get("tree") or steady.get("entries") or steady.get("cycles"):
        frames = dict(ctrl.get("frames", {}))
        cycles = steady.get("cycles", 0)
        negotiated = ctrl.get("negotiated_ticks", 0)
        if base:
            b = base.get("control", {})
            for d in frames:
                frames[d] -= b.get("frames", {}).get(d, 0)
            cycles -= b.get("steady", {}).get("cycles", 0)
            negotiated -= b.get("negotiated_ticks", 0)
        lines.append("== control ==")
        lines.append(
            f"{'tree depth 2' if ctrl.get('tree') else 'star'}, "
            f"{ctrl.get('hosts', 1)} host(s), fan-in "
            f"{ctrl.get('children', 0)}; steady "
            f"{'ACTIVE' if steady.get('active') else 'off'} "
            f"(pattern {steady.get('pattern_len', 0)}, threshold "
            f"{steady.get('threshold', 0)}), cycles {cycles} steady / "
            f"{negotiated} negotiated, entries "
            f"{steady.get('entries', 0)} / exits {steady.get('exits', 0)}; "
            f"frames sent {frames.get('sent', 0)}, received "
            f"{frames.get('received', 0)}")

    # Heartbeat failure detector (docs/fault-tolerance.md
    # #failure-detection); only rendered when the detector is armed
    # (HVD_TPU_HEARTBEAT_MS > 0), so detector-off dumps stay unchanged.
    live = snap.get("liveness", {})
    if live.get("interval_ms"):
        frames = live.get("frames", {})
        peers = live.get("peers", {})
        worst = max((p.get("misses", 0) for p in peers.values()),
                    default=0)
        lines.append("== liveness ==")
        lines.append(
            f"heartbeat every {live.get('interval_ms', 0)} ms, miss limit "
            f"{live.get('miss_limit', 0)}; beacons sent "
            f"{frames.get('sent', 0)}, received "
            f"{frames.get('received', 0)}; {len(peers)} peer(s), worst "
            f"miss streak {worst}; miss events "
            f"{live.get('miss_events', 0)}, evictions "
            f"{live.get('evictions', 0)}; clock fan-in "
            f"{live.get('clock_fanin', 0)}")

    # Anomaly verdicts (docs/metrics.md#anomalies); only rendered when
    # the detector saw something (or is explicitly disabled), so clean
    # dumps stay unchanged.  Full per-link detail lives behind --links.
    anomalies = snap.get("anomalies", {})
    verdicts = {k: v for k, v in anomalies.get("verdicts", {}).items()
                if v}
    if base:
        for k, v in (base or {}).get("anomalies", {}).get(
                "verdicts", {}).items():
            if k in verdicts:
                verdicts[k] -= v
        verdicts = {k: v for k, v in verdicts.items() if v}
    if verdicts:
        lines.append("== anomalies ==")
        lines.append(
            "verdicts " + ", ".join(f"{k}x{v}" for k, v in
                                    sorted(verdicts.items()))
            + f" (sigma {anomalies.get('sigma', 0)})")
        for e in anomalies.get("log", [])[-4:]:
            subject = f"({e.get('subject')})" if e.get("subject") else ""
            lines.append(f"  {e.get('kind')}{subject}: "
                         f"{e.get('detail', '')} "
                         f"[{e.get('age_us', 0) / 1e6:.1f}s ago]")

    # Point-to-point plane (docs/pipeline.md#observability); only
    # rendered when the rank moved p2p traffic, so pure data-parallel
    # dumps stay unchanged.  Counters diff in two-file mode; the
    # unmatched / open-channel gauges stay absolute — the B dump's live
    # state.
    p2p = dict(snap.get("p2p", {}))
    pbytes = dict(p2p.get("bytes", {}))
    if base:
        b = base.get("p2p", {})
        for k in ("sends", "recvs", "matched", "group_ops"):
            p2p[k] = p2p.get(k, 0) - b.get(k, 0)
        for d in pbytes:
            pbytes[d] = pbytes.get(d, 0) - b.get("bytes", {}).get(d, 0)
    if p2p.get("sends") or p2p.get("recvs") or p2p.get("group_ops"):
        lines.append("== p2p ==")
        lines.append(
            f"sends {p2p.get('sends', 0)} "
            f"({_fmt_bytes(pbytes.get('out', 0))}), recvs "
            f"{p2p.get('recvs', 0)} ({_fmt_bytes(pbytes.get('in', 0))}); "
            f"matched {p2p.get('matched', 0)}, unmatched in flight "
            f"{p2p.get('unmatched', 0)}; stage-group ops "
            f"{p2p.get('group_ops', 0)}; dedicated channels "
            f"{p2p.get('channels', 0)}")

    # Elastic membership (docs/fault-tolerance.md#elastic-membership);
    # only rendered once the job reshaped, so pre-elastic dumps stay
    # unchanged.
    member = snap.get("membership", {})
    if member.get("epoch") or member.get("reshapes"):
        lines.append("== membership ==")
        lost = member.get("ranks_lost", [])
        joined = member.get("ranks_joined", [])
        lines.append(
            f"epoch {member.get('epoch', 0)}, size {member.get('size', 0)}, "
            f"reshapes {member.get('reshapes', 0)}; lost "
            + (", ".join(f"rank{r}" for r in lost) or "none")
            + "; joined "
            + (", ".join(f"rank{r}" for r in joined) or "none"))

    # State plane (docs/fault-tolerance.md#state-plane); only rendered
    # once a rank armed it (or a checkpoint moved), so pre-state dumps
    # stay unchanged.  Counters diff in two-file mode; the last-step /
    # overlap gauges stay absolute — the B dump's live state.
    st = dict(snap.get("state", {}))
    if st.get("armed") or st.get("snapshots") \
            or any(st.get("ckpt", {}).values()):
        counters = ("snapshots", "snapshot_bytes", "peer_copies_sent",
                    "peer_copies_received", "restores", "peer_restores",
                    "root_broadcast_fallbacks")
        if base:
            b = base.get("state", {})
            for k in counters:
                st[k] = st.get(k, 0) - b.get(k, 0)
        ck = dict(st.get("ckpt", {}))
        if base:
            bck = base.get("state", {}).get("ckpt", {})
            ck = {k: v - bck.get(k, 0) for k, v in ck.items()}
        lines.append("== state plane ==")
        lines.append(
            f"snapshots {st.get('snapshots', 0)} "
            f"({_fmt_bytes(st.get('snapshot_bytes', 0))}, last step "
            f"{st.get('last_snapshot_step', -1)}, overlap "
            f"{100.0 * st.get('overlap_ratio', 1.0):.1f}%); peer copies "
            f"sent {st.get('peer_copies_sent', 0)} / received "
            f"{st.get('peer_copies_received', 0)} (peer last step "
            f"{st.get('peer_last_step', -1)})")
        lines.append(
            f"restores {st.get('restores', 0)} "
            f"(peer {st.get('peer_restores', 0)}, root-broadcast "
            f"fallbacks {st.get('root_broadcast_fallbacks', 0)}); ckpt "
            f"saves sharded {ck.get('sharded_saves', 0)} / legacy "
            f"{ck.get('legacy_saves', 0)}, loads {ck.get('loads', 0)}, "
            f"pruned {ck.get('pruned', 0)}")

    # Serving plane (docs/inference.md); only rendered when the rank
    # served traffic, so training dumps stay unchanged.  Per-tenant
    # detail lives behind --tenants.  Counters diff in two-file mode
    # like every other section; gauges (queue, kv blocks, occupancy)
    # stay absolute — the B dump's live state.
    serving = dict(snap.get("serving", {}))
    if base:
        base_serving = base.get("serving", {})
        for k in ("requests", "admitted", "rejected", "retired", "failed",
                  "preempted", "reformed", "steps"):
            serving[k] = serving.get(k, 0) - base_serving.get(k, 0)
    if serving.get("requests") or serving.get("steps"):
        lines.append("== serving ==")
        lines.append(
            f"requests {serving.get('requests', 0)} "
            f"(admitted {serving.get('admitted', 0)}, "
            f"rejected {serving.get('rejected', 0)}, "
            f"retired {serving.get('retired', 0)}, "
            f"failed {serving.get('failed', 0)}, "
            f"preempted {serving.get('preempted', 0)})")
        lines.append(
            f"steps {serving.get('steps', 0)}, occupancy "
            f"{100.0 * serving.get('occupancy', 0.0):.1f}%, queue "
            f"{serving.get('queue_depth', 0)}, kv blocks "
            f"{serving.get('kv_blocks_in_use', 0)}/"
            f"{serving.get('kv_blocks_total', 0)}, reshapes ridden "
            f"{serving.get('reformed', 0)}")

    # Online autotuning (docs/performance.md#autotuning); only rendered
    # when the job opted in, so pre-autotune dumps stay unchanged.
    tune = snap.get("autotune", {})
    if tune.get("enabled"):
        lines.append("== autotune ==")
        state = "frozen" if tune.get("frozen") else "searching"
        lines.append(
            f"{state} after {tune.get('windows', 0)} window(s): "
            f"fusion {_fmt_bytes(tune.get('fusion_threshold', 0))}, "
            f"cycle {tune.get('cycle_time_ms', 0.0):g} ms, "
            f"best score {tune.get('best_score', 0.0):.0f}")

    lines.append("== histograms ==")
    lines.append(f"{'name':<18}{'count':>8}{'mean':>10}{'p50':>10}"
                 f"{'p99':>10}")
    for name, hist in snap["histograms"].items():
        if base:
            b = base["histograms"][name]
            hist = {"buckets": hist["buckets"],
                    "counts": [x - y for x, y in zip(hist["counts"],
                                                     b["counts"])],
                    "sum": hist["sum"] - b["sum"],
                    "count": hist["count"] - b["count"]}
        mean = hist["sum"] / hist["count"] if hist["count"] else None
        fmt = _fmt_sec if name.endswith("_sec") else (
            lambda v: "-" if v is None else f"{v:.2f}")
        lines.append(f"{name:<18}{hist['count']:>8}{fmt(mean):>10}"
                     f"{fmt(quantile(hist, 0.5)):>10}"
                     f"{fmt(quantile(hist, 0.99)):>10}")
    return "\n".join(lines)


def render_tenants(snap: dict) -> str:
    """The --tenants view: per-tenant request/token/reject breakdown from
    the serving section (docs/inference.md; use rank 0's dump — the
    scheduler lives there)."""
    lines = ["== tenants (serving plane, rank-0 scheduler view) =="]
    tenants = snap.get("serving", {}).get("tenants", {})
    if not tenants:
        lines.append("(no serving traffic recorded — not a serving rank, "
                     "or not the scheduler's dump; use rank 0's file)")
        return "\n".join(lines)
    lines.append(f"{'tenant':<16}{'admitted':>9}{'rejected':>9}"
                 f"{'retired':>8}{'failed':>7}{'prompt':>8}{'gen':>8}")
    for name in sorted(tenants,
                       key=lambda t: -tenants[t].get("admitted", 0)):
        e = tenants[name]
        lines.append(f"{name[:15]:<16}{e.get('admitted', 0):>9}"
                     f"{e.get('rejected', 0):>9}{e.get('retired', 0):>8}"
                     f"{e.get('failed', 0):>7}"
                     f"{e.get('prompt_tokens', 0):>8}"
                     f"{e.get('generated_tokens', 0):>8}")
    total_rej = sum(e.get("rejected", 0) for e in tenants.values())
    total_req = sum(e.get("requests", 0) for e in tenants.values())
    lines.append(f"shed rate: {total_rej}/{total_req} requests rejected "
                 f"({100.0 * total_rej / max(total_req, 1):.1f}%)")
    return "\n".join(lines)


def render_stragglers(snap: dict) -> str:
    """The --stragglers view: ranks by last_to_announce share plus the
    announce-skew histogram's estimated p50/p99."""
    lines = ["== stragglers (last_to_announce share, coordinator view) =="]
    counts = {int(k): v for k, v in
              snap.get("skew", {}).get("last_to_announce", {}).items()}
    total = sum(counts.values())
    if not total:
        lines.append("(no negotiations recorded — single rank, or not the "
                     "coordinator's dump; use rank 0's file)")
    else:
        lines.append(f"{'rank':<6}{'last':>8}{'share':>9}")
        for r, n in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            lines.append(f"{r:<6}{n:>8}{100.0 * n / total:>8.1f}%")
        worst = max(counts, key=counts.get)
        lines.append(f"dominant straggler: rank {worst} "
                     f"({100.0 * counts[worst] / total:.1f}% of "
                     f"{total} negotiations)")
    hist = snap.get("histograms", {}).get("announce_skew_sec")
    if hist and hist.get("count"):
        lines.append(f"announce skew: n={hist['count']} "
                     f"p50={_fmt_sec(quantile(hist, 0.5))} "
                     f"p99={_fmt_sec(quantile(hist, 0.99))}")
    else:
        lines.append("announce skew: (empty histogram)")
    return "\n".join(lines)


def render_links(snap: dict) -> str:
    """The --links view: one row per peer link — bytes each way, timed
    sends with mean/p99 latency estimated from the fixed buckets,
    heartbeat-echo RTT, and transport backpressure
    (docs/metrics.md#links)."""
    lines = ["== links (per-peer transport telemetry) =="]
    links = snap.get("links", {})
    peers = links.get("peers", {})
    if not links.get("enabled", False):
        lines.append("(link telemetry disabled — HVD_TPU_LINK_STATS=0, "
                     "or a pre-telemetry dump)")
        return "\n".join(lines)
    if not peers:
        lines.append("(no links — single rank)")
        return "\n".join(lines)
    # Bucket bounds mirror LINK_SEND_BUCKETS_US (common/metrics.py) so
    # the tool stays importable without the package on scrape hosts.
    bounds = [50, 100, 250, 500, 1000, 2500, 5000, 10000, 50000]
    lines.append(f"{'peer':<6}{'out':>10}{'in':>10}{'sends':>8}"
                 f"{'mean':>9}{'p99':>9}{'rtt':>9}{'stalls':>8}")
    for r in sorted(peers, key=int):
        v = peers[r]
        count = v.get("send_us_count", 0)
        mean = (f"{v.get('send_us_sum', 0) / count:.0f}us"
                if count else "-")
        hist = {"buckets": bounds,
                "counts": v.get("send_us_buckets", [])[:len(bounds)],
                "count": count}
        p99 = quantile(hist, 0.99) if count else None
        rtt = (f"{v.get('rtt_ewma_us', 0)}us"
               if v.get("rtt_samples", 0) else "-")
        stalls = v.get("stalls", 0) + v.get("short_writes", 0)
        lines.append(
            f"{r:<6}{_fmt_bytes(v.get('bytes_out', 0)):>10}"
            f"{_fmt_bytes(v.get('bytes_in', 0)):>10}"
            f"{v.get('sends', 0):>8}{mean:>9}"
            f"{'-' if p99 is None else f'{p99:.0f}us':>9}"
            f"{rtt:>9}{stalls:>8}")
    verdicts = snap.get("anomalies", {}).get("verdicts", {})
    slow = [e for e in snap.get("anomalies", {}).get("log", [])
            if e.get("kind") == "slow_link"]
    if verdicts.get("slow_link"):
        lines.append("slow-link verdicts: " + "; ".join(
            f"{e.get('subject')} ({e.get('detail', '')})"
            for e in slow[-4:]))
    return "\n".join(lines)


def main(argv) -> int:
    argv = list(argv)
    stragglers = "--stragglers" in argv
    if stragglers:
        argv.remove("--stragglers")
    tenants = "--tenants" in argv
    if tenants:
        argv.remove("--tenants")
    links = "--links" in argv
    if links:
        argv.remove("--links")
    if len(argv) not in (2, 3) or argv[1] in ("-h", "--help"):
        print(__doc__)
        return 2
    if (stragglers or tenants or links) and len(argv) != 2:
        print("--stragglers/--tenants/--links take a single dump",
              file=sys.stderr)
        return 2
    with open(argv[1]) as f:
        a = json.load(f)
    if stragglers:
        print(render_stragglers(a))
        return 0
    if tenants:
        print(render_tenants(a))
        return 0
    if links:
        print(render_links(a))
        return 0
    if len(argv) == 3:
        with open(argv[2]) as f:
            b = json.load(f)
        print(f"A: {argv[1]}\nB: {argv[2]}")
        print(render(b, base=a))
    else:
        print(render(a))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
