#!/usr/bin/env python
"""Microbenchmark each distinct ResNet-101 conv (fwd, bwd-data, bwd-filter).

Times XLA's lowering of every conv shape in the headline model at the
benchmark batch size and reports achieved TFLOP/s vs the chip's practical
matmul peak — the shape-by-shape evidence behind conv-optimisation
decisions (docs/benchmarks.md round-4 log).

Usage: python tools/conv_microbench.py [--batch 64] [--iters 20]
"""

from __future__ import annotations

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# (name, H, Cin, Cout, k, stride, count) — ResNet-101 v1.5 @224, after the
# space-to-depth stem.  count = occurrences in the network.
SHAPES = [
    ("stem 4x4x12->64 /1@112", 112, 12, 64, 4, 1, 1),
    ("s1 1x1 64->64", 56, 64, 64, 1, 1, 2),
    ("s1 1x1 256->64", 56, 256, 64, 1, 1, 2),
    ("s1 3x3 64->64", 56, 64, 64, 3, 1, 3),
    ("s1 1x1 64->256", 56, 64, 256, 1, 1, 3),
    ("s1 proj 1x1 64->256", 56, 64, 256, 1, 1, 1),
    ("s2 1x1 256->128", 56, 256, 128, 1, 1, 1),
    ("s2 3x3 128->128 /2", 56, 128, 128, 3, 2, 1),
    ("s2 1x1 512->128", 28, 512, 128, 1, 1, 3),
    ("s2 3x3 128->128", 28, 128, 128, 3, 1, 3),
    ("s2 1x1 128->512", 28, 128, 512, 1, 1, 4),
    ("s2 proj 1x1 256->512 /2", 56, 256, 512, 1, 2, 1),
    ("s3 1x1 512->256", 28, 512, 256, 1, 1, 1),
    ("s3 3x3 256->256 /2", 28, 256, 256, 3, 2, 1),
    ("s3 1x1 1024->256", 14, 1024, 256, 1, 1, 22),
    ("s3 3x3 256->256", 14, 256, 256, 3, 1, 22),
    ("s3 1x1 256->1024", 14, 256, 1024, 1, 1, 23),
    ("s3 proj 1x1 512->1024 /2", 28, 512, 1024, 1, 2, 1),
    ("s4 1x1 1024->512", 14, 1024, 512, 1, 1, 1),
    ("s4 3x3 512->512 /2", 14, 512, 512, 3, 2, 1),
    ("s4 1x1 2048->512", 7, 2048, 512, 1, 1, 2),
    ("s4 3x3 512->512", 7, 512, 512, 3, 1, 2),
    ("s4 1x1 512->2048", 7, 512, 2048, 1, 1, 3),
    ("s4 proj 1x1 1024->2048 /2", 14, 1024, 2048, 1, 2, 1),
]

DN = ("NHWC", "HWIO", "NHWC")


def timed(fn, *args, iters):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    # One scalar fetch drains the chain (tunnel-safe, the bench.py pattern).
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--peak", type=float, default=116.0,
                    help="practical bf16 TFLOP/s of this chip")
    args = ap.parse_args()
    B = args.batch

    total = {"fwd": 0.0, "dx": 0.0, "dw": 0.0}
    ideal = {"fwd": 0.0, "dx": 0.0, "dw": 0.0}
    print(f"{'shape':<28}{'dir':>5}{'ms':>9}{'TF/s':>8}{'%peak':>7}")
    for name, H, cin, cout, k, stride, count in SHAPES:
        Ho = H // stride
        x = jnp.asarray(np.random.RandomState(0).randn(B, H, H, cin),
                        jnp.bfloat16)
        w = jnp.asarray(np.random.RandomState(1).randn(k, k, cin, cout),
                        jnp.bfloat16)
        pad = "SAME"

        @jax.jit
        def fwd(x, w):
            return lax.conv_general_dilated(x, w, (stride, stride), pad,
                                            dimension_numbers=DN)

        def loss(x, w):
            return jnp.sum(fwd(x, w).astype(jnp.float32))

        dx_fn = jax.jit(jax.grad(loss, argnums=0))
        dw_fn = jax.jit(jax.grad(loss, argnums=1))

        flops = 2 * B * Ho * Ho * k * k * cin * cout
        for tag, fn in (("fwd", fwd), ("dx", dx_fn), ("dw", dw_fn)):
            dt = timed(fn, x, w, iters=args.iters)
            tf = flops / dt / 1e12
            total[tag] += dt * count * 1e3
            ideal[tag] += flops * count / (args.peak * 1e12) * 1e3
            print(f"{name:<28}{tag:>5}{dt * 1e3:>9.3f}{tf:>8.1f}"
                  f"{100 * tf / args.peak:>6.1f}%")
    print("\nnetwork totals (shape x count), ms and vs practical peak:")
    for tag in ("fwd", "dx", "dw"):
        print(f"  {tag}: {total[tag]:8.2f} ms   ideal {ideal[tag]:6.2f} ms "
              f" -> {100 * ideal[tag] / max(total[tag], 1e-9):.0f}% eff")


if __name__ == "__main__":
    main()
