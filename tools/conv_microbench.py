#!/usr/bin/env python
"""Microbenchmark each distinct ResNet-101 conv (fwd, bwd-data, bwd-filter).

Times XLA's lowering of every conv shape in the headline model at the
benchmark batch size and reports achieved TFLOP/s vs the chip's practical
matmul peak — the shape-by-shape evidence behind conv-optimisation
decisions (docs/benchmarks.md round-4 log).

Through the axon tunnel a single dispatch costs milliseconds, so each
measurement runs K convolutions inside ONE jitted lax.scan (over K
distinct weight buffers, so XLA cannot CSE them) and fetches one scalar;
per-conv time is the scan time over K with the empty-scan overhead
subtracted.

Usage: python tools/conv_microbench.py [--batch 64] [--k 24]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# (name, H, Cin, Cout, k, stride, count) — ResNet-101 v1.5 @224, after the
# space-to-depth stem.  count = occurrences in the network.
SHAPES = [
    ("stem 4x4x12->64 /1@112", 112, 12, 64, 4, 1, 1),
    ("s1 1x1 64->64", 56, 64, 64, 1, 1, 2),
    ("s1 1x1 256->64", 56, 256, 64, 1, 1, 2),
    ("s1 3x3 64->64", 56, 64, 64, 3, 1, 3),
    ("s1 1x1 64->256", 56, 64, 256, 1, 1, 4),
    ("s2 1x1 256->128", 56, 256, 128, 1, 1, 1),
    ("s2 3x3 128->128 /2", 56, 128, 128, 3, 2, 1),
    ("s2 1x1 512->128", 28, 512, 128, 1, 1, 3),
    ("s2 3x3 128->128", 28, 128, 128, 3, 1, 3),
    ("s2 1x1 128->512", 28, 128, 512, 1, 1, 4),
    ("s2 proj 1x1 256->512 /2", 56, 256, 512, 1, 2, 1),
    ("s3 1x1 512->256", 28, 512, 256, 1, 1, 1),
    ("s3 3x3 256->256 /2", 28, 256, 256, 3, 2, 1),
    ("s3 1x1 1024->256", 14, 1024, 256, 1, 1, 22),
    ("s3 3x3 256->256", 14, 256, 256, 3, 1, 22),
    ("s3 1x1 256->1024", 14, 256, 1024, 1, 1, 23),
    ("s3 proj 1x1 512->1024 /2", 28, 512, 1024, 1, 2, 1),
    ("s4 1x1 1024->512", 14, 1024, 512, 1, 1, 1),
    ("s4 3x3 512->512 /2", 14, 512, 512, 3, 2, 1),
    ("s4 1x1 2048->512", 7, 2048, 512, 1, 1, 2),
    ("s4 3x3 512->512", 7, 512, 512, 3, 1, 2),
    ("s4 1x1 512->2048", 7, 512, 2048, 1, 1, 3),
    ("s4 proj 1x1 1024->2048 /2", 14, 1024, 2048, 1, 2, 1),
]

DN = ("NHWC", "HWIO", "NHWC")


def scan_time(make_scalar, pool, iters, reps=3):
    """Median wall time of one jitted scan running `make_scalar` `iters`
    times (one dispatch, one scalar fetch).  Weights cycle through a
    small pool by dynamic index — distinct enough that XLA cannot hoist
    the conv out of the loop, small enough to bound HBM."""

    @jax.jit
    def run(pool):
        def body(acc, idx):
            return acc + make_scalar(pool[idx]), None

        acc, _ = lax.scan(body, jnp.float32(0),
                          jnp.arange(iters) % pool.shape[0])
        return acc

    float(run(pool))  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(run(pool))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def per_iter_time(make_scalar, pool, iters):
    """Two-point measurement: (T(3N) - T(N)) / 2N cancels the constant
    dispatch + fetch overhead (~100 ms through the tunnel) exactly,
    instead of subtracting a separately measured (noisy) baseline."""
    t1 = scan_time(make_scalar, pool, iters)
    t3 = scan_time(make_scalar, pool, 3 * iters)
    return max(t3 - t1, 1e-12) / (2 * iters)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--k", type=int, default=24, help="convs per dispatch")
    ap.add_argument("--peak", type=float, default=116.0,
                    help="practical bf16 TFLOP/s of this chip")
    ap.add_argument("--only", default="",
                    help="substring filter on shape names (comma-separated)")
    args = ap.parse_args()
    B, K = args.batch, args.k
    shapes = SHAPES
    if args.only:
        keys = [s.strip() for s in args.only.split(",") if s.strip()]
        shapes = [s for s in SHAPES if any(k in s[0] for k in keys)]

    # Overhead of an empty scan + dispatch + fetch (the tunnel RTT is
    # ~100 ms), subtracted from every sample; iteration counts below are
    # sized so the conv signal is several times this noise floor.
    base = scan_time(lambda wi: jnp.sum(wi),
                     jnp.zeros((4, 8), jnp.float32), 16)

    total = {"fwd": 0.0, "dx": 0.0, "dw": 0.0}
    ideal = {"fwd": 0.0, "dx": 0.0, "dw": 0.0}
    print(f"dispatch+empty-scan overhead: {base * 1e3:.2f} ms")
    print(f"{'shape':<27}{'dir':>5}{'iters':>6}{'us':>9}{'TF/s':>8}"
          f"{'%peak':>7}")
    for name, H, cin, cout, k, stride, count in shapes:
        Ho = (H + stride - 1) // stride
        x = jnp.asarray(np.random.RandomState(0).randn(B, H, H, cin),
                        jnp.bfloat16)
        flops_one = 2 * B * Ho * Ho * k * k * cin * cout
        # Enough iterations that at ~200 TF/s the N-vs-3N delta is
        # several times the run-to-run RTT noise; pool bounded to ~64 MB.
        iters = int(min(2048, max(
            32, 2 * base / (flops_one / 200e12))))
        pool_n = max(1, min(iters, (64 << 20) // (2 * k * k * cin * cout)))
        ws = jnp.asarray(
            np.random.RandomState(1).randn(pool_n, k, k, cin, cout),
            jnp.bfloat16)

        def conv(x, w):
            return lax.conv_general_dilated(x, w, (stride, stride), "SAME",
                                            dimension_numbers=DN)

        # sum(y*y), NOT sum(y): a linear consumer lets XLA's algebraic
        # simplifier collapse reduce(conv) into a tiny matmul (and makes
        # d/dw independent of w, so the whole grad hoists out of the
        # timing loop) — both were observed, reporting >nominal-peak
        # numbers.  The square also gives the backward a realistic
        # activation-dependent cotangent.
        def fwd_scalar(wi):
            y = conv(x, wi).astype(jnp.float32)
            return jnp.sum(y * y)

        def dx_scalar(wi):
            g = jax.grad(lambda xx: fwd_scalar_x(xx, wi))(x)
            return jnp.sum(g.astype(jnp.float32) ** 2)

        def fwd_scalar_x(xx, wi):
            y = conv(xx, wi).astype(jnp.float32)
            return jnp.sum(y * y)

        def dw_scalar(wi):
            g = jax.grad(lambda w_: fwd_scalar_x(x, w_))(wi)
            return jnp.sum(g.astype(jnp.float32) ** 2)

        flops = flops_one
        # grad-of-sum-of-squares times include the forward conv recompute;
        # subtract the measured forward to isolate the backward conv.
        fwd_dt = None
        for tag, fn in (("fwd", fwd_scalar), ("dx", dx_scalar),
                        ("dw", dw_scalar)):
            dt = per_iter_time(fn, ws, iters)
            if tag == "fwd":
                fwd_dt = dt
            else:
                dt = max(dt - fwd_dt, 1e-9)
            tf = flops / dt / 1e12
            total[tag] += dt * count * 1e3
            ideal[tag] += flops * count / (args.peak * 1e12) * 1e3
            print(f"{name:<27}{tag:>5}{iters:>6}{dt * 1e6:>9.1f}{tf:>8.1f}"
                  f"{100 * tf / args.peak:>6.1f}%")
    print("\nnetwork totals (shape x count), ms and vs practical peak:")
    for tag in ("fwd", "dx", "dw"):
        print(f"  {tag}: {total[tag]:8.2f} ms   ideal {ideal[tag]:6.2f} ms "
              f" -> {100 * ideal[tag] / max(total[tag], 1e-9):.0f}% eff")


if __name__ == "__main__":
    main()
