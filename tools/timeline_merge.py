#!/usr/bin/env python
"""Merge per-rank Horovod-TPU timeline files into one Perfetto/Chrome
trace and report stragglers (docs/timeline.md).

Per-rank files come from the directory / ``%d`` forms of
``HOROVOD_TIMELINE`` or from ``hvdrun --timeline DIR``.  Each rank's
events become one process group in the merged trace (pid = rank, one
thread row per tensor/span), with the coordinator's NTP-style clock
offsets — the ``hvd_clock_sync`` metadata every rank records at init —
subtracted so all timestamps land on rank 0's clock.

    python tools/timeline_merge.py /tmp/tl -o merged.json
    python tools/timeline_merge.py /tmp/tl/rank0.json /tmp/tl/rank1.json

The straggler report (stdout; ``--no-report`` to skip) reads rank 0's
NEGOTIATE rows: per-tensor announce order (RANK_READY instants), which
rank announced last and by how many µs, and p50/p99 of the first->last
skew distribution.  Crash-truncated files are salvaged by dropping the
torn tail, so post-mortem traces from aborted jobs merge too.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import Counter
from typing import List, Optional, Tuple


def load_events(path: str) -> list:
    """Parse one timeline file.  The writer streams events with trailing
    commas and no closing ``]`` (Chrome tolerates it); normalize, and on a
    torn tail (a rank crashed mid-write) drop lines until it parses."""
    with open(path) as f:
        raw = f.read()
    lines = raw.rstrip().splitlines()
    while lines:
        body = "\n".join(lines).rstrip().rstrip(",")
        if body in ("", "["):
            return []
        try:
            return json.loads(body + "]")
        except json.JSONDecodeError:
            lines.pop()
    return []


def trace_meta(events: list) -> Tuple[Optional[int], int, int]:
    """(rank, clock_offset_us, clock_rtt_us) from a file's metadata
    events; rank None / offset 0 when absent (pre-clock-sync traces)."""
    rank, offset, rtt = None, 0, 0
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "hvd_rank":
            rank = int(e.get("args", {}).get("rank", 0))
        elif e.get("name") == "hvd_clock_sync":
            args = e.get("args", {})
            offset = int(args.get("offset_us", 0))
            rtt = int(args.get("rtt_us", 0))
    return rank, offset, rtt


_RANK_FILE_RE = re.compile(r"^rank(\d+)(?:\.e(\d+))?\.json$")


def resolve_inputs(paths: List[str]) -> List[str]:
    """Expand a single directory argument to its trace files.  A job run
    under ``--max-restarts`` leaves one file per (rank, restart epoch)
    — ``rank<N>.json``, ``rank<N>.e1.json``, ... — so the directory form
    keeps only the LATEST epoch per rank (merging two attempts of the
    same rank into one trace would interleave unrelated runs); pass
    explicit files to merge an earlier attempt's post-mortem traces."""
    if len(paths) == 1 and os.path.isdir(paths[0]):
        per_rank = {}
        others = []
        for name in sorted(os.listdir(paths[0])):
            if not name.endswith(".json"):
                continue
            m = _RANK_FILE_RE.match(name)
            if not m:
                others.append(name)
                continue
            rank, epoch = int(m.group(1)), int(m.group(2) or 0)
            kept = per_rank.get(rank)
            if kept is None or epoch > kept[0]:
                per_rank[rank] = (epoch, name)
        skipped = sum(
            1 for name in sorted(os.listdir(paths[0]))
            if name.endswith(".json") and _RANK_FILE_RE.match(name)
            and name not in {v[1] for v in per_rank.values()})
        if skipped:
            print(f"timeline_merge: note: {skipped} earlier-epoch file(s) "
                  f"in {paths[0]} skipped (pass them explicitly to merge "
                  f"a previous attempt)")
        files = [os.path.join(paths[0], v[1])
                 for _, v in sorted(per_rank.items())]
        files += [os.path.join(paths[0], n) for n in others]
        if not files:
            raise SystemExit(f"timeline_merge: no .json files in {paths[0]}")
        return files
    return paths


def merge(files: List[str]):
    """Fuse per-rank files: one process group per rank, offsets applied.
    Returns (merged_events, per_rank_events keyed by rank)."""
    merged = []
    by_rank = {}
    for path in files:
        events = load_events(path)
        rank, offset, _ = trace_meta(events)
        if rank is None:
            m = re.search(r"rank(\d+)", os.path.basename(path))
            rank = int(m.group(1)) if m else len(by_rank)
        by_rank[rank] = events
        merged.append({"name": "process_name", "ph": "M", "ts": 0,
                       "pid": rank, "args": {"name": f"rank {rank}"}})
        for e in events:
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    # Tensor-row label becomes a thread name inside this
                    # rank's process group.
                    merged.append({"name": "thread_name", "ph": "M",
                                   "ts": 0, "pid": rank, "tid": e["pid"],
                                   "args": dict(e.get("args", {}))})
                continue  # hvd_rank / hvd_clock_sync: consumed above
            out = dict(e)
            out["pid"] = rank
            out["tid"] = e.get("pid", 0)
            out["ts"] = int(e.get("ts", 0)) - offset
            merged.append(out)
    # Rebase so the earliest event sits at ts 0 (offset-corrected worker
    # events may precede rank 0's epoch), then order by time.
    timed = [e["ts"] for e in merged if e.get("ph") != "M"]
    base = min(timed) if timed else 0
    for e in merged:
        if e.get("ph") != "M":
            e["ts"] -= base
    merged.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0)))
    return merged, by_rank


def negotiations(rank0_events: list) -> List[Tuple[str, int, int, list]]:
    """Per-negotiation (tensor, last_rank, skew_us, announce_order) from
    the coordinator's NEGOTIATE rows: the RANK_READY instants between a
    NEGOTIATE B and its E carry each rank's announce, in order."""
    pid_names = {e["pid"]: e.get("args", {}).get("name", "")
                 for e in rank0_events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    open_neg = {}
    out = []
    for e in rank0_events:
        ph, pid = e.get("ph"), e.get("pid")
        if ph == "B" and e.get("name") == "NEGOTIATE":
            open_neg[pid] = []
        elif (ph == "i" and e.get("name") == "RANK_READY"
              and pid in open_neg):
            open_neg[pid].append((int(e.get("ts", 0)),
                                  e.get("args", {}).get("rank")))
        elif ph == "E" and e.get("name") == "NEGOTIATE" and pid in open_neg:
            readies = open_neg.pop(pid)
            if readies:
                first_ts = readies[0][0]
                last_ts, last_rank = readies[-1]
                out.append((pid_names.get(pid, f"pid{pid}"), last_rank,
                            last_ts - first_ts, [r for _, r in readies]))
    return out


def _fmt_us(us: float) -> str:
    if us >= 1e6:
        return f"{us / 1e6:.2f}s"
    if us >= 1e3:
        return f"{us / 1e3:.1f}ms"
    return f"{us:.0f}us"


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return float(sorted_vals[idx])


def render_report(negs: List[Tuple[str, int, int, list]],
                  top_tensors: int = 10) -> str:
    lines = ["== straggler report (rank-0 coordinator announce order) =="]
    if not negs:
        lines.append("(no NEGOTIATE rows found — was a rank-0/coordinator "
                     "trace among the inputs?)")
        return "\n".join(lines)
    lines.append(f"negotiations: {len(negs)}")
    last_counts = Counter(last for _, last, _, _ in negs
                          if last is not None)
    total = sum(last_counts.values()) or 1
    lines.append(f"{'rank':<6}{'last_count':>12}{'share':>9}")
    ranked = last_counts.most_common()
    for rank, n in ranked:
        lines.append(f"{rank:<6}{n:>12}{100.0 * n / total:>8.1f}%")
    if ranked:
        rank, n = ranked[0]
        lines.append(f"dominant straggler: rank {rank} "
                     f"({100.0 * n / total:.1f}% of last announces)")
    skews = sorted(skew for _, _, skew, _ in negs)
    lines.append(f"announce skew: p50={_fmt_us(_pct(skews, 0.5))} "
                 f"p99={_fmt_us(_pct(skews, 0.99))} "
                 f"max={_fmt_us(skews[-1])}")
    lines.append(f"worst tensors (top {min(top_tensors, len(negs))} by "
                 f"skew):")
    for name, last, skew, order in sorted(
            negs, key=lambda t: -t[2])[:top_tensors]:
        lines.append(f"  {name}: last=rank {last}, skew={_fmt_us(skew)}, "
                     f"announce order {order}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="timeline_merge",
        description="Merge per-rank HOROVOD_TIMELINE files into one "
                    "Perfetto/Chrome trace and report stragglers.")
    parser.add_argument("inputs", nargs="+",
                        help="a timeline directory, or the per-rank files")
    parser.add_argument("-o", "--output", default="timeline_merged.json",
                        help="merged trace path (default "
                             "timeline_merged.json)")
    parser.add_argument("--no-report", action="store_true",
                        help="skip the straggler report")
    args = parser.parse_args(argv)

    files = resolve_inputs(args.inputs)
    # Writing the merged file into the timeline directory must not feed
    # it back into a later merge.
    out_abs = os.path.abspath(args.output)
    files = [f for f in files if os.path.abspath(f) != out_abs]
    merged, by_rank = merge(files)
    with open(args.output, "w") as f:
        json.dump({"traceEvents": merged}, f)
        f.write("\n")
    print(f"timeline_merge: wrote {len(merged)} events from "
          f"{len(files)} rank file(s) to {args.output}")
    if not args.no_report:
        coordinator = by_rank.get(0) or next(iter(by_rank.values()), [])
        print(render_report(negotiations(coordinator)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
