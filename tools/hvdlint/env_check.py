"""Checker 2: HVD_TPU_* environment-variable coverage and defaults.

Every ``HVD_TPU_*`` read in Python or C++ is a public configuration
surface; ``docs/running.md`` is its canonical registry.  Three rules:

1. **coverage** — every env var the code reads must appear in
   docs/running.md (table or prose).  A reference's ``HOROVOD_<x>`` row
   also documents the winning ``HVD_TPU_<x>`` spelling, matching the
   aliasing in common/config.py.
2. **no stale rows** — every ``HVD_TPU_*`` name in the running.md table
   must be read somewhere, or the row documents a knob that no longer
   exists.
3. **default agreement** — the defaults must agree across planes
   (engine/cc/engine.h EngineOptions vs common/config.py Config: the C++
   default is what a caller bypassing Python init gets, so divergence is
   a live trap) and between the doc table's numeric default column and
   the dataclass default the code uses.

Dynamic reads through a prefix helper (serving/scheduler.py's
``_int("MAX_BATCH", ...)`` against ``f"HVD_TPU_SERVE_{name}"``) are
resolved by pairing the f-string prefix with the helper's literal first
arguments — new dynamic read sites must follow that idiom to stay
lintable (docs/contributing.md).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.hvdlint import (Violation, iter_py_files, read,
                           strip_cxx_comments, strip_py_comments)

RUNNING_MD = os.path.join("docs", "running.md")
CONFIG_PY = os.path.join("horovod_tpu", "common", "config.py")
ENGINE_H = os.path.join("horovod_tpu", "engine", "cc", "engine.h")
SCHEDULER_PY = os.path.join("horovod_tpu", "serving", "scheduler.py")
CC_DIR = os.path.join("horovod_tpu", "engine", "cc")
# Python trees whose env reads form the public surface (tests excluded:
# their HVD_TPU_TEST_* knobs configure the harness, not the framework).
PY_SCOPE = ["horovod_tpu", "tools", "bench.py"]

_READ_PATTERNS = (
    r"os\.environ\.get\(\s*\"(HVD_TPU_\w+)\"",
    r"os\.environ\[\s*\"(HVD_TPU_\w+)\"\s*\](?!\s*=[^=])",
    r"os\.getenv\(\s*\"(HVD_TPU_\w+)\"",
    r"os\.environ\.setdefault\(\s*\"(HVD_TPU_\w+)\"",
    r"_get\(\s*\"(HVD_TPU_\w+)\"",  # config.py new/old alias helper
    r"_env_int\(\s*\"(HVD_TPU_\w+)\"",  # basics.py endpoint-port helper
)
_DYNAMIC_PREFIX = re.compile(r"os\.environ\.get\(\s*f\"(HVD_TPU_\w+?)_\{")
_HELPER_DEF = re.compile(r"^([ \t]*)def (_\w+)\(", re.M)

# Plane-agreement map: Config field -> EngineOptions field.  Both sides
# are parsed textually so the check needs no imports (and works against
# the synthetic fixtures in tests/test_hvdlint.py).
PLANE_FIELDS = {
    "fusion_threshold": "fusion_threshold",
    "cycle_time_ms": "cycle_time_ms",
    "stall_warning_sec": "stall_warning_sec",
    "collective_timeout_sec": "collective_timeout_sec",
    "cache_capacity": "cache_capacity",
    "autotune_warmup": "autotune_warmup",
    "autotune_window": "autotune_window",
    "compression_min_bytes": "compression_min_bytes",
    "cross_algo_threshold": "cross_algo_threshold",
    "min_np": "min_size",
    # Control-plane topology / steady state (PR 13): a Python/C++ default
    # split here silently changes which protocol a bare-C++ caller runs.
    "coord_tree": "coord_tree",
    "steady_threshold": "steady_threshold",
    "steady_max_period": "steady_max_period",
}

# Doc-table default column -> dataclass default.  ("config", f) reads
# Config in common/config.py; ("serve", f) reads ServeConfig in
# serving/scheduler.py.
DOC_DEFAULTS: Dict[str, Tuple[str, str]] = {
    "HVD_TPU_FUSION_THRESHOLD": ("config", "fusion_threshold"),
    "HOROVOD_FUSION_THRESHOLD": ("config", "fusion_threshold"),
    "HVD_TPU_CYCLE_TIME_MS": ("config", "cycle_time_ms"),
    "HVD_TPU_STALL_WARNING_SEC": ("config", "stall_warning_sec"),
    "HVD_TPU_CACHE_CAPACITY": ("config", "cache_capacity"),
    "HVD_TPU_AUTOTUNE_WINDOW": ("config", "autotune_window"),
    "HVD_TPU_AUTOTUNE_WARMUP": ("config", "autotune_warmup"),
    "HVD_TPU_COMPRESSION_MIN_BYTES": ("config", "compression_min_bytes"),
    "HVD_TPU_CROSS_ALGO_THRESHOLD": ("config", "cross_algo_threshold"),
    "HVD_TPU_FLIGHT_EVENTS": ("config", "flight_events"),
    "HVD_TPU_MIN_NP": ("config", "min_np"),
    "HVD_TPU_RESTART_EPOCH": ("config", "restart_epoch"),
    "HVD_TPU_STEADY_THRESHOLD": ("config", "steady_threshold"),
    "HVD_TPU_STEADY_MAX_PERIOD": ("config", "steady_max_period"),
    "HVD_TPU_ANOMALY_SIGMA": ("config", "anomaly_sigma"),
    "HVD_TPU_ANOMALY_INTERVAL_MS": ("config", "anomaly_interval_ms"),
    # Transport knobs (docs/performance.md#transport).  HVD_TPU_SHM's
    # default is the string "auto" — the numeric comparison skips it, but
    # the entry keeps the registry exhaustive.
    "HVD_TPU_SHM": ("config", "shm"),
    "HVD_TPU_SHM_RING_BYTES": ("config", "shm_ring_bytes"),
    "HVD_TPU_SERVE_PORT": ("serve", "port"),
    "HVD_TPU_SERVE_MAX_BATCH": ("serve", "max_batch"),
    "HVD_TPU_SERVE_PREFILL_CHUNK": ("serve", "prefill_chunk"),
    "HVD_TPU_SERVE_BLOCK_TOKENS": ("serve", "block_tokens"),
    "HVD_TPU_SERVE_KV_BLOCKS": ("serve", "num_blocks"),
    "HVD_TPU_SERVE_MAX_BLOCKS_PER_SEQ": ("serve", "max_blocks_per_seq"),
    "HVD_TPU_SERVE_QUEUE": ("serve", "queue_limit"),
    "HVD_TPU_SERVE_TENANT_INFLIGHT": ("serve", "tenant_max_inflight"),
    "HVD_TPU_SERVE_RING_MIN_TOKENS": ("serve", "ring_min_tokens"),
    "HVD_TPU_SERVE_REQUEST_TIMEOUT_SEC": ("serve", "request_timeout_sec"),
    "HVD_TPU_SERVE_EOS": ("serve", "eos_id"),
    "HVD_TPU_SERVE_IDLE_SLEEP_SEC": ("serve", "idle_sleep_sec"),
}

_NUM_RE = re.compile(r"^-?[\d_]+(\.\d+)?$")
_EXPR_RE = re.compile(r"^[-+*\s().\d_]+$")


def _safe_eval(expr: str,
               names: Dict[str, float]) -> Optional[float]:
    """Evaluate a default expression: arithmetic over numbers,
    already-resolved constant names, and bool literals (Python
    ``True``/``False`` and C++ ``true``/``false`` normalize to 1/0 so
    flag defaults like ``coord_tree`` compare across planes); None for
    anything else (enum values, strings — out of scope for the numeric
    agreement check)."""
    expr = expr.strip()
    expr = re.sub(r"\b[Tt]rue\b", "1", expr)
    expr = re.sub(r"\b[Ff]alse\b", "0", expr)
    for name, value in names.items():
        expr = re.sub(rf"\b{name}\b", repr(value), expr)
    if not expr or not _EXPR_RE.match(expr):
        return None
    try:
        return float(eval(expr, {"__builtins__": {}}, {}))  # noqa: S307
    except Exception:
        return None


def _dynamic_helpers(text: str) -> List[Tuple[str, str]]:
    """(helper name, env prefix) pairs: helper functions whose own BODY
    reads ``os.environ.get(f"HVD_TPU_<prefix>_{...}")``.  Pairing the
    prefix with its enclosing helper — not every helper in the file —
    keeps an unrelated local ``_int()`` (or a second prefix) from
    fabricating phantom env names."""
    defs = list(_HELPER_DEF.finditer(text))
    out = []
    for i, dm in enumerate(defs):
        indent = dm.group(1)
        end = len(text)
        # The body runs until the next def at the same or outer indent.
        for nm in defs[i + 1:]:
            if len(nm.group(1)) <= len(indent):
                end = nm.start()
                break
        pm = _DYNAMIC_PREFIX.search(text, dm.start(), end)
        if pm:
            out.append((dm.group(2), pm.group(1)))
    return out


def collect_env_reads(root: str) -> Dict[str, Tuple[str, int]]:
    """Env var -> (file, line) of one read site, across the Python scope
    and the engine C++ sources."""
    reads: Dict[str, Tuple[str, int]] = {}

    def note(name: str, rel: str, pos_line: int) -> None:
        reads.setdefault(name, (rel, pos_line))

    for rel in iter_py_files(root, PY_SCOPE):
        if rel.startswith(os.path.join("tools", "hvdlint")):
            continue  # the lint's own pattern tables are not reads
        try:
            # Comment-stripped: `# was: os.environ.get("HVD_TPU_X")` is
            # neither a read (false undocumented-var failure) nor keeps
            # a stale doc row alive.
            text = strip_py_comments(read(root, rel))
        except OSError:
            continue
        for pat in _READ_PATTERNS:
            for m in re.finditer(pat, text):
                note(m.group(1), rel, text.count("\n", 0, m.start()) + 1)
        for helper, prefix in _dynamic_helpers(text):
            for hm in re.finditer(
                    rf"\b{helper}\(\s*\"([A-Z0-9_]+)\"", text):
                note(f"{prefix}_{hm.group(1)}", rel,
                     text.count("\n", 0, hm.start()) + 1)
    cc_dir = os.path.join(root, CC_DIR)
    if os.path.isdir(cc_dir):
        for fname in sorted(os.listdir(cc_dir)):
            if not fname.endswith((".cc", ".h")):
                continue
            rel = os.path.join(CC_DIR, fname)
            text = strip_cxx_comments(read(root, rel))
            for m in re.finditer(r"getenv\(\s*\"(HVD_TPU_\w+)\"", text):
                note(m.group(1), rel, text.count("\n", 0, m.start()) + 1)
    return reads


def parse_doc(doc: str) -> Tuple[Set[str], Dict[str, Tuple[str, int]],
                                 Set[str]]:
    """(documented names incl. HOROVOD->HVD_TPU aliases,
    table name -> (default cell, line), table-row names)."""
    documented: Set[str] = set()
    for m in re.finditer(r"\b(HOROVOD|HVD_TPU)_(\w+)", doc):
        documented.add(m.group(0))
        if m.group(1) == "HOROVOD":
            documented.add("HVD_TPU_" + m.group(2))
    defaults: Dict[str, Tuple[str, int]] = {}
    table_names: Set[str] = set()
    for lineno, line in enumerate(doc.splitlines(), 1):
        if not line.startswith("|") or "`" not in line:
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if len(cells) < 3:
            continue
        names = re.findall(r"`((?:HOROVOD|HVD_TPU)_\w+)`", cells[0])
        if not names:
            continue
        table_names.update(n for n in names if n.startswith("HVD_TPU_"))
        cell_defaults = [d.strip() for d in cells[1].split("/")]
        if len(cell_defaults) == len(names):
            pairs = zip(names, cell_defaults)
        else:
            pairs = ((n, cells[1]) for n in names)
        for name, default in pairs:
            defaults[name] = (default, lineno)
            if name.startswith("HOROVOD_"):
                defaults.setdefault("HVD_TPU_" + name[len("HOROVOD_"):],
                                    (default, lineno))
    return documented, defaults, table_names


def parse_dataclass_defaults(text: str,
                             cls: str) -> Dict[str, Optional[float]]:
    """Numeric field defaults of ``class <cls>`` parsed textually; module
    -level ``NAME = <expr>`` constants are resolved first."""
    consts: Dict[str, float] = {}
    for m in re.finditer(r"^([A-Z][A-Z0-9_]*)\s*=\s*([^#\n]+?)\s*(?:#.*)?$",
                         text, flags=re.M):
        val = _safe_eval(m.group(2), consts)
        if val is not None:
            consts[m.group(1)] = val
    cm = re.search(rf"^class {cls}\b.*?:$", text, flags=re.M)
    if not cm:
        return {}
    body = text[cm.end():]
    stop = re.search(r"^\s*@property|^\s*@staticmethod|^\s*def ", body,
                     flags=re.M)
    if stop:
        body = body[:stop.start()]
    fields: Dict[str, Optional[float]] = {}
    for m in re.finditer(
            r"^\s{4}(\w+)\s*:\s*[\w\[\]\". ]+=\s*([^#\n]+?)\s*(?:#.*)?$",
            body, flags=re.M):
        fields[m.group(1)] = _safe_eval(m.group(2), consts)
    return fields


def parse_engine_options(text: str) -> Dict[str, Optional[float]]:
    """Numeric member defaults of EngineOptions in engine.h."""
    text = strip_cxx_comments(text)
    m = re.search(r"struct\s+EngineOptions\s*\{(.*?)\n\};", text,
                  flags=re.S)
    if not m:
        return {}
    fields: Dict[str, Optional[float]] = {}
    for fm in re.finditer(r"^\s*[\w:]+\s+(\w+)\s*=\s*([^;]+);",
                          m.group(1), flags=re.M):
        fields[fm.group(1)] = _safe_eval(fm.group(2), {})
    return fields


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    try:
        doc = read(root, RUNNING_MD)
    except OSError as exc:
        return [Violation("env", RUNNING_MD, 0,
                          f"cannot read the env-var registry: {exc}")]
    documented, doc_defaults, table_names = parse_doc(doc)
    reads = collect_env_reads(root)
    for name in sorted(reads):
        rel, line = reads[name]
        if name not in documented:
            out.append(Violation(
                "env", rel, line,
                f"{name} is read here but undocumented in "
                f"docs/running.md — every HVD_TPU_* knob needs a row (or "
                f"prose) there"))
    for name in sorted(table_names - set(reads)):
        _, lineno = doc_defaults.get(name, ("", 0))
        out.append(Violation(
            "env", RUNNING_MD, lineno,
            f"{name} is documented but never read by any code in scope: "
            f"stale row, or the read site dropped out of the lintable "
            f"idiom"))

    # Plane default agreement: config.py Config vs engine.h EngineOptions.
    cfg_fields: Dict[str, Optional[float]] = {}
    try:
        cfg_fields = parse_dataclass_defaults(read(root, CONFIG_PY),
                                              "Config")
        eng_fields = parse_engine_options(read(root, ENGINE_H))
    except OSError:
        eng_fields = {}
    if cfg_fields and eng_fields:
        for cfg_name, eng_name in sorted(PLANE_FIELDS.items()):
            c, e = cfg_fields.get(cfg_name), eng_fields.get(eng_name)
            if c is None or e is None:
                continue
            if abs(c - e) > 1e-9:
                out.append(Violation(
                    "env", ENGINE_H, 0,
                    f"default disagreement between planes: "
                    f"Config.{cfg_name}={c:g} (common/config.py) but "
                    f"EngineOptions.{eng_name}={e:g} (engine.h) — a "
                    f"caller bypassing Python init gets different "
                    f"behavior"))

    # Doc-table numeric defaults vs the dataclass defaults the code uses.
    serve_fields: Dict[str, Optional[float]] = {}
    try:
        serve_fields = parse_dataclass_defaults(read(root, SCHEDULER_PY),
                                                "ServeConfig")
    except OSError:
        pass
    for env_name, (src, field) in sorted(DOC_DEFAULTS.items()):
        if env_name not in doc_defaults:
            continue
        cell, lineno = doc_defaults[env_name]
        if not _NUM_RE.match(cell):
            continue  # "off"/"unset"/prose defaults are not comparable
        fields = cfg_fields if src == "config" else serve_fields
        code_val = fields.get(field)
        if code_val is None:
            continue
        if abs(float(cell.replace("_", "")) - code_val) > 1e-9:
            out.append(Violation(
                "env", RUNNING_MD, lineno,
                f"{env_name}: documented default {cell} but the code "
                f"default is {code_val:g} "
                f"({'common/config.py Config.' if src == 'config' else 'serving/scheduler.py ServeConfig.'}"
                f"{field})"))
    return out
