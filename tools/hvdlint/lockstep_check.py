"""Checker 4: lockstep-mutation lint over engine.cc.

The engine's replicated state — response cache, applied autotune
parameters, wire-compression mode, error-feedback residuals, membership
identity — must mutate ONLY while every rank is processing the same
coordinator broadcast, in list order (the determinism contract PR 4's
response cache established and docs/performance.md documents).  A write
from anywhere else (an API thread, a per-rank heuristic) desynchronizes
slot numbering or bucket packing across ranks, the class of bug the PR-9
``compression_min_bytes`` race was.

This is a clang-free heuristic pass: it tracks which ``Engine::``
member function each line belongs to and flags protected-state writes
outside the whitelisted lifecycle/broadcast-processing functions.
Genuinely-safe exceptions carry an inline annotation::

    foo_ = bar;  // hvdlint: lockstep-ok(reason the write is safe)

on the offending line or the line above (grammar in
docs/contributing.md).
"""

from __future__ import annotations

import os
import re
from typing import List

from tools.hvdlint import Violation, read, strip_cxx_comments

ENGINE_CC = os.path.join("horovod_tpu", "engine", "cc", "engine.cc")

# Functions allowed to mutate lockstep state, and why.
WHITELIST = {
    # Lifecycle: single-threaded bring-up/teardown, no peers in flight.
    "Engine::Init": "bring-up before the background loop starts",
    "Engine::SetupSockets": "job-wide agreement exchange during bring-up",
    "Engine::SetupShmTransport": "transport agreement (token relay) and "
                                 "ring attach during bring-up",
    "Engine::CloseTopologyFds": "coordinated two-level teardown; every "
                                "rank demotes the topology on the same "
                                "failed collective",
    "Engine::Shutdown": "teardown after the background loop exits",
    "Engine::BackgroundLoop": "exit drain after the loop stopped ticking",
    "Engine::AbortLocal": "abort latch; every rank aborts the same tick",
    # Broadcast processing: every rank runs these on the identical
    # coordinator response list, in list order.
    "Engine::ApplyTunedParams": "applies the lockstep tuned broadcast",
    "Engine::ApplyReshape": "applies the reshape barrier broadcast",
    "Engine::SetupRejoinSockets": "adopts the admitting reshape broadcast",
    "Engine::ProcessCacheHits": "replays broadcast cache hits in order",
    "Engine::PerformOperation": "cache insert/erase in response-list order",
    "Engine::ExecuteAllreduce": "residual update while executing the list",
    "Engine::ExecuteSendRecv": "p2p residual update while executing the "
                               "list (sender-side error feedback)",
    # Steady state (PR 13): the pattern is installed by a broadcast and
    # replayed self-clocked; its cursors move in canonical slot order on
    # every rank, so the replay loop IS the lockstep.
    "Engine::ApplySteady": "applies the steady-pattern broadcast",
    "Engine::ExitSteadyLocal": "exit latch; miss coordinates are "
                               "re-agreed through the coordinator",
    "Engine::SteadyLoopOnce": "replays the agreed pattern in slot order",
    "Engine::SubRelayPass": "relay-side exit/requeue of the same pattern",
    "Engine::MaybeRevokeSteadyForReshape": "rank-0 revocation broadcast; "
                                           "survivors re-negotiate from "
                                           "tick one",
}

# Protected-state write patterns.  Reads (.load(), lookup methods) are
# deliberately NOT matched.
PROTECTED = (
    # Response cache: mutation methods only (Lookup/SlotByName/Get are
    # rank-local reads).
    r"\bcache_\.(set_capacity|Clear|Put|Touch|Erase)\s*\(",
    # The engine-thread-owned option mirror of lockstep knobs.
    r"\bopts_\.(fusion_threshold|cycle_time_ms|compression_mode|"
    r"compression_min_bytes|cross_algo_threshold|cache_capacity|rank|size|"
    r"local_rank|local_size|min_size|data_endpoints)\s*(=[^=]|\.assign\b)",
    r"\bopts_\s*=[^=]",
    # The atomics Python API threads read live.
    r"\bcur_(fusion|cycle_us|compression|comp_min_bytes|cross_algo|rank|"
    r"size|local_rank|local_size)_\.(store|exchange|fetch_add|fetch_sub)"
    r"\s*\(",
    r"\bmembership_epoch_\.(store|exchange|fetch_add)\s*\(",
    r"\bautotune_frozen_\.(store|exchange)\s*\(",
    r"\bapplied_window_\.(store|exchange)\s*\(",
    # Error-feedback residuals (compression state).
    r"\bresiduals_\.(clear|emplace|erase|insert|swap)\s*\(|\bresiduals_\[",
    # Per-tick change-point histories the XLA plane replays.
    r"\b(fusion_history_|compression_history_)\.(push_back|emplace_back|"
    r"pop_front|pop_back|clear|assign)\s*\(",
    # Steady-replay state: the pattern/groups install only from the
    # coordinator's steady broadcast, and the cursors/pending buffers
    # advance only inside the slot-ordered replay loop (reads —
    # .size()/.empty()/.begin()/[] — are deliberately not matched).
    r"\b(steady_pattern_|steady_groups_|steady_pending_group_|"
    r"steady_pending_reqs_)\.(clear|assign|push_back|emplace_back|"
    r"resize|swap)\s*\(",
    r"\b(steady_pattern_|steady_groups_)\s*=[^=]",
    r"\b(steady_pos_|steady_group_idx_|steady_epoch_|"
    r"steady_exit_epoch_)\s*(=[^=]|\+=)",
    r"\+\+\s*(steady_pos_|steady_group_idx_|steady_epoch_)",
    r"\bsteady_exit_pending_\s*=[^=]",
    r"\b(steady_active_|steady_pattern_len_)\.(store|exchange)\s*\(",
    # Data-plane transport choice (docs/performance.md#transport): armed
    # only by the init job-wide agreement + token relay, torn down only on
    # coordinated topology teardown — a rank-local flip would split the
    # job between shm rings and TCP sockets mid-collective.
    r"\b(shm_mode_|shm_agreed_|shm_active_)\s*=[^=]",
    r"\btopo_shm_\.(store|exchange)\s*\(",
)

# Definitions start at column 0 (`bool Engine::ApplyReshape(...) {`);
# indented qualified calls (std::to_string(...)) must not match.
_FUNC_RE = re.compile(r"^[A-Za-z_][\w:<>,*&\s]*?\b(\w+::\w+)\s*\((?!.*;)")
# Free/static helpers at column 0 (`static void Helper(...) {`): they
# must take over from a preceding (possibly whitelisted) member function
# — a write inside one is NOT broadcast processing.
_FREE_FUNC_RE = re.compile(r"^[A-Za-z_][\w<>,*&\s]*?\b(\w+)\s*\((?!.*;)")
_OK_RE = re.compile(r"hvdlint:\s*lockstep-ok\(([^)]*)\)")


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    try:
        raw = read(root, ENGINE_CC)
    except OSError as exc:
        return [Violation("lockstep", ENGINE_CC, 0,
                          f"cannot read engine.cc: {exc}")]
    stripped = strip_cxx_comments(raw)
    raw_lines = raw.splitlines()
    current = ""
    protected = [re.compile(p) for p in PROTECTED]
    for lineno, line in enumerate(stripped.splitlines(), 1):
        if line.startswith("}"):
            current = ""  # a column-0 close ends the current function
        fm = _FUNC_RE.match(line)
        if fm and "::" in fm.group(1):
            current = fm.group(1)
        elif _FREE_FUNC_RE.match(line):
            current = _FREE_FUNC_RE.match(line).group(1)
        for pat in protected:
            m = pat.search(line)
            if not m:
                continue
            if current in WHITELIST:
                continue
            annotated = any(
                _OK_RE.search(raw_lines[i])
                for i in (lineno - 1, lineno - 2)
                if 0 <= i < len(raw_lines))
            if annotated:
                continue
            out.append(Violation(
                "lockstep", ENGINE_CC, lineno,
                f"write to lockstep state ({m.group(0).strip()}) in "
                f"{current or '<file scope>'}, which is not a "
                f"whitelisted broadcast-processing function — mutate it "
                f"while processing the coordinator broadcast, or "
                f"annotate with '// hvdlint: lockstep-ok(reason)'"))
            break  # one report per line is enough
    return out
