"""CLI entry: ``python -m tools.hvdlint [checker ...] [--root DIR]``.

Runs every registered checker (or the named subset) against the repo and
prints one ``file:line: [checker] message`` report per violation.  Exit 0
clean, 1 with violations — tier-1 runs this as a fast test
(tests/test_hvdlint.py), so wire/env/API drift fails the suite at the PR
that introduces it.  ``--list`` names the checkers.
"""

from __future__ import annotations

import argparse
import sys

from tools.hvdlint import checkers, repo_root, run


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.hvdlint",
        description="horovod_tpu project-invariant static analysis")
    parser.add_argument("names", nargs="*",
                        help="checker subset (default: all)")
    parser.add_argument("--root", default=repo_root(),
                        help="tree to lint (default: this repo)")
    parser.add_argument("--list", action="store_true",
                        help="list checkers and exit")
    args = parser.parse_args(argv)
    table = checkers()
    if args.list:
        for name in table:
            print(name)
        return 0
    try:
        violations = run(args.root, args.names or None)
    except ValueError as exc:
        parser.error(str(exc))
    for v in violations:
        print(v.render(), file=sys.stderr)
    ran = args.names or list(table)
    if violations:
        print(f"hvdlint: {len(violations)} violation(s) from "
              f"{len(ran)} checker(s)", file=sys.stderr)
        return 1
    print(f"hvdlint: OK ({', '.join(ran)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
