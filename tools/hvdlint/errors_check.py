"""Checker 5: typed-error discipline.

Every ``raise`` in horovod_tpu/ must use the ``HorovodInternalError``
hierarchy (common/__init__.py) or a stdlib exception type — never bare
``Exception``/``BaseException``.  A bare Exception can't be caught
selectively: the elastic driver retries ``MembershipChangedError``, the
launcher maps ``RanksDownError`` to restart policy, and serving maps
typed errors to HTTP statuses; an untyped raise falls through all of
those to a job kill.  (AST-based, so strings and comments never
false-positive.)
"""

from __future__ import annotations

import ast
from typing import List

from tools.hvdlint import Violation, iter_py_files, read

SCOPE = ["horovod_tpu"]
_BANNED = {"Exception", "BaseException"}


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    for rel in iter_py_files(root, SCOPE):
        try:
            tree = ast.parse(read(root, rel))
        except (OSError, SyntaxError) as exc:
            out.append(Violation("errors", rel, 0,
                                 f"cannot parse: {exc}"))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name) and exc.id in _BANNED:
                out.append(Violation(
                    "errors", rel, node.lineno,
                    f"bare `raise {exc.id}`: use the "
                    f"HorovodInternalError hierarchy or a specific "
                    f"stdlib type so callers can catch it selectively"))
    return out
