"""Checker 3: C-API / ctypes parity.

The ctypes seam (horovod_tpu/common/__init__.py _load_lib) re-declares
every ``hvd_tpu_*`` signature by hand; ctypes checks nothing, so a drifted
argument count or type truncates silently on x86-64 (a ``long long``
passed through the default ``c_int`` conversion loses its top 32 bits —
exactly the class of bug that motivated the PR-9 compression_min_bytes
review finding).  Rules:

1. every ``hvd_tpu_*`` function c_api.cc exports has an explicit
   ``lib.<name>.restype`` AND ``lib.<name>.argtypes`` declaration whose
   types match the C signature (``None``/empty list for void/no-arg);
2. every ``hvd_tpu_*`` symbol any Python file references exists in
   c_api.cc (no bindings to dead symbols).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from tools.hvdlint import (Violation, iter_py_files, read,
                           strip_cxx_comments, strip_py_comments)

C_API = os.path.join("horovod_tpu", "engine", "cc", "c_api.cc")
BINDINGS = os.path.join("horovod_tpu", "common", "__init__.py")

_RET_MAP = {
    "void": "None",
    "int": "c_int",
    "long long": "c_longlong",
    "double": "c_double",
    "const char*": "c_char_p",
    "char*": "c_char_p",
    "void*": "c_void_p",
}
_ARG_MAP = {
    "int": "c_int",
    "long long": "c_longlong",
    "double": "c_double",
    "const char*": "c_char_p",
    "char*": "c_char_p",
    "const void*": "c_void_p",
    "void*": "c_void_p",
    "const long long*": "POINTER(c_longlong)",
    "long long*": "POINTER(c_longlong)",
}


def _norm_ctype(text: str) -> str:
    return text.replace("ctypes.", "").replace(" ", "").replace("\\", "")


def _c_param_type(param: str) -> str:
    """'const char* coord_endpoint' -> 'const char*' (drop the name,
    normalize pointer spacing)."""
    param = re.sub(r"\s*\*\s*", "* ", param.strip())
    typ = param.rsplit(" ", 1)[0] if " " in param else param
    return re.sub(r"\s+", " ", typ).replace("* ", "*").strip()


def parse_c_exports(text: str) -> Dict[str, Tuple[str, List[str], int]]:
    """name -> (return type, param types, line) for every hvd_tpu_*
    definition (comments stripped; params may span lines)."""
    text = strip_cxx_comments(text)
    out: Dict[str, Tuple[str, List[str], int]] = {}
    pat = re.compile(
        r"(?m)^(const char\s*\*|void\s*\*|void|int|long long|double)\s+"
        r"(hvd_tpu_\w+)\s*\(([^)]*)\)\s*\{", re.S)
    for m in pat.finditer(text):
        ret = re.sub(r"\s*\*", "*", re.sub(r"\s+", " ", m.group(1))).strip()
        params_text = m.group(3).strip()
        if params_text in ("", "void"):
            params: List[str] = []
        else:
            params = [_c_param_type(p)
                      for p in re.sub(r"\s+", " ", params_text).split(",")]
        out[m.group(2)] = (ret, params,
                           text.count("\n", 0, m.start()) + 1)
    return out


def parse_bindings(text: str) -> Tuple[Dict[str, Tuple[str, int]],
                                       Dict[str, Tuple[List[str], int]]]:
    """(restypes, argtypes) declared via ``lib.<name>.restype = ...`` /
    ``lib.<name>.argtypes = [...]`` (multiline lists handled)."""
    restypes: Dict[str, Tuple[str, int]] = {}
    argtypes: Dict[str, Tuple[List[str], int]] = {}
    for m in re.finditer(r"lib\.(hvd_tpu_\w+)\.restype\s*=\s*", text):
        rest = text[m.end():]
        value = rest.split("\n", 1)[0]
        while value.rstrip().endswith("\\"):
            rest = rest.split("\n", 1)[1]
            value = value.rstrip()[:-1] + rest.split("\n", 1)[0]
        restypes[m.group(1)] = (_norm_ctype(value.strip()),
                                text.count("\n", 0, m.start()) + 1)
    for m in re.finditer(r"lib\.(hvd_tpu_\w+)\.argtypes\s*=\s*\[", text):
        depth, i = 1, m.end()
        while i < len(text) and depth:
            if text[i] == "[":
                depth += 1
            elif text[i] == "]":
                depth -= 1
            i += 1
        body = _norm_ctype(text[m.end():i - 1])
        # POINTER(...) args contain no top-level commas in this codebase's
        # usage, so a flat split is exact.
        items = [t for t in body.replace("\n", "").split(",") if t]
        argtypes[m.group(1)] = (items,
                                text.count("\n", 0, m.start()) + 1)
    return restypes, argtypes


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    try:
        exports = parse_c_exports(read(root, C_API))
        # Comment-stripped: a commented-out binding must not satisfy the
        # parity check (nor count as a reference below).
        bindings_text = strip_py_comments(read(root, BINDINGS))
    except OSError as exc:
        return [Violation("capi", C_API, 0,
                          f"cannot read the C API seam: {exc}")]
    if not exports:
        return [Violation("capi", C_API, 0,
                          "no hvd_tpu_* exports found — parser drift?")]
    restypes, argtypes = parse_bindings(bindings_text)
    for name in sorted(exports):
        ret, params, line = exports[name]
        want_ret = _RET_MAP.get(ret)
        if name not in restypes:
            out.append(Violation(
                "capi", BINDINGS, 0,
                f"{name} (c_api.cc:{line}) has no explicit "
                f"lib.{name}.restype declaration (want {want_ret}); "
                f"ctypes' silent c_int default truncates {ret!r} returns"))
        elif want_ret and restypes[name][0] != want_ret:
            out.append(Violation(
                "capi", BINDINGS, restypes[name][1],
                f"{name}: restype {restypes[name][0]} does not match the "
                f"C return type {ret!r} (want {want_ret})"))
        want_args = [_ARG_MAP.get(p, f"<unmapped:{p}>") for p in params]
        if name not in argtypes:
            out.append(Violation(
                "capi", BINDINGS, 0,
                f"{name} (c_api.cc:{line}) has no explicit "
                f"lib.{name}.argtypes declaration (want "
                f"[{', '.join(want_args)}])"))
        else:
            got, bline = argtypes[name]
            if len(got) != len(params):
                out.append(Violation(
                    "capi", BINDINGS, bline,
                    f"{name}: argtypes declares {len(got)} argument(s) "
                    f"but the C signature (c_api.cc:{line}) takes "
                    f"{len(params)}"))
            else:
                for i, (g, w) in enumerate(zip(got, want_args)):
                    if g != w:
                        out.append(Violation(
                            "capi", BINDINGS, bline,
                            f"{name}: argtypes[{i}] is {g} but the C "
                            f"parameter is {params[i]!r} (want {w})"))
    # Reverse direction: every referenced symbol must exist in the C API.
    for rel in iter_py_files(root, ["horovod_tpu"]):
        try:
            text = strip_py_comments(read(root, rel))
        except OSError:
            continue
        for m in re.finditer(r"\b\w*lib\.(hvd_tpu_\w+)", text):
            if m.group(1) not in exports:
                out.append(Violation(
                    "capi", rel, text.count("\n", 0, m.start()) + 1,
                    f"{m.group(1)} is referenced here but c_api.cc "
                    f"exports no such symbol"))
    return out
