"""Checker 1: wire-protocol roundtrip completeness.

The coordinator protocol (engine/cc/wire.{h,cc}) is hand-rolled: a struct
field added to wire.h but forgotten in SerializeResponseList or
ParseResponseList silently truncates on the wire and desynchronizes ranks
— the class of bug a FlatBuffers schema would have made impossible.  This
checker parses the struct definitions out of wire.h and verifies:

1. every field of Request / RequestList / Response / ResponseList is
   referenced in BOTH the serialize and the parse function that carries
   that struct;
2. reshape-carried lockstep state is complete: every ``tuned_<knob>``
   field of ResponseList (the online-autotune broadcast) has a matching
   ``reshape_<knob>`` field, and the explicit barrier baseline fields
   (cache capacity, compression floor) exist — a knob broadcast in
   lockstep mid-run but not re-broadcast at the reshape barrier would
   leave admitted standbys running the env default while survivors run
   the tuned value (the divergence class docs/fault-tolerance.md's
   re-agreement contract exists to prevent).  The same twin rule covers
   any LIST-LEVEL ``p2p_<knob>`` / ``stage_<knob>`` field of
   ResponseList: persistent p2p/stage-membership state broadcast in
   lockstep must be re-broadcast at the barrier.  (The per-item
   ``Request.stage_ranks`` / ``Response.p2p_*`` fields deliberately
   don't trip this: membership travels with each op and the barrier
   clears every cache, so no stale stage state can survive a reshape —
   the audit behind docs/pipeline.md#fault-semantics.)
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from tools.hvdlint import Violation, read, strip_cxx_comments

WIRE_H = os.path.join("horovod_tpu", "engine", "cc", "wire.h")
WIRE_CC = os.path.join("horovod_tpu", "engine", "cc", "wire.cc")

# struct -> the (serialize, parse) function pair whose bodies must
# reference every one of its fields.  Request/Response ride inside their
# list's functions (the wire format has no standalone per-item codec).
STRUCT_FUNCS = {
    "Request": ("SerializeRequestList", "ParseRequestList"),
    "RequestList": ("SerializeRequestList", "ParseRequestList"),
    # The coordinator-tree aggregate's per-slot bit groups ride inside
    # the RequestList codec (PR-13); a BitGroup field dropped from either
    # side would silently desynchronize rank 0's per-rank announce
    # accounting.
    "BitGroup": ("SerializeRequestList", "ParseRequestList"),
    "Response": ("SerializeResponseList", "ParseResponseList"),
    "ResponseList": ("SerializeResponseList", "ParseResponseList"),
}

# ResponseList fields that are bookkeeping for an optional block, not
# re-broadcastable knobs (rule 2 skips them when deriving reshape_*
# counterparts from tuned_*).
_TUNED_BOOKKEEPING = {"tuned_present", "tuned_frozen", "tuned_window"}
# Barrier baseline fields with no tuned_* twin that must still exist:
# joiners adopt these from the admitting broadcast, never from their env.
_REQUIRED_RESHAPE = ("reshape_cache_capacity",
                     "reshape_compression_min_bytes")


def parse_struct_fields(header: str,
                        struct: str) -> List[Tuple[str, int]]:
    """(field, line) members of ``struct <name> { ... };`` in header text
    (comments already stripped)."""
    m = re.search(rf"\bstruct\s+{struct}\s*\{{", header)
    if not m:
        return []
    body_start = m.end()
    depth = 1
    i = body_start
    while i < len(header) and depth:
        if header[i] == "{":
            depth += 1
        elif header[i] == "}":
            depth -= 1
        i += 1
    body = header[body_start:i - 1]
    line0 = header.count("\n", 0, body_start)
    fields = []
    # Member declarations: `type name;` or `type name = default;` where
    # type may be templated (std::vector<int64_t>).  Methods/ctors have
    # parens before the terminating ';' and don't match.
    for fm in re.finditer(
            r"^\s*(?:[\w:]+(?:<[^<>]*>)?[&*\s]+)(\w+)\s*(?:=[^;()]*)?;",
            body, flags=re.M):
        fields.append((fm.group(1),
                       line0 + body.count("\n", 0, fm.start()) + 1))
    return fields


def function_body(source: str, name: str) -> str:
    """Body text of the first definition of `name` (empty if absent)."""
    m = re.search(rf"\b{name}\s*\([^;{{]*\)\s*\{{", source)
    if not m:
        return ""
    depth = 1
    i = m.end()
    while i < len(source) and depth:
        if source[i] == "{":
            depth += 1
        elif source[i] == "}":
            depth -= 1
        i += 1
    return source[m.end():i - 1]


def check(root: str) -> List[Violation]:
    out: List[Violation] = []
    try:
        header = strip_cxx_comments(read(root, WIRE_H))
        source = strip_cxx_comments(read(root, WIRE_CC))
    except OSError as exc:
        return [Violation("wire", WIRE_H, 0, f"cannot read wire files: "
                          f"{exc}")]
    bodies: Dict[str, str] = {}
    all_fields: Dict[str, List[Tuple[str, int]]] = {}
    for struct, (ser, par) in STRUCT_FUNCS.items():
        fields = parse_struct_fields(header, struct)
        all_fields[struct] = fields
        if not fields:
            out.append(Violation(
                "wire", WIRE_H, 0,
                f"struct {struct} not found (or has no parseable fields) "
                f"— the roundtrip check cannot see the wire schema"))
            continue
        for fn in (ser, par):
            if fn not in bodies:
                bodies[fn] = function_body(source, fn)
                if not bodies[fn]:
                    out.append(Violation(
                        "wire", WIRE_CC, 0, f"function {fn} not found"))
        for field, line in fields:
            for fn, side in ((ser, "serialize"), (par, "parse")):
                body = bodies.get(fn, "")
                if body and not re.search(rf"\b{field}\b", body):
                    out.append(Violation(
                        "wire", WIRE_H, line,
                        f"{struct}.{field} is missing from the {side} "
                        f"path ({fn} in wire.cc): the field would "
                        f"silently drop on the wire"))
    # Rule 2: reshape re-broadcast completeness over ResponseList.
    rl_names = {f for f, _ in all_fields.get("ResponseList", [])}
    rl_lines = dict(all_fields.get("ResponseList", []))
    if rl_names:
        for field in sorted(rl_names):
            prefix = next((p for p in ("tuned_", "p2p_", "stage_")
                           if field.startswith(p)), None)
            if prefix is None or field in _TUNED_BOOKKEEPING:
                continue
            want = "reshape_" + field[len(prefix):]
            if want not in rl_names:
                out.append(Violation(
                    "wire", WIRE_H, rl_lines[field],
                    f"lockstep knob ResponseList.{field} has no "
                    f"ResponseList.{want}: the value is broadcast in "
                    f"lockstep mid-run but not re-broadcast at the "
                    f"reshape barrier, so an admitted standby would run "
                    f"its env default while survivors run the tuned "
                    f"value"))
        for want in _REQUIRED_RESHAPE:
            if want not in rl_names:
                out.append(Violation(
                    "wire", WIRE_H, 0,
                    f"ResponseList.{want} is missing: joiners must adopt "
                    f"this barrier baseline from the admitting broadcast, "
                    f"not from their own environment"))
    return out
