"""hvdlint: project-invariant static analysis for horovod_tpu.

The project's correctness rests on cross-layer contracts no compiler
checks: every wire field must serialize, parse, and survive a reshape
re-broadcast identically on all ranks; lockstep state (cache, autotune,
compression, membership) may mutate only while processing the
coordinator's broadcast; every ``HVD_TPU_*`` knob must be documented with
the default the code actually uses; every C symbol must have a ctypes
binding that matches its signature.  Each checker here machine-checks one
of those contracts against the source tree, so a violation fails tier-1
at the PR that introduces it instead of surfacing as a cross-rank
divergence at pod scale (docs/contributing.md).

Run everything::

    python -m tools.hvdlint            # exit 0 clean, 1 with file:line report

or a subset: ``python -m tools.hvdlint wire capi``.  Checkers take a
repo-root argument so tests can point them at small synthetic trees
(tests/test_hvdlint.py).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable, Dict, List


@dataclasses.dataclass(frozen=True)
class Violation:
    """One contract violation, printable as ``file:line: [checker] msg``."""

    checker: str
    file: str  # repo-relative path
    line: int  # 1-based; 0 = whole-file / tree-level finding
    message: str

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        return f"{loc}: [{self.checker}] {self.message}"


def repo_root() -> str:
    """The tree hvdlint ships in (two levels above this package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def strip_cxx_comments(text: str) -> str:
    """Replace C++ ``//`` and ``/* */`` comment bodies with spaces,
    preserving line numbers (and the ``hvdlint:`` annotation lines, which
    callers inspect in the ORIGINAL text)."""

    def _blank(m: re.Match) -> str:
        return re.sub(r"[^\n]", " ", m.group(0))

    text = re.sub(r"/\*.*?\*/", _blank, text, flags=re.S)
    return re.sub(r"//[^\n]*", _blank, text)


def strip_py_comments(text: str) -> str:
    """Blank ``#`` comment bodies in Python source, preserving strings
    and line numbers (tokenize-based) — a commented-out binding or env
    read must not satisfy (or trip) a text checker.  Returns the text
    unchanged if it doesn't tokenize."""
    import io
    import tokenize

    lines = text.splitlines(keepends=True)
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                row, col = tok.start
                line = lines[row - 1]
                end = col + len(tok.string)
                lines[row - 1] = line[:col] + " " * (end - col) + line[end:]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return text
    return "".join(lines)


def iter_py_files(root: str, subdirs: List[str]) -> List[str]:
    """Repo-relative paths of every .py file under the given subdirs
    (sorted; __pycache__ skipped)."""
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base) and base.endswith(".py"):
            out.append(sub)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fname), root))
    return sorted(out)


def read(root: str, rel: str) -> str:
    with open(os.path.join(root, rel)) as f:
        return f.read()


def checkers() -> Dict[str, Callable[[str], List[Violation]]]:
    """Name -> check(root) for every registered checker, in report order."""
    from tools.hvdlint import (capi_check, env_check, errors_check,
                               lockstep_check, metrics_check, model_check,
                               wire_check)

    return {
        "wire": wire_check.check,
        "env": env_check.check,
        "capi": capi_check.check,
        "lockstep": lockstep_check.check,
        "errors": errors_check.check,
        "metrics": metrics_check.check,
        "model": model_check.check,
    }


def run(root: str, names: List[str] | None = None) -> List[Violation]:
    """Run the named checkers (default: all) against `root`."""
    table = checkers()
    unknown = [n for n in (names or []) if n not in table]
    if unknown:
        raise ValueError(f"unknown checker(s) {unknown}; "
                         f"have {sorted(table)}")
    out: List[Violation] = []
    for name in (names or list(table)):
        out.extend(table[name](root))
    return out
