"""Checker 6: Prometheus metric-name and section-coverage lint.

The former standalone ``tools/check_metric_names.py``, folded into
hvdlint (the old CLI remains as a thin shim).  Renders a registry with
one of everything recorded and verifies: every family is snake_case with
the ``hvd_tpu_`` prefix, pairs ``# HELP`` with ``# TYPE``, is unique
across sections; and every ``metrics_snapshot()`` top-level section maps
to at least one rendered family (SECTION_FAMILIES) and is documented in
docs/metrics.md.  Unlike the text-parsing checkers this one imports the
live registry, so it lints what the code actually renders.
"""

from __future__ import annotations

import os
import re
import sys
from collections import Counter
from typing import List

from tools.hvdlint import Violation

NAME_RE = re.compile(r"^hvd_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")

# Section-coverage contract: every metrics_snapshot() top-level section
# must export at least one Prometheus family AND be documented in
# docs/metrics.md — a new section missing from this map, a mapped family
# missing from the exposition, or an undocumented section all fail the
# lint (this drifted silently in past PRs).  "enabled" is the gate flag,
# not a section; "histograms" is special-cased (one family per histogram).
SECTION_FAMILIES = {
    "ops": ("hvd_tpu_ops_total",),
    "bytes": ("hvd_tpu_bytes_total",),
    "batches": ("hvd_tpu_batches_dispatched_total",
                "hvd_tpu_fused_tensors_total"),
    "stalls": ("hvd_tpu_stall_events_total", "hvd_tpu_stalled_tensor_total"),
    "faults": ("hvd_tpu_faults_injected_total", "hvd_tpu_aborts_total",
               "hvd_tpu_restart_epoch"),
    "skew": ("hvd_tpu_announce_total", "hvd_tpu_last_to_announce_total"),
    "cache": ("hvd_tpu_response_cache_events_total",
              "hvd_tpu_response_cache_size"),
    "membership": ("hvd_tpu_membership_epoch", "hvd_tpu_membership_size",
                   "hvd_tpu_membership_reshapes_total"),
    "autotune": ("hvd_tpu_autotune_enabled",
                 "hvd_tpu_autotune_windows_total"),
    "serving": ("hvd_tpu_serving_requests_total",
                "hvd_tpu_serving_steps_total"),
    "flight": ("hvd_tpu_flight_events_total",
               "hvd_tpu_flight_ring_capacity"),
    "compression": ("hvd_tpu_compression_mode",
                    "hvd_tpu_compression_wire_bytes_total",
                    "hvd_tpu_compression_payload_bytes_total",
                    "hvd_tpu_compression_ops_total",
                    "hvd_tpu_compression_residual_bytes"),
    "topology": ("hvd_tpu_topology_hierarchical",
                 "hvd_tpu_topology_nodes",
                 "hvd_tpu_topology_local_size",
                 "hvd_tpu_topology_cross_algo_threshold_bytes",
                 "hvd_tpu_topology_cross_ops_total",
                 "hvd_tpu_topology_bytes_total"),
    "liveness": ("hvd_tpu_liveness_interval_ms",
                 "hvd_tpu_liveness_miss_limit",
                 "hvd_tpu_liveness_frames_total",
                 "hvd_tpu_liveness_miss_events_total",
                 "hvd_tpu_liveness_evictions_total",
                 "hvd_tpu_liveness_clock_fanin",
                 "hvd_tpu_liveness_peer_age_us"),
    "p2p": ("hvd_tpu_p2p_transfers_total",
            "hvd_tpu_p2p_bytes_total",
            "hvd_tpu_p2p_matched_total",
            "hvd_tpu_p2p_unmatched",
            "hvd_tpu_p2p_group_ops_total",
            "hvd_tpu_p2p_channels"),
    "links": ("hvd_tpu_link_stats_enabled",
              "hvd_tpu_link_bytes_total",
              "hvd_tpu_link_sends_total",
              "hvd_tpu_link_stall_events_total",
              "hvd_tpu_link_send_latency_us",
              "hvd_tpu_link_rtt_us",
              "hvd_tpu_link_rtt_samples_total"),
    "anomalies": ("hvd_tpu_anomaly_sigma",
                  "hvd_tpu_anomaly_verdicts_total"),
    "control": ("hvd_tpu_control_tree_depth",
                "hvd_tpu_control_children",
                "hvd_tpu_control_steady_active",
                "hvd_tpu_control_steady_cycles_total",
                "hvd_tpu_control_steady_transitions_total",
                "hvd_tpu_control_negotiated_ticks_total",
                "hvd_tpu_control_frames_total"),
    "state": ("hvd_tpu_state_armed",
              "hvd_tpu_state_snapshots_total",
              "hvd_tpu_state_snapshot_bytes_total",
              "hvd_tpu_state_last_snapshot_step",
              "hvd_tpu_state_overlap_ratio",
              "hvd_tpu_state_peer_copies_total",
              "hvd_tpu_state_peer_last_step",
              "hvd_tpu_state_restores_total",
              "hvd_tpu_state_checkpoint_events_total",
              "hvd_tpu_state_checkpoint_shard_bytes_total"),
    "histograms": (),
}


def populated_registry():
    """A registry with at least one sample in every section, so the
    exposition renders every family the code can produce."""
    from horovod_tpu.common import metrics

    reg = metrics.MetricsRegistry()
    reg.record_enqueue("engine", "allreduce", 1024)
    reg.record_bytes_out("engine", 1024)
    reg.record_batch(2)
    reg.record_stall("lint.tensor", 1.0)
    reg.record_fault("crash")
    reg.record_abort("ranks_down")
    reg.record_last_announce(1, 2)
    reg.set_restart_epoch(1)
    reg.record_cache("engine", "hits")
    reg.record_cache("xla", "misses")
    reg.set_cache_size("engine", 1)
    reg.set_membership({"epoch": 1, "size": 3, "reshapes": 1,
                        "ranks_lost": [1], "ranks_joined": [3]})
    reg.record_serving("requests", "lint-tenant")
    reg.record_serving("admitted", "lint-tenant")
    reg.record_serving("rejected", "lint-tenant")
    reg.record_serving("retired", "lint-tenant")
    reg.record_serving_tokens("lint-tenant", "prompt", 8)
    reg.record_serving_tokens("lint-tenant", "generated", 4)
    reg.record_serving_step(2, 4)
    reg.set_serving_gauges(queue_depth=1, active=2, kv_blocks_in_use=3,
                           kv_blocks_total=8)
    reg.set_flight({"events": {"engine": 5, "xla": 2}, "capacity": 512})
    reg.set_state_armed(True)
    reg.record_state_snapshot(7, 4096)
    reg.set_state_overlap(0.01, 0.4)
    reg.record_state_peer(sent_bytes=4096)
    reg.record_state_peer(received_step=7)
    reg.record_state_restore("peer")
    reg.record_state_restore("local")
    reg.record_state_restore("root_broadcast")
    reg.record_state_ckpt("sharded_saves", nbytes=4096)
    reg.record_state_ckpt("legacy_saves", nbytes=8192)
    reg.record_state_ckpt("loads")
    reg.record_state_ckpt("pruned")
    reg.set_topology({"hierarchical": True, "nodes": 2, "local_size": 2,
                      "cross_algo_threshold": 64 << 10,
                      "cross_ops": {"ring": 3, "tree": 1},
                      "bytes": {"local": 4096, "cross": 1024}})
    reg.set_control({"tree": True, "depth": 2, "children": 3, "hosts": 2,
                     "steady": {"active": True, "pattern_len": 4,
                                "threshold": 32, "entries": 1, "exits": 0,
                                "replays": 40, "cycles": 10},
                     "negotiated_ticks": 12,
                     "frames": {"sent": 24, "received": 24}})
    reg.set_liveness({"interval_ms": 100, "miss_limit": 10,
                      "frames": {"sent": 120, "received": 118},
                      "miss_events": 1, "evictions": 1, "clock_fanin": 2,
                      "peers": {1: {"age_us": 900, "misses": 0}}})
    reg.set_links({"enabled": True, "peers": {
        1: {"bytes_out": 4096, "bytes_in": 2048, "sends": 32,
            "recvs": 30, "stalls": 1, "short_writes": 0,
            "send_us_sum": 640, "send_us_count": 32,
            "send_us_buckets": [30, 2, 0, 0, 0, 0, 0, 0, 0, 0],
            "rtt_last_us": 210, "rtt_ewma_us": 200, "rtt_samples": 5}}})
    reg.set_anomalies({"sigma": 5, "interval_ms": 500,
                       "verdicts": {"slow_link": 1, "straggler": 0,
                                    "cache_degraded": 0, "slow_phase": 0},
                       "log": [{"kind": "slow_link", "subject": "0-1",
                                "detail": "lint", "age_us": 1000}]})
    reg.set_compression({
        "mode": "bf16", "min_bytes": 1024,
        "planes": {"engine": {"wire_bytes": 512, "payload_bytes": 1024,
                              "ops": {"none": 1, "bf16": 2, "fp8": 0}},
                   "xla": {"wire_bytes": 0, "payload_bytes": 0,
                           "ops": {"none": 0, "bf16": 0, "fp8": 0}}},
        "residual_bytes": 4096, "residual_tensors": 2,
    })
    reg.set_autotune({
        "enabled": True, "frozen": True, "windows": 3,
        "fusion_threshold": 1 << 20, "cycle_time_ms": 2.5,
        "best_score": 123.4,
        "history": [{"window": 1, "fusion_threshold": 1 << 20,
                     "cycle_time_ms": 2.5, "score": 123.4}],
        "applied": [{"tick": 7, "fusion_threshold": 1 << 20,
                     "cycle_time_ms": 2.5, "frozen": True}],
    })
    for name in metrics.HISTOGRAMS:
        reg.observe(name, 0.001)
    return reg


def lint(text: str) -> list:
    """Return the list of naming-convention violations in a Prometheus
    text exposition (empty = clean)."""
    errors = []
    helps = []
    families = []
    for line in text.splitlines():
        if line.startswith("# HELP "):
            helps.append(line.split()[2])
        elif line.startswith("# TYPE "):
            families.append(line.split()[2])
        elif line.startswith("#"):
            errors.append(f"unexpected comment line: {line!r}")
    for name in families:
        if not NAME_RE.match(name):
            errors.append(
                f"metric family '{name}' violates the naming convention "
                f"(snake_case with hvd_tpu_ prefix)")
        if name not in helps:
            errors.append(f"metric family '{name}' has # TYPE but no "
                          f"# HELP")
    for name in helps:
        if name not in families:
            errors.append(f"metric family '{name}' has # HELP but no "
                          f"# TYPE")
    for name, n in Counter(families).items():
        if n > 1:
            errors.append(
                f"duplicate metric family '{name}': two registry sections "
                f"export the same name")
    declared = set(families)
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample = line.split("{")[0].split(" ")[0]
        base = sample
        for suffix in HIST_SUFFIXES:
            if sample.endswith(suffix) and sample[:-len(suffix)] in declared:
                base = sample[:-len(suffix)]
                break
        if base not in declared:
            errors.append(f"sample '{sample}' has no # TYPE declaration")
    return errors


def _metrics_doc_text(root: str = None) -> str:
    if root is None:
        from tools.hvdlint import repo_root

        root = repo_root()
    try:
        with open(os.path.join(root, "docs", "metrics.md")) as f:
            return f.read().lower()
    except OSError:
        return ""


def lint_sections(snapshot: dict, text: str, doc_text: str) -> list:
    """Section-coverage violations: every snapshot top-level section must
    map to at least one rendered Prometheus family (SECTION_FAMILIES) and
    appear in docs/metrics.md."""
    errors = []
    families = {line.split()[2] for line in text.splitlines()
                if line.startswith("# TYPE ")}
    for section, value in snapshot.items():
        if section == "enabled":
            continue  # the collection gate, not a metrics section
        if section not in SECTION_FAMILIES:
            errors.append(
                f"snapshot section '{section}' has no SECTION_FAMILIES "
                f"entry (tools/hvdlint/metrics_check.py): declare its "
                f"Prometheus families so the exposition cannot silently "
                f"drop it")
            continue
        expected = SECTION_FAMILIES[section]
        if section == "histograms":
            from horovod_tpu.common.metrics import _prom_hist_name

            expected = tuple(_prom_hist_name(name) for name in value)
        if not expected:
            errors.append(
                f"snapshot section '{section}' declares no Prometheus "
                f"family at all")
        for family in expected:
            if family not in families:
                errors.append(
                    f"snapshot section '{section}': declared family "
                    f"'{family}' is missing from the exposition")
        if section.lower() not in doc_text:
            errors.append(
                f"snapshot section '{section}' is not documented in "
                f"docs/metrics.md")
    return errors


def lint_errors(root: str) -> List[str]:
    """All metric-lint error strings against the live registry."""
    from horovod_tpu.common import metrics

    snapshot = populated_registry().snapshot()
    text = metrics.prometheus_text(snapshot)
    return lint(text) + lint_sections(snapshot, text,
                                      _metrics_doc_text(root))


def check(root: str) -> List[Violation]:
    rel = os.path.join("horovod_tpu", "common", "metrics.py")
    from tools.hvdlint import repo_root

    if os.path.realpath(root) == os.path.realpath(repo_root()):
        try:
            errors = lint_errors(root)
        except Exception as exc:  # import/registry drift is a finding
            return [Violation("metrics", rel, 0,
                              f"metric lint could not run: {exc!r}")]
        return [Violation("metrics", rel, 0, err) for err in errors]
    # A foreign --root: this checker lints the LIVE registry, so the
    # import must resolve horovod_tpu (and this module) from the target
    # tree, not the invoker's checkout — run it in a subprocess with the
    # target tree at the head of sys.path.
    import subprocess
    import sys as _sys

    driver = ("import sys\n"
              f"sys.path.insert(0, {root!r})\n"
              "from tools.hvdlint.metrics_check import lint_errors\n"
              f"for e in lint_errors({root!r}):\n"
              "    print(e)\n")
    try:
        proc = subprocess.run([_sys.executable, "-c", driver],
                              capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as exc:
        return [Violation("metrics", rel, 0,
                          f"metric lint could not run on {root}: "
                          f"{exc!r}")]
    if proc.returncode != 0:
        return [Violation("metrics", rel, 0,
                          f"metric lint could not run on {root}: "
                          f"{proc.stderr.strip()[-500:]}")]
    return [Violation("metrics", rel, 0, line)
            for line in proc.stdout.splitlines() if line.strip()]


def main() -> int:
    """Standalone CLI (the tools/check_metric_names.py compatibility
    surface)."""
    from horovod_tpu.common import metrics
    from tools.hvdlint import repo_root

    snapshot = populated_registry().snapshot()
    text = metrics.prometheus_text(snapshot)
    errors = lint(text)
    errors += lint_sections(snapshot, text, _metrics_doc_text(repo_root()))
    for err in errors:
        print(f"check_metric_names: {err}", file=sys.stderr)
    if not errors:
        n = len([l for l in text.splitlines() if l.startswith("# TYPE ")])
        print(f"check_metric_names: OK ({n} metric families, "
              f"{len(snapshot) - 1} snapshot sections covered)")
    return 1 if errors else 0
