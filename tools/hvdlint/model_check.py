"""Checker 7: model-checker / C++ protocol sync (tools/hvdmodel).

The hvdmodel explorer is only worth its CI minutes while it models the
protocol the engine actually speaks.  This checker pins the two ends
together BIDIRECTIONALLY:

1. ``tools/hvdmodel/coverage.py`` declares, as plain set literals, the
   status codes and the steady/reshape wire fields the model covers;
2. ``engine/cc/wire.h`` is the ground truth: its ``StatusCode`` enum and
   the steady/membership family of ``RequestList`` fields (``steady_*``,
   ``dead_ranks``, ``membership_epoch``) plus the steady/reshape family
   of ``ResponseList`` fields (``steady_*``, ``reshape_*``, ``member_*``,
   ``membership_epoch``) must EQUAL the declared sets.

A field added to wire.h without extending the model fails here at the
introducing PR (the model would silently verify a stale protocol);
a name deleted from the model while the C++ still carries it fails the
same way in the other direction.  Each declared name must additionally
be referenced somewhere in the model source itself, so the coverage
file cannot drift into aspirational documentation
(docs/contributing.md "Extending the protocol").
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from tools.hvdlint import Violation, read, strip_cxx_comments
from tools.hvdlint.wire_check import WIRE_H, parse_struct_fields

COVERAGE_PY = os.path.join("tools", "hvdmodel", "coverage.py")
MODEL_DIR = os.path.join("tools", "hvdmodel")

# wire.h struct -> (coverage.py set name, family regex).  A field whose
# name matches the family participates in the control-plane protocol the
# model abstracts; everything else (payload routing, autotune lockstep)
# is covered by checkers 1-6 instead.
FAMILIES = {
    "RequestList": (
        "MODELED_REQUEST_FIELDS",
        re.compile(r"^(steady_.*|dead_ranks|hb_report"
                   r"|membership_epoch)$")),
    "ResponseList": (
        "MODELED_RESPONSE_FIELDS",
        re.compile(r"^(steady_.*|reshape_.*|member_.*|membership_epoch)$")),
    # Point-to-point plane (docs/pipeline.md): the per-item pairing fields
    # drive the coordinator's paired-readiness negotiation, which the
    # model's p2p announce/match/execute states abstract — a field added
    # to either struct without extending the model would let the explorer
    # verify a protocol the engine no longer speaks.
    "Request": (
        "MODELED_P2P_REQUEST_FIELDS",
        re.compile(r"^(p2p_.*|stage_.*)$")),
    "Response": (
        "MODELED_P2P_RESPONSE_FIELDS",
        re.compile(r"^(p2p_.*|stage_.*)$")),
}

STATUS_SET = "MODELED_STATUS_CODES"
_ENUM_RE = re.compile(r"enum\s+StatusCode\s*:[^{]*\{(.*?)\}", re.S)
_CODE_RE = re.compile(r"\b(ST_[A-Z_]+)\s*=")


def _status_codes(header: str) -> Dict[str, int]:
    """ST_* name -> 1-based line from wire.h's StatusCode enum."""
    m = _ENUM_RE.search(header)
    if not m:
        return {}
    out: Dict[str, int] = {}
    base = header[:m.start(1)].count("\n")
    for cm in _CODE_RE.finditer(m.group(1)):
        out[cm.group(1)] = base + m.group(1)[:cm.start(1)].count("\n") + 1
    return out


def _declared_sets(root: str) -> Tuple[Dict[str, Set[str]],
                                       Dict[str, int], List[Violation]]:
    """Parse coverage.py's module-level set literals with the AST so a
    syntax-valid but computed value (comprehension, union) is rejected —
    the sets must stay ``ast.literal_eval``-able by design."""
    vios: List[Violation] = []
    sets: Dict[str, Set[str]] = {}
    lines: Dict[str, int] = {}
    try:
        tree = ast.parse(read(root, COVERAGE_PY))
    except (OSError, SyntaxError) as exc:
        return {}, {}, [Violation("model", COVERAGE_PY, 0,
                                  f"cannot parse: {exc}")]
    wanted = {STATUS_SET} | {s for s, _ in FAMILIES.values()}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name) or tgt.id not in wanted:
            continue
        lines[tgt.id] = node.lineno
        try:
            val = ast.literal_eval(node.value)
        except ValueError:
            vios.append(Violation(
                "model", COVERAGE_PY, node.lineno,
                f"{tgt.id} must be a literal set of strings (it is "
                f"cross-checked against wire.h by eye and by tool)"))
            continue
        if (not isinstance(val, (set, frozenset))
                or not all(isinstance(x, str) for x in val)):
            vios.append(Violation(
                "model", COVERAGE_PY, node.lineno,
                f"{tgt.id} must be a set of strings"))
            continue
        sets[tgt.id] = set(val)
    for name in sorted(wanted - set(sets)):
        if not any(v.message.startswith(name) for v in vios):
            vios.append(Violation("model", COVERAGE_PY, 0,
                                  f"missing declaration {name}"))
    return sets, lines, vios


def _model_source(root: str) -> str:
    """Concatenated source of every hvdmodel module except coverage.py
    itself (a name only present in its own declaration is dead)."""
    base = os.path.join(root, MODEL_DIR)
    chunks = []
    for fname in sorted(os.listdir(base)):
        if not fname.endswith(".py") or fname == "coverage.py":
            continue
        chunks.append(read(root, os.path.join(MODEL_DIR, fname)))
    return "\n".join(chunks)


def check(root: str) -> List[Violation]:
    sets, set_lines, out = _declared_sets(root)
    try:
        header = strip_cxx_comments(read(root, WIRE_H))
    except OSError as exc:
        out.append(Violation("model", WIRE_H, 0, f"cannot read: {exc}"))
        return out

    # -- 1. StatusCode enum <-> MODELED_STATUS_CODES -------------------
    codes = _status_codes(header)
    if not codes:
        out.append(Violation("model", WIRE_H, 0,
                             "StatusCode enum not found"))
    declared = sets.get(STATUS_SET, set())
    for name in sorted(set(codes) - declared):
        out.append(Violation(
            "model", WIRE_H, codes[name],
            f"status {name} is not modeled: add it to "
            f"{COVERAGE_PY}:{STATUS_SET} and give it a transition in "
            f"tools/hvdmodel/model.py"))
    for name in sorted(declared - set(codes)):
        out.append(Violation(
            "model", COVERAGE_PY, set_lines.get(STATUS_SET, 0),
            f"{STATUS_SET} lists {name} which wire.h's StatusCode "
            f"enum no longer defines"))

    # -- 2. wire-field families <-> MODELED_*_FIELDS -------------------
    for struct, (set_name, family) in sorted(FAMILIES.items()):
        fields = {f: ln for f, ln in parse_struct_fields(header, struct)}
        if not fields:
            out.append(Violation("model", WIRE_H, 0,
                                 f"struct {struct} not found"))
            continue
        in_family = {f for f in fields if family.match(f)}
        declared = sets.get(set_name, set())
        for name in sorted(in_family - declared):
            out.append(Violation(
                "model", WIRE_H, fields[name],
                f"{struct}.{name} is control-plane state the model "
                f"does not cover: add it to {COVERAGE_PY}:{set_name} "
                f"and bind it in model.WIRE_BINDING"))
        for name in sorted(declared - in_family):
            out.append(Violation(
                "model", COVERAGE_PY, set_lines.get(set_name, 0),
                f"{set_name} lists {name} which {struct} in wire.h "
                f"no longer carries"))

    # -- 3. every declared name is live in the model source ------------
    src = _model_source(root)
    for set_name, names in sorted(sets.items()):
        for name in sorted(names):
            if name not in src:
                out.append(Violation(
                    "model", COVERAGE_PY, set_lines.get(set_name, 0),
                    f"{set_name} declares {name} but nothing in "
                    f"tools/hvdmodel/ references it — the model does "
                    f"not actually cover it"))
    return out
