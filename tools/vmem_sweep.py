#!/usr/bin/env python
"""Scoped-VMEM calibration sweep for the flash-attention backward.

Recompiles `jax.grad(flash_attention)` over (seq, head_dim, block_q,
block_k) on the attached TPU and reports which configs fit the chip's
scoped-VMEM ceiling — the ground truth behind
`horovod_tpu.ops.attention._bwd_plan` (r5 calibration; the r4 regression
was a tuned block choice that stopped compiling at seq 8192).  Compile-
only: safe to run anywhere a TPU is visible, ~1-2 s per config.

Usage: python tools/vmem_sweep.py [--full]
  default: the documented sweep {1k, 4k, 8k, 16k} x {64, 128} with the
  plan's chosen blocks (should print all OK);
  --full: every block candidate per shape, to re-derive the plan table
  after a Mosaic/compiler update.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from horovod_tpu.ops.attention import _bwd_plan, flash_attention


def try_compile(sl, d, bq, bk, bh=16):
    q = jnp.zeros((bh // 8, 8, sl, d), jnp.bfloat16)

    def f(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=bq,
                               block_k=bk).astype(jnp.float32).sum()

    t0 = time.time()
    try:
        jax.jit(jax.grad(f, argnums=(0, 1, 2))).lower(q, q, q).compile()
        return "OK", time.time() - t0, ""
    except Exception as e:  # report the Mosaic scoped-vmem line if present
        lines = str(e).splitlines() or [repr(e)]
        key = next((ln.strip() for ln in lines
                    if "Scoped allocation" in ln), lines[0])
        return "FAIL", time.time() - t0, key[:110]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="sweep every block candidate, not just the plan's")
    args = ap.parse_args()
    if jax.default_backend() != "tpu":
        print("no TPU visible; this sweep only means something on-chip")
        return
    cands = [(1024, 1024), (512, 1024), (1024, 512), (512, 512),
             (256, 512), (256, 256)]
    # bench-protocol bh (token-constant seq:batch sweep) plus a high-bh
    # probe per seq: the scoped size varies non-monotonically with the
    # batch*heads grid dim (see attention._bwd_plan).
    bench_bh = {1024: 128, 4096: 32, 8192: 16, 16384: 8}
    failures = 0
    for d in (64, 128):
        for sl in (1024, 4096, 8192, 16384):
            for bh in dict.fromkeys((bench_bh[sl], 128)):
                if args.full:
                    todo = [c for c in cands
                            if sl % c[0] == 0 and sl % c[1] == 0]
                else:
                    mode, bq, bk = _bwd_plan(sl, d, 1024, 1024, bh)
                    todo = [(bq, bk)]
                for bq, bk in todo:
                    st, dt, key = try_compile(sl, d, bq, bk, bh)
                    plan = _bwd_plan(sl, d, bq, bk, bh)
                    print(f"d={d} sl={sl} bh={bh} bq={bq} bk={bk} "
                          f"plan={plan}: {st} ({dt:.1f}s) {key}", flush=True)
                    failures += st != "OK" and not args.full
    if failures:
        sys.exit(f"{failures} plan-chosen config(s) failed to compile")
    print("all plan-chosen configs compile")


if __name__ == "__main__":
    main()
