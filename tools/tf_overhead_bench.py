#!/usr/bin/env python
"""Measure the TF-binding collective overhead vs the raw engine path.

The TF/Keras bindings run collectives through ``tf.py_function`` (eager
numpy in, engine, numpy out) — the deliberate division of labor where
COMPILED training belongs to the JAX path (docs/tpu.md).  A TF user
should know exactly what that costs: this tool times, at ResNet-50
gradient scale (~25M floats, fused by the engine to the 64 MiB
threshold),

1. the raw engine allreduce (numpy in/out — the floor the TF path can
   at best reach), and
2. a graph-mode ``tf.function`` step whose gradients go through
   ``horovod_tpu.tensorflow.allreduce_async`` + ``synchronize`` (the
   enqueue-all-then-wait group path DistributedOptimizer uses),

on an np=2 loopback ring, and prints one JSON line with both ms/step
figures and the implied overhead.  Run:

    python tools/tf_overhead_bench.py            # np=2 loopback
    TF_OVERHEAD_NP=3 python tools/tf_overhead_bench.py
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RANK_CODE = r"""
import json, os, time
import numpy as np
import horovod_tpu as hvd

hvd.init()
n = int(os.environ.get("TF_OVERHEAD_FLOATS", str(25 * 1024 * 1024)))
iters = int(os.environ.get("TF_OVERHEAD_ITERS", "10"))

# 1) raw engine floor: one fused numpy allreduce of the full buffer.
x = np.ones(n, np.float32)
hvd.allreduce(x, name="warm")
t0 = time.perf_counter()
for i in range(iters):
    hvd.allreduce(x, name=f"raw.{i}")
raw_ms = (time.perf_counter() - t0) / iters * 1e3

# 2) TF graph mode: the same bytes as 16 gradient-sized tensors inside
# a tf.function (the DistributedOptimizer shape), via the group path.
import tensorflow as tf
import horovod_tpu.tensorflow as hvd_tf

parts = [tf.ones([n // 16], tf.float32) for _ in range(16)]

@tf.function
def step(ts):
    hs = [hvd_tf.allreduce_async(t, name=f"tfg.{j}")
          for j, t in enumerate(ts)]
    return hvd_tf.synchronize(hs)

step(parts)  # trace + warm
t0 = time.perf_counter()
for _ in range(iters):
    out = step(parts)
tf_ms = (time.perf_counter() - t0) / iters * 1e3
if hvd.rank() == 0:
    print("RESULT " + json.dumps({"raw_ms": round(raw_ms, 1),
                                  "tf_ms": round(tf_ms, 1)}), flush=True)
hvd.shutdown()
"""


def main():
    np_ = int(os.environ.get("TF_OVERHEAD_NP", "2"))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable, "-c", RANK_CODE],
        capture_output=True, text=True, env=env, timeout=900)
    if out.returncode != 0:
        sys.exit(f"rank failure:\n{out.stderr[-2000:]}")
    rec = next(json.loads(line.split(" ", 1)[1])
               for line in out.stdout.splitlines()
               if line.startswith("RESULT "))
    rec.update({
        "metric": f"tf_graph_allreduce_overhead_np{np_}",
        "floats": int(os.environ.get("TF_OVERHEAD_FLOATS",
                                     str(25 * 1024 * 1024))),
        "tf_over_raw": round(rec["tf_ms"] / rec["raw_ms"], 2),
        "unit": "ms/step",
    })
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
