#!/usr/bin/env python
"""Thin compatibility shim: the metric-name lint moved into the hvdlint
suite (``tools/hvdlint/metrics_check.py``, checker name ``metrics``) —
run it via ``python -m tools.hvdlint metrics`` or this legacy CLI:

    python tools/check_metric_names.py

Everything the old module exported (``lint``, ``lint_sections``,
``populated_registry``, ``SECTION_FAMILIES``, ``NAME_RE``,
``HIST_SUFFIXES``, ``main``) re-exports from the new home so existing
test/doc references keep working.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.hvdlint.metrics_check import (  # noqa: E402,F401
    HIST_SUFFIXES, NAME_RE, SECTION_FAMILIES, _metrics_doc_text, lint,
    lint_sections, main, populated_registry)

if __name__ == "__main__":
    sys.exit(main())
