#!/usr/bin/env python
"""Lint the Prometheus metric names exposed by the collective metrics
registry (horovod_tpu/common/metrics.py): every family must be
snake_case, carry the ``hvd_tpu_`` prefix, pair a ``# HELP`` with its
``# TYPE``, and be unique across registry sections — so new metrics can't
silently drift from the naming convention.  Runs against a registry with
one of everything recorded, so every family actually renders.

Tier-1 runs it (tests/test_metrics.py::test_check_metric_names_lint);
standalone:

    python tools/check_metric_names.py
"""

from __future__ import annotations

import re
import sys
from collections import Counter

NAME_RE = re.compile(r"^hvd_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def populated_registry():
    """A registry with at least one sample in every section, so the
    exposition renders every family the code can produce."""
    from horovod_tpu.common import metrics

    reg = metrics.MetricsRegistry()
    reg.record_enqueue("engine", "allreduce", 1024)
    reg.record_bytes_out("engine", 1024)
    reg.record_batch(2)
    reg.record_stall("lint.tensor", 1.0)
    reg.record_fault("crash")
    reg.record_abort("ranks_down")
    reg.record_last_announce(1, 2)
    reg.set_restart_epoch(1)
    reg.record_cache("engine", "hits")
    reg.record_cache("xla", "misses")
    reg.set_cache_size("engine", 1)
    reg.set_membership({"epoch": 1, "size": 3, "reshapes": 1,
                        "ranks_lost": [1], "ranks_joined": [3]})
    reg.record_serving("requests", "lint-tenant")
    reg.record_serving("admitted", "lint-tenant")
    reg.record_serving("rejected", "lint-tenant")
    reg.record_serving("retired", "lint-tenant")
    reg.record_serving_tokens("lint-tenant", "prompt", 8)
    reg.record_serving_tokens("lint-tenant", "generated", 4)
    reg.record_serving_step(2, 4)
    reg.set_serving_gauges(queue_depth=1, active=2, kv_blocks_in_use=3,
                           kv_blocks_total=8)
    reg.set_autotune({
        "enabled": True, "frozen": True, "windows": 3,
        "fusion_threshold": 1 << 20, "cycle_time_ms": 2.5,
        "best_score": 123.4,
        "history": [{"window": 1, "fusion_threshold": 1 << 20,
                     "cycle_time_ms": 2.5, "score": 123.4}],
        "applied": [{"tick": 7, "fusion_threshold": 1 << 20,
                     "cycle_time_ms": 2.5, "frozen": True}],
    })
    for name in metrics.HISTOGRAMS:
        reg.observe(name, 0.001)
    return reg


def lint(text: str) -> list:
    """Return the list of naming-convention violations in a Prometheus
    text exposition (empty = clean)."""
    errors = []
    helps = []
    families = []
    for line in text.splitlines():
        if line.startswith("# HELP "):
            helps.append(line.split()[2])
        elif line.startswith("# TYPE "):
            families.append(line.split()[2])
        elif line.startswith("#"):
            errors.append(f"unexpected comment line: {line!r}")
    for name in families:
        if not NAME_RE.match(name):
            errors.append(
                f"metric family '{name}' violates the naming convention "
                f"(snake_case with hvd_tpu_ prefix)")
        if name not in helps:
            errors.append(f"metric family '{name}' has # TYPE but no "
                          f"# HELP")
    for name in helps:
        if name not in families:
            errors.append(f"metric family '{name}' has # HELP but no "
                          f"# TYPE")
    for name, n in Counter(families).items():
        if n > 1:
            errors.append(
                f"duplicate metric family '{name}': two registry sections "
                f"export the same name")
    declared = set(families)
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample = line.split("{")[0].split(" ")[0]
        base = sample
        for suffix in HIST_SUFFIXES:
            if sample.endswith(suffix) and sample[:-len(suffix)] in declared:
                base = sample[:-len(suffix)]
                break
        if base not in declared:
            errors.append(f"sample '{sample}' has no # TYPE declaration")
    return errors


def main() -> int:
    from horovod_tpu.common import metrics

    text = metrics.prometheus_text(populated_registry().snapshot())
    errors = lint(text)
    for err in errors:
        print(f"check_metric_names: {err}", file=sys.stderr)
    if not errors:
        n = len([l for l in text.splitlines() if l.startswith("# TYPE ")])
        print(f"check_metric_names: OK ({n} metric families)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
