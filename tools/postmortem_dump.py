#!/usr/bin/env python
"""Render a postmortem dump directory into the human story.

``hvdrun --postmortem-dir DIR`` (or ``HVD_TPU_POSTMORTEM_DIR=DIR``) makes
every rank write ``rank-<N>.json`` when it dies a typed death
(docs/troubleshooting.md#reading-a-postmortem).  This tool reads the
directory and tells the story an operator needs at 3am:

    $ python tools/postmortem_dump.py /tmp/pm
    postmortem: 3 dump(s) in /tmp/pm (job size 4)
    rank 0: timeout  rank 2: timeout  rank 3: timeout
    membership epoch 0 on every dumped rank (consistent)
    cross-rank diagnosis: the coordinator is at tick 1841; rank 1 last
      announced 'step.11' at tick 1803 and stopped announcing after that
    waiting-on (rank 0 coordinator view):
      'step.12' stalled 2.1s, waiting on ranks [1]
    rank 0 — last flight-recorder events (engine):
      ... enqueue step.12 / announce step.12 / tick 1803 ...

Options: ``--rank N`` focuses one rank, ``--events K`` sets the ring tail
length (default 12), ``--json`` re-emits the merged view as JSON.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional


def load_dumps(directory: str) -> Dict[int, dict]:
    """rank -> dump doc; restart-epoch-suffixed files win over older
    plain ones when both exist (newest mtime per rank)."""
    by_rank: Dict[int, str] = {}
    for path in glob.glob(os.path.join(directory, "rank-*.json")):
        base = os.path.basename(path)[len("rank-"):-len(".json")]
        rank_s = base.split(".e")[0]
        try:
            rank = int(rank_s)
        except ValueError:
            continue
        if (rank not in by_rank
                or os.path.getmtime(path) > os.path.getmtime(by_rank[rank])):
            by_rank[rank] = path
    dumps = {}
    for rank, path in by_rank.items():
        try:
            with open(path) as f:
                dumps[rank] = json.load(f)
            dumps[rank]["_path"] = path
        except (OSError, ValueError) as exc:
            print(f"postmortem_dump: skipping unreadable {path}: {exc}",
                  file=sys.stderr)
    return dumps


def _fmt_event(e: dict) -> str:
    name = f" {e['name']}" if e.get("name") else ""
    arg = f" ({e['arg']})" if e.get("arg") else ""
    return f"      t+{e['ts_us'] / 1e6:9.3f}s  {e['event']}{name}{arg}"


def render(dumps: Dict[int, dict], events: int = 12,
           only_rank: Optional[int] = None) -> List[str]:
    lines: List[str] = []
    ranks = sorted(dumps)
    size = max((d.get("size", 0) for d in dumps.values()), default=0)
    lines.append(f"postmortem: {len(dumps)} dump(s) for rank(s) "
                 f"{ranks} (job size {size})")
    reasons = {r: dumps[r].get("reason", "?") for r in ranks}
    lines.append("  " + "  ".join(f"rank {r}: {reasons[r]}" for r in ranks))
    epochs = {dumps[r].get("membership_epoch", 0) for r in ranks}
    if len(epochs) <= 1:
        lines.append(f"  membership epoch {epochs.pop() if epochs else 0} "
                     f"on every dumped rank (consistent)")
    else:
        per_rank = {r: dumps[r].get("membership_epoch") for r in ranks}
        lines.append(
            f"  MEMBERSHIP EPOCH DISAGREEMENT across dumps: {per_rank}")
    diagnosis = next((dumps[r].get("diagnosis") for r in ranks
                      if dumps[r].get("diagnosis")), None)
    if diagnosis:
        lines.append(f"  cross-rank diagnosis: {diagnosis}")
    missing = [r for r in range(size) if r not in dumps]
    if missing:
        lines.append(f"  no dump from rank(s) {missing} — these are "
                     f"usually the ranks that died hard (SIGKILL/crash "
                     f"before the writer ran); the survivors' diagnosis "
                     f"and pending tables above name them")
    coord = next((dumps[r] for r in ranks
                  if dumps[r].get("pending", {}).get("coordinator")), None)
    if coord:
        lines.append("  waiting-on (rank 0 coordinator view):")
        for entry in coord["pending"]["coordinator"]:
            lines.append(f"    '{entry['name']}' stalled "
                         f"{entry['age_sec']:.1f}s, waiting on ranks "
                         f"{entry['missing_ranks']}")
    for r in ranks:
        if only_rank is not None and r != only_rank:
            continue
        d = dumps[r]
        lines.append(f"rank {r} ({d.get('_path', '?')}):")
        abort = d.get("abort", {})
        if abort.get("message"):
            head = abort["message"].split(" cross-rank diagnosis: ")[0]
            lines.append(f"    abort[{abort.get('code')}]: {head[:300]}")
        if d.get("exception"):
            lines.append(f"    exception: {d['exception']['type']}: "
                         f"{d['exception']['message'][:200]}")
        transport = d.get("transport") or {}
        if transport:
            peers = transport.get("peers") or {}
            peer_part = ("  peers: " + "  ".join(
                f"{p}={peers[p]}" for p in sorted(
                    peers, key=lambda x: int(x) if x.isdigit() else 0))
                if peers else "")
            lines.append(f"    transport: local hops on "
                         f"{transport.get('local', 'tcp')}{peer_part}")
        pending = d.get("pending", {}).get("local", [])
        if pending:
            lines.append("    in-flight collectives at death:")
            for entry in pending[:8]:
                lines.append(f"      '{entry['name']}' ({entry['op']}) "
                             f"pending {entry['age_sec']:.1f}s")
        for plane in ("engine", "xla"):
            ring = d.get("ring", {}).get(plane, [])
            if not ring:
                continue
            lines.append(f"    last flight-recorder events ({plane}, "
                         f"{len(ring)} in ring):")
            lines.extend(_fmt_event(e) for e in ring[-events:])
    return lines


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Render HVD_TPU_POSTMORTEM_DIR rank dumps into the "
                    "human story (docs/troubleshooting.md).")
    parser.add_argument("directory", help="postmortem dump directory")
    parser.add_argument("--rank", type=int, default=None,
                        help="show only this rank's detail section")
    parser.add_argument("--events", type=int, default=12,
                        help="flight-ring tail length per rank "
                             "(default 12)")
    parser.add_argument("--json", action="store_true",
                        help="emit the merged dumps as one JSON document")
    args = parser.parse_args(argv)
    dumps = load_dumps(args.directory)
    if not dumps:
        print(f"postmortem_dump: no rank-*.json dumps in "
              f"{args.directory}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({str(r): d for r, d in dumps.items()}, indent=2))
        return 0
    for line in render(dumps, events=args.events, only_rank=args.rank):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
