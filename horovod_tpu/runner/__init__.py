"""hvdrun: process launcher (the reference's `mpirun` replacement).

The reference delegates launching to an external `mpirun`
(/root/reference/docs/running.md); TPU pods have no MPI, so horovod_tpu ships
its own launcher.  It allocates the control/data-plane TCP endpoints, exports
the HVD_TPU_* environment consumed by horovod_tpu.common.basics, spawns one
process per rank, and tears the job down if any rank fails.

CLI:  python -m horovod_tpu.runner -np 4 python train.py
API:  from horovod_tpu.runner import run_command / launch_fn
"""

from horovod_tpu.runner.launch import (  # noqa: F401
    RankResult,
    failure_report,
    launch_fn,
    make_rank_env,
    membership_succeeded,
    run_command,
    run_elastic,
    run_hosts,
    run_membership,
    signal_name,
)
