"""Per-local-rank TPU chip pinning for the launcher.

Step 2 of the reference's five-line recipe is "pin one accelerator per
process by local_rank()" (/root/reference/examples/tensorflow_mnist.py:69-71
``config.gpu_options.visible_device_list = str(hvd.local_rank())``;
/root/reference/examples/pytorch_mnist.py:60
``torch.cuda.set_device(hvd.local_rank())``).  On TPU the pinning cannot
live in the user script: chip visibility is fixed at libtpu client
initialization by environment variables.  The launcher therefore computes
the pinning env per rank (``hvdrun --tpu-pin``, or ``HVD_TPU_PIN=1``), and
examples need no edits — the TPU-native analogue of the recipe's step 2.

The libtpu multi-process contract (the one JAX's own multi-process-per-host
setups use):

* ``TPU_VISIBLE_CHIPS``      — the local chip id(s) this process may open.
* ``TPU_CHIPS_PER_PROCESS_BOUNDS`` — per-process chip sub-grid (x,y,z).
* ``TPU_PROCESS_BOUNDS``     — the process grid over the whole slice.
* ``TPU_PROCESS_ADDRESSES``  — every process's coordination endpoint, in
  task-id order.
* ``TPU_PROCESS_PORT`` / ``CLOUD_TPU_TASK_ID`` — this process's endpoint
  and its index into the address list.

Physical chip grids per host are not linear: a 4-chip v5e host is a 2x2
grid, an 8-chip v5e host 4x2.  ``host_chip_grid`` encodes the common
layouts and ``--tpu-topology x,y[,z]`` overrides them for exotic slices.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

# Physical chip grid of one host, by chips-per-host count (v5e/v4 hosts).
DEFAULT_HOST_GRIDS: Dict[int, Tuple[int, int, int]] = {
    1: (1, 1, 1),
    2: (2, 1, 1),
    4: (2, 2, 1),
    8: (4, 2, 1),
}

# Coordination ports sit clear of the engine data ports (port_base+1 ..
# port_base+local_size, runner/hosts.py) and the XLA-plane coordinator
# (port_base+500).
TPU_PORT_OFFSET = 600


def parse_topology(spec: str) -> Tuple[int, int, int]:
    """``"4,2"`` or ``"4x2x1"`` -> (4, 2, 1)."""
    parts = [p for p in spec.replace("x", ",").split(",") if p]
    dims = [int(p) for p in parts]
    if not 1 <= len(dims) <= 3 or any(d < 1 for d in dims):
        raise ValueError(f"bad TPU topology spec: {spec!r}")
    while len(dims) < 3:
        dims.append(1)
    return tuple(dims)  # type: ignore[return-value]


def host_chip_grid(chips_per_host: int,
                   topology: Optional[str] = None) -> Tuple[int, int, int]:
    if topology:
        grid = parse_topology(topology)
        if grid[0] * grid[1] * grid[2] != chips_per_host:
            raise ValueError(
                f"topology {topology!r} has {grid[0] * grid[1] * grid[2]} "
                f"chips, but {chips_per_host} ranks are placed per host")
        return grid
    grid = DEFAULT_HOST_GRIDS.get(chips_per_host)
    if grid is None:
        raise ValueError(
            f"no default chip grid for {chips_per_host} chips per host; "
            "pass --tpu-topology x,y[,z]")
    return grid


def pin_env(rank: int, local_rank: int, chips_per_host: int,
            host_index: int, n_hosts: int,
            addresses: Sequence[str],
            topology: Optional[str] = None) -> Dict[str, str]:
    """Environment confining launcher rank ``rank`` to one local chip.

    ``addresses``: every rank's ``host:port`` coordination endpoint, in
    rank order (rank order must equal task-id order — hvdrun places ranks
    in contiguous blocks per host, which libtpu's host-major process
    numbering matches).  Multi-chip-per-process layouts can keep using
    plain jax.distributed without pinning; this covers the
    one-process-per-chip model of the reference examples.
    """
    gx, gy, gz = host_chip_grid(chips_per_host, topology)
    # Process grid: hosts stack along y (host-major), chips within a host
    # along (x, y) of the host grid.  One chip per process.
    process_bounds = f"{gx},{gy * n_hosts},{gz}"
    return {
        "TPU_VISIBLE_CHIPS": str(local_rank),
        "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
        "TPU_PROCESS_BOUNDS": process_bounds,
        "TPU_PROCESS_ADDRESSES": ",".join(addresses),
        "TPU_PROCESS_PORT": addresses[rank].rsplit(":", 1)[1],
        "CLOUD_TPU_TASK_ID": str(rank),
    }


def pin_addresses(placements: Sequence[Tuple[str, int]],
                  port_base: int) -> List[str]:
    """``host:port`` per rank for TPU_PROCESS_ADDRESSES: the host's
    address with a per-local-rank port above the engine's port range."""
    return [f"{host}:{port_base + TPU_PORT_OFFSET + lr}"
            for host, lr in placements]


def pinning_requested(flag: Optional[bool] = None) -> bool:
    if flag is not None:
        return flag
    return os.environ.get("HVD_TPU_PIN", "0") not in ("", "0", "false")
