"""Multi-host job planning for hvdrun (the `mpirun -H host1:2,host2:2`
replacement, /root/reference/docs/running.md).

A host spec assigns ranks to hosts in contiguous blocks (host order, then
slot order), which defines local_rank/local_size and satisfies BOTH
engine layout contracts that key off it: the two-level data topology
(docs/performance.md#two-level-topology) and the control-plane
coordinator tree (docs/performance.md#control-plane-scaling), under
which each host's local-rank-0 becomes the sub-coordinator for its
block — its node's control sockets multiplex over the same per-rank
data listen port via a typed hello, so no extra ports are planned here.
With
HOROVOD_HIERARCHICAL_ALLREDUCE, every local rank drives its OWN
cross-node (DCN) stream to its same-local-rank peers — rank
``node*L + r`` connects to ``(node±1)*L + r`` and, for the tree
exchange, to ``(node^2^k)*L + r`` — so equal ``local_size`` on every
host and contiguous rank blocks are required (the engine validates this
job-wide at init and falls back to the flat ring otherwise).  Endpoints
use fixed, configurable ports (free-port probing is impossible on remote
hosts): the coordinator lives on the first host at ``port_base``; each
rank's data endpoint is ``host:port_base + 1 + local_rank``, and the
intra-node ring, cross-node rings, and tree partners all multiplex over
each rank's single data listen port via typed hellos.

Remote ranks are started over ``ssh`` with the rank environment inlined
into the remote command; local ranks spawn directly.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import socket
from typing import Dict, List, Optional, Sequence

DEFAULT_PORT_BASE = 58930


@dataclasses.dataclass(frozen=True)
class RankPlacement:
    rank: int
    host: str
    local_rank: int
    local_size: int
    env: Dict[str, str]  # HVD_TPU_* for this rank

    @property
    def is_local(self) -> bool:
        return is_local_host(self.host)


def is_local_host(host: str) -> bool:
    if host in ("localhost", "127.0.0.1", "::1"):
        return True
    try:
        names = {socket.gethostname(), socket.getfqdn()}
    except OSError:  # pragma: no cover
        names = set()
    return host in names


def parse_hosts(spec: str) -> List:
    """``"host1:2,host2:4"`` -> [("host1", 2), ("host2", 4)].  A bare host
    means 1 slot; repeated hosts merge their slots (as mpirun's -H does),
    keeping first-appearance order — duplicates must not produce colliding
    local ranks/data ports."""
    order: List[str] = []
    slots_by_host: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("["):  # bracketed IPv6: "[::1]" or "[::1]:2"
            host, rest = part[1:].split("]", 1)
            n = int(rest[1:]) if rest.startswith(":") else 1
        elif part.count(":") == 1:  # "host:slots"
            host, slots = part.split(":")
            n = int(slots)
        else:  # bare host, incl. unbracketed IPv6 literals
            host, n = part, 1
        if n < 1:
            raise ValueError(f"bad slot count in host spec: {part!r}")
        if host not in slots_by_host:
            order.append(host)
            slots_by_host[host] = 0
        slots_by_host[host] += n
    if not order:
        raise ValueError(f"empty host spec: {spec!r}")
    return [(h, slots_by_host[h]) for h in order]


def plan(np_: int, hosts_spec: str,
         port_base: int = DEFAULT_PORT_BASE,
         tpu_pin: bool = False,
         tpu_topology: Optional[str] = None) -> List[RankPlacement]:
    """Assign `np_` ranks across the host spec in contiguous blocks.

    With ``tpu_pin``, each rank's env additionally confines its libtpu
    client to one local chip by ``local_rank`` (runner/tpu_pin.py) — the
    TPU analogue of the reference recipe's
    ``visible_device_list = str(hvd.local_rank())`` step.
    """
    hosts = parse_hosts(hosts_spec)
    capacity = sum(n for _, n in hosts)
    if np_ > capacity:
        raise ValueError(
            f"-np {np_} exceeds the {capacity} slots in the host spec")
    placements: List[tuple] = []  # (host, local_rank)
    for host, slots in hosts:
        for s in range(slots):
            if len(placements) < np_:
                placements.append((host, s))
    # local_size = ranks actually placed on the host (last host may be
    # partially filled).
    per_host: Dict[str, int] = {}
    for host, _ in placements:
        per_host[host] = per_host.get(host, 0) + 1

    coord = f"{placements[0][0]}:{port_base}"
    # Data ports occupy port_base+1 .. port_base+slots; the XLA data
    # plane's jax.distributed coordinator gets a port well clear of them.
    xla_coord = f"{placements[0][0]}:{port_base + 500}"
    data = [f"{host}:{port_base + 1 + lr}" for host, lr in placements]
    pin_envs: List[Dict[str, str]] = [{} for _ in placements]
    if tpu_pin:
        from horovod_tpu.runner.tpu_pin import pin_addresses, pin_env

        sizes = set(per_host.values())
        if len(sizes) != 1:
            raise ValueError(
                "--tpu-pin requires the same number of ranks on every "
                f"host (got {per_host}); chip grids are per-host uniform")
        chips_per_host = sizes.pop()
        host_order = list(per_host)
        addresses = pin_addresses(placements, port_base)
        pin_envs = [
            pin_env(rank, lr, chips_per_host, host_order.index(host),
                    len(host_order), addresses, tpu_topology)
            for rank, (host, lr) in enumerate(placements)]
    out = []
    for rank, (host, lr) in enumerate(placements):
        env = {
            "HVD_TPU_RANK": str(rank),
            "HVD_TPU_SIZE": str(np_),
            "HVD_TPU_LOCAL_RANK": str(lr),
            "HVD_TPU_LOCAL_SIZE": str(per_host[host]),
            "HVD_TPU_COORD": coord,
            "HVD_TPU_DATA": ",".join(data),
            "HVD_TPU_XLA_COORD": xla_coord,
        }
        env.update(pin_envs[rank])
        out.append(RankPlacement(rank, host, lr, per_host[host], env))
    return out


def ssh_command(placement: RankPlacement, cmd: Sequence[str],
                ssh_args: Sequence[str] = (),
                extra_env: Optional[Dict[str, str]] = None,
                cwd: Optional[str] = None) -> List[str]:
    """The `ssh` argv that runs `cmd` on the placement's host.

    The rank environment (plus ``extra_env``) is inlined into the remote
    command; the remote shell first ``cd``s into ``cwd`` (default: the
    local working directory) when that path exists there, matching
    mpirun's working-directory propagation so relative script paths work.
    """
    env = dict(extra_env or {})
    env.update(placement.env)
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items()))
    cwd = cwd if cwd is not None else os.getcwd()
    remote = (f"cd {shlex.quote(cwd)} 2>/dev/null; env {exports} "
              + " ".join(shlex.quote(c) for c in cmd))
    return ["ssh", *ssh_args, placement.host, remote]
