from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence

from horovod_tpu.common.basics import pick_free_port


@dataclasses.dataclass
class RankResult:
    rank: int
    returncode: int
    stdout: str
    stderr: str
    # True on the rank the launcher saw fail FIRST — the one whose error is
    # the real one; later nonzero exits are usually the kill cascade.
    first_failure: bool = False


def signal_name(returncode: int) -> str:
    """Human label for a rank exit code: 'SIGKILL (signal 9)' for signal
    deaths (negative returncodes, the subprocess convention), or the plain
    code otherwise."""
    if returncode >= 0:
        return str(returncode)
    import signal

    try:
        name = signal.Signals(-returncode).name
    except ValueError:
        name = f"signal {-returncode}"
    return f"{name} (signal {-returncode})"


def failure_report(results, tail_lines: int = 30,
                   postmortem_dir: Optional[str] = None) -> str:
    """One-stop failure summary: every failing rank labeled (signal names
    included), then the FIRST-failing rank's stderr tail — the root cause,
    ahead of the kill cascade's -9 noise.  With a postmortem dir set
    (``--postmortem-dir`` / ``HVD_TPU_POSTMORTEM_DIR``), points at the
    first-failing rank's dump and repeats the coordinator's cross-rank
    diagnosis next to the tail."""
    lines = []
    first = None
    for r in results:
        if r.returncode == 0:
            continue
        marker = "  <- first failure" if r.first_failure else ""
        lines.append(
            f"rank {r.rank} exited with {signal_name(r.returncode)}{marker}")
        if r.first_failure:
            first = r
    if first is None:  # no flagged rank (e.g. all died in the same sweep)
        first = next((r for r in results if r.returncode != 0), None)
    if first is not None and first.stderr:
        tail = first.stderr.strip().splitlines()[-tail_lines:]
        lines.append(f"--- rank {first.rank} stderr (last {len(tail)} "
                     f"lines) ---")
        lines.extend(tail)
    directory = (postmortem_dir
                 or os.environ.get("HVD_TPU_POSTMORTEM_DIR") or "")
    if first is not None and directory:
        lines.extend(_postmortem_lines(directory, first.rank))
    return "\n".join(lines)


def _postmortem_lines(directory: str, first_rank: int) -> List[str]:
    """Postmortem pointers for the failure report: the first-failing
    rank's dump path (a crashed-before-init rank may have none — fall
    back to any rank's) and the cross-rank diagnosis, read from whichever
    dump carries it (the coordinator broadcast it to every survivor)."""
    import glob
    import json

    from horovod_tpu.common import postmortem as _postmortem

    lines: List[str] = []
    path = _postmortem.dump_path_for(directory, first_rank)
    all_dumps = sorted(glob.glob(os.path.join(directory, "rank-*.json")))
    if path is None and all_dumps:
        path = all_dumps[0]
    if path is None:
        return lines
    lines.append(f"postmortem: {path}"
                 + (f" (+{len(all_dumps) - 1} more rank dump(s); render "
                    f"with tools/postmortem_dump.py {directory})"
                    if len(all_dumps) > 1 else ""))
    diagnosis = None
    transport = None
    for candidate in ([path] + [p for p in all_dumps if p != path]):
        try:
            with open(candidate) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if transport is None:
            transport = doc.get("transport")
        if diagnosis is None:
            diagnosis = doc.get("diagnosis")
        if diagnosis and transport:
            break
    if diagnosis:
        lines.append(f"cross-rank diagnosis: {diagnosis}")
    # Which data-plane transport each link ran on when the rank died
    # (docs/performance.md#transport): a fault on a same-host link behaves
    # differently over shm rings than over TCP sockets, so the report
    # names the active path per peer up front.
    if transport:
        peers = transport.get("peers") or {}
        peer_part = ("  peers: " + "  ".join(
            f"{p}={peers[p]}" for p in sorted(
                peers, key=lambda x: int(x) if x.isdigit() else 0))
            if peers else "")
        lines.append(f"transport: local hops on "
                     f"{transport.get('local', 'tcp')}{peer_part}")
    return lines


def _shm_job_prefix(coord: str) -> str:
    """FNV-1a-32 of the coordinator endpoint, matching the engine's
    ``ShmSegmentName`` (engine/cc/transport.cc): every shared-memory
    segment a job keyed on this coordinator can create is named
    ``hvdtpu_<hash>_n<node>_e<epoch>`` under /dev/shm."""
    h = 2166136261
    for b in coord.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return f"hvdtpu_{h:08x}_"


def sweep_shm_segments(coord: str) -> List[str]:
    """Unlink every /dev/shm segment left by the job keyed on ``coord``;
    returns the names removed.  The engine unlinks its own segment the
    moment all local ranks have attached, and again on every typed-death
    path, so residue is only possible when a rank dies inside the narrow
    create-to-attach window (e.g. SIGKILL from an injected crash).  The
    launcher sweeps after every attempt — success included, where it is a
    no-op — so even that window cannot leak across a --max-restarts
    relaunch or past job exit.  Local filesystem only: remote (ssh) ranks
    rely on the engine's own unlink paths."""
    removed: List[str] = []
    prefix = _shm_job_prefix(coord)
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return removed
    for name in names:
        if name.startswith(prefix):
            try:
                os.unlink(os.path.join("/dev/shm", name))
                removed.append(name)
            except OSError:
                pass
    return removed


def make_rank_env(rank: int, size: int, coord: str, data: Sequence[str],
                  base_env: Optional[Dict[str, str]] = None,
                  local_rank: Optional[int] = None,
                  local_size: Optional[int] = None,
                  xla_coord: Optional[str] = None) -> Dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    env["HVD_TPU_RANK"] = str(rank)
    env["HVD_TPU_SIZE"] = str(size)
    env["HVD_TPU_LOCAL_RANK"] = str(local_rank if local_rank is not None else rank)
    env["HVD_TPU_LOCAL_SIZE"] = str(local_size if local_size is not None else size)
    env["HVD_TPU_COORD"] = coord
    env["HVD_TPU_DATA"] = ",".join(data)
    if xla_coord:
        env["HVD_TPU_XLA_COORD"] = xla_coord
    # Sanitized engine builds (docs/contributing.md#sanitized-engine
    # -builds): the instrumented libhvdtpu.<mode>.so needs the sanitizer
    # runtime preloaded into the RANK processes — but preloading the
    # launcher's own python wedges it (TSan interceptors vs the rank
    # multiplexing), so hvdrun resolves and injects LD_PRELOAD here
    # instead of asking users to export it job-wide.  A pre-existing
    # LD_PRELOAD (jemalloc etc.) is composed with, sanitizer first —
    # skipping it would dlopen the instrumented engine without its
    # runtime and die in __tsan init.
    if env.get("HVD_TPU_SANITIZE"):
        from horovod_tpu.engine.build import sanitizer_preload

        preload = None  # None = bad mode (the rank's build() raises too)
        try:
            preload = sanitizer_preload(env["HVD_TPU_SANITIZE"].strip()
                                        .lower())
        except ValueError as exc:
            _warn_sanitize_once(str(exc))
        existing = env.get("LD_PRELOAD", "")
        if preload:
            if preload not in existing.split(":"):
                env["LD_PRELOAD"] = (f"{preload}:{existing}" if existing
                                     else preload)
        elif preload == "" and not any(
                runtime in existing
                for runtime in ("tsan", "asan", "ubsan")):
            # Fail loudly up front: without the runtime every rank would
            # dlopen the instrumented engine and die in __tsan/__asan
            # init with N identical cryptic errors.  (A user-supplied
            # LD_PRELOAD that already names a sanitizer runtime is the
            # one case resolution failure is fine.)
            _warn_sanitize_once(
                f"HVD_TPU_SANITIZE={env['HVD_TPU_SANITIZE']} is set but "
                f"the sanitizer runtime could not be resolved "
                f"(g++ -print-file-name); ranks will likely fail to load "
                f"the instrumented engine. Install the libsanitizer "
                f"runtime or set LD_PRELOAD yourself.")
    return env


# Launch-time sanitizer diagnostics already emitted (make_rank_env runs
# once PER RANK; the job needs each warning once).
_sanitize_warned: set = set()


def _warn_sanitize_once(msg: str) -> None:
    if msg not in _sanitize_warned:
        _sanitize_warned.add(msg)
        print(f"hvdrun: WARNING: {msg}", file=sys.stderr)


def allocate_endpoints(size: int, host: str = "127.0.0.1", extra: int = 0):
    """Coordinator + per-rank data endpoints, picked as ONE held batch
    (pick_free_ports) so no port is handed out twice within a launch.
    ``extra`` reserves additional ports in the same batch; they come
    back as a third element when requested."""
    from horovod_tpu.common.basics import pick_free_ports

    ports = pick_free_ports(size + 1 + extra, host)
    coord = f"{host}:{ports[0]}"
    data = [f"{host}:{p}" for p in ports[1:size + 1]]
    if extra:
        return coord, data, ports[size + 1:]
    return coord, data


def _kill_grace_sec() -> float:
    """How long a finished/failed job waits for its remaining ranks to
    exit on their own before SIGKILLing them (the engine cascades a
    coordinated shutdown/abort, so healthy ranks exit well within this).
    Tunable so fault-injection tests with deliberately wedged ranks stay
    fast; shared by the static and elastic launchers."""
    try:
        return float(os.environ.get("HVD_TPU_KILL_GRACE_SEC") or 15.0)
    except ValueError:
        return 15.0


class _StderrTee:
    """Echo one rank's stderr to the launcher's stderr line-by-line while
    retaining the last N lines.  Non-capture runs (the hvdrun CLI) keep
    their live streaming AND get a first-failing-rank tail in the failure
    report — without buffering whole-job output in memory."""

    def __init__(self, pipe, tail_lines: int = 80):
        import collections
        import threading

        self._pipe = pipe
        self._tail = collections.deque(maxlen=tail_lines)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for line in self._pipe:
                sys.stderr.write(line)
                self._tail.append(line)
        except (ValueError, OSError):
            pass  # pipe torn down mid-read (kill cascade)
        finally:
            try:
                self._pipe.close()
            except OSError:
                pass

    def join(self, timeout: float) -> None:
        self._thread.join(timeout)

    def text(self) -> str:
        return "".join(self._tail)


def run_command(cmd: Sequence[str], np: int,
                env: Optional[Dict[str, str]] = None,
                timeout: float = 300.0,
                capture: bool = False,
                host: str = "127.0.0.1",
                tpu_pin: bool = False,
                tpu_topology: Optional[str] = None) -> List[RankResult]:
    """Launch `cmd` as `np` local ranks; wait for all; kill all on any
    failure.  Returns per-rank results (stdout/stderr only if capture).
    ``tpu_pin`` confines each rank's libtpu client to the chip matching
    its local_rank (runner/tpu_pin.py)."""
    # One held batch for every port this launch needs — separate picks
    # can collide with each other once their probe sockets close.
    coord, data, spare = allocate_endpoints(
        np, host, extra=1 + (np if tpu_pin else 0))
    xla_coord = f"{host}:{spare[0]}"
    pin_envs = [{} for _ in range(np)]
    if tpu_pin:
        from horovod_tpu.runner.tpu_pin import pin_env

        addresses = [f"{host}:{p}" for p in spare[1:]]
        pin_envs = [pin_env(r, r, np, 0, 1, addresses, tpu_topology)
                    for r in range(np)]
    procs = []
    tees = []
    for r in range(np):
        rank_env = make_rank_env(r, np, coord, data, env,
                                 xla_coord=xla_coord)
        rank_env.update(pin_envs[r])
        p = subprocess.Popen(
            list(cmd),
            env=rank_env,
            stdout=subprocess.PIPE if capture else None,
            stderr=subprocess.PIPE,
            text=True, start_new_session=True)
        # Non-capture: tee stderr (live echo + retained tail for the
        # failure report).  Capture: communicate() drains it as before.
        tees.append(None if capture else _StderrTee(p.stderr))
        procs.append(p)
    try:
        return _wait_all(cmd, procs, timeout, tees)
    finally:
        # Typed aborts, injected crashes, timeouts, clean exits alike:
        # no attempt may strand a /dev/shm segment (see sweep docstring).
        sweep_shm_segments(coord)


def run_hosts(cmd: Sequence[str], np: int, hosts_spec: str,
              port_base: Optional[int] = None,
              env: Optional[Dict[str, str]] = None,
              timeout: float = 3e7,
              capture: bool = False,
              ssh_args: Sequence[str] = (),
              tpu_pin: bool = False,
              tpu_topology: Optional[str] = None) -> List[RankResult]:
    """Launch `cmd` across a host spec ("host1:2,host2:2"): local ranks
    spawn directly, remote ranks over ssh (the `mpirun -H` replacement,
    /root/reference/docs/running.md).  Keys of `env` that differ from this
    process's environment are forwarded to remote ranks too (inlined into
    the ssh command), so overrides like PYTHONPATH reach every rank."""
    from horovod_tpu.runner.hosts import DEFAULT_PORT_BASE, plan, ssh_command

    placements = plan(np, hosts_spec, port_base or DEFAULT_PORT_BASE,
                      tpu_pin=tpu_pin, tpu_topology=tpu_topology)
    base_env = dict(env if env is not None else os.environ)
    overrides = {k: v for k, v in base_env.items()
                 if os.environ.get(k) != v}
    # Remote ranks get a fresh login environment from ssh, not this
    # process's: forward the accelerator/runtime selection explicitly so
    # a remote rank resolves the same platform and imports as a local one
    # (mpirun inherited these wholesale; ssh does not).
    for key in ("JAX_PLATFORMS", "PYTHONPATH", "XLA_FLAGS",
                "HVD_TPU_XLA_DATA_PLANE", "HOROVOD_XLA_DATA_PLANE"):
        if key in base_env:
            overrides.setdefault(key, base_env[key])
    procs = []
    tees = []
    for p in placements:
        rank_env = dict(base_env)
        rank_env.update(p.env)
        argv = list(cmd) if p.is_local else ssh_command(
            p, cmd, ssh_args, extra_env=overrides)
        proc = subprocess.Popen(
            argv, env=rank_env,
            stdout=subprocess.PIPE if capture else None,
            stderr=subprocess.PIPE,
            text=True, start_new_session=True)
        tees.append(None if capture else _StderrTee(proc.stderr))
        procs.append(proc)
    try:
        return _wait_all(cmd, procs, timeout, tees)
    finally:
        # Local ranks' segments only; remote hosts clean their own via
        # the engine's unlink-on-death paths.
        sweep_shm_segments(placements[0].env.get("HVD_TPU_COORD", "")
                           if placements else "")


def _kill_rank(p) -> None:
    """Kill a rank and everything it spawned.  Ranks start in their own
    session (start_new_session=True), so killing the process group reaches
    grandchildren too — a rank that exec'd through a shell (the ssh path)
    would otherwise leave a descendant holding the stdout/stderr pipes,
    and communicate() below would block on them long past the timeout."""
    import signal

    try:
        os.killpg(p.pid, signal.SIGKILL)
    except OSError:
        p.kill()


def _wait_all(cmd: Sequence[str], procs, timeout: float,
              tees: Optional[List[Optional["_StderrTee"]]] = None
              ) -> List[RankResult]:
    import time

    # Poll all ranks; when one fails, give the rest a grace period (the
    # engine cascades a coordinated shutdown/abort to every rank) and then
    # kill stragglers -- the fail-fast the reference left to mpirun.  The
    # grace is tunable (HVD_TPU_KILL_GRACE_SEC) so fault-injection tests
    # with deliberately wedged ranks stay fast.
    grace_sec = _kill_grace_sec()
    # A rank exiting rc 0 while its peers keep running for MINUTES means
    # the job can never form or finish (synchronous SPMD completes in
    # lockstep): a rank that dies cleanly before init() completes — e.g.
    # during a --max-restarts relaunch window — would otherwise park the
    # remaining ranks in their connect retries until the TOTAL --timeout
    # budget (often unbounded) burned, with no failure report.  Kill the
    # stragglers after a bounded completion grace instead, so the attempt
    # fails fast, counts against --max-restarts, and carries the stderr
    # tail.  The default is deliberately generous — legitimate post-
    # barrier work (rank 0 writing a large final checkpoint after the
    # workers exited) must fit inside it; <= 0 disables the deadline.
    try:
        straggler_sec = float(
            os.environ.get("HVD_TPU_EXIT_STRAGGLER_SEC") or 300.0)
    except ValueError:
        straggler_sec = 300.0
    deadline = time.monotonic() + timeout
    grace_deadline = None
    zero_exit_deadline = None
    first_failed = None  # rank index of the first observed nonzero exit
    timed_out = False
    try:
        # Poll EVERY rank each pass (a short-circuiting any(p.poll()...)
        # would stop at the first live rank and never populate the
        # returncodes the deadline scans below read).
        while sum(1 for p in procs if p.poll() is None):
            now = time.monotonic()
            if grace_deadline is None:
                failed = [i for i, p in enumerate(procs)
                          if p.returncode not in (None, 0)]
                if failed:
                    first_failed = failed[0]
                    grace_deadline = now + grace_sec
            if (straggler_sec > 0 and zero_exit_deadline is None
                    and any(p.returncode == 0 for p in procs)):
                zero_exit_deadline = now + straggler_sec
            if (now >= deadline or (grace_deadline and now >= grace_deadline)
                    or (zero_exit_deadline and now >= zero_exit_deadline)):
                timed_out = now >= deadline
                for p in procs:
                    if p.poll() is None:
                        _kill_rank(p)
                break
            time.sleep(0.05)
    except BaseException:
        # Ctrl-C / SIGTERM on the launcher: ranks run in their own
        # sessions (no terminal signal fan-out), so propagate the kill
        # to every rank group before re-raising.
        for p in procs:
            if p.poll() is None:
                _kill_rank(p)
        raise
    results = _collect_results(procs, tees, first_failed=first_failed)
    if timed_out:
        raise subprocess.TimeoutExpired(cmd, timeout)
    return results


def _collect_results(procs, tees,
                     first_failed: Optional[int] = None) -> List[RankResult]:
    """Drain every launched process into a :class:`RankResult` after the
    polling loop decided the job is over: bounded waits, group-kill of
    anything (or any orphan sharing its pipes) that survives them, and
    stdout/stderr salvage — shared by ``_wait_all`` and
    ``run_membership``."""
    results = []
    for r, p in enumerate(procs):
        tee = tees[r] if tees else None
        if tee is not None:
            # Tee'd stderr is drained by its thread; only wait for the
            # process (communicate() would race the reader on the pipe).
            try:
                p.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                _kill_rank(p)
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
            tee.join(timeout=5.0)
            out, errout = "", tee.text()
        else:
            try:
                out, errout = p.communicate(timeout=30.0)
            except subprocess.TimeoutExpired:
                # A straggler (or an orphan sharing its pipes) survived:
                # kill its group and salvage what it wrote; never hang the
                # launcher.
                _kill_rank(p)
                try:
                    out, errout = p.communicate(timeout=5.0)
                except subprocess.TimeoutExpired:
                    out, errout = "", ""
        rc = p.returncode if p.returncode is not None else -9
        results.append(RankResult(r, rc, out or "", errout or "",
                                  first_failure=(r == first_failed)))
    return results


def _elastic_bounds(np: int, min_np: Optional[int],
                    max_np: Optional[int]) -> Tuple[int, int]:
    """Normalize and validate the elastic membership bounds — the ONE
    place the rules live (run_elastic, run_membership, and the CLI all
    route through it).  An unset --min-np means "all launched ranks must
    finish", NOT "one survivor is enough"; an unset --max-np means no
    planned growth."""
    # `is not None`, not truthiness: an explicit --min-np 0 must reach the
    # range check and be rejected, not silently read as "unset".
    min_np = min_np if min_np is not None else np
    max_np = max_np if max_np is not None else np
    if not (1 <= min_np <= np <= max_np):
        raise ValueError(
            f"need 1 <= min-np ({min_np}) <= np ({np}) <= max-np ({max_np})")
    return min_np, max_np


def _check_elastic_support(hosts_spec: Optional[str],
                           tpu_pin: bool) -> None:
    """Reject launcher features elastic membership cannot compose with
    yet, loudly, instead of silently dropping them."""
    if hosts_spec:
        raise ValueError(
            "elastic membership (min_np/max_np) supports single-host "
            "launches only")
    if tpu_pin:
        raise ValueError(
            "elastic membership (min_np/max_np) does not support TPU "
            "chip pinning yet: standby ranks have no stable local_rank "
            "to pin to")


def run_elastic(cmd: Sequence[str], np: int, max_restarts: int = 0,
                env: Optional[Dict[str, str]] = None,
                timeout: float = 300.0,
                capture: bool = False,
                host: str = "127.0.0.1",
                hosts_spec: Optional[str] = None,
                port_base: Optional[int] = None,
                tpu_pin: bool = False,
                tpu_topology: Optional[str] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                max_rejoins: Optional[int] = None,
                report: Callable[[str], None] = None):
    """Job-level restart (docs/fault-tolerance.md): launch the job, and on
    failure — any rank exiting nonzero, or the job timing out — group-kill
    the survivors (``_wait_all`` already does) and relaunch ALL ranks with
    ``HVD_TPU_RESTART_EPOCH`` incremented, up to ``max_restarts`` times.
    Fresh endpoints are allocated per attempt, so a crashed job's
    lingering sockets cannot poison the relaunch.  Returns
    ``(results, restarts_used)``; the caller's training script is expected
    to resume from its latest checkpoint (see
    ``horovod_tpu.jax.train.load_latest_checkpoint`` / the keras
    ``BroadcastGlobalVariablesCallback`` glue).

    With ``min_np``/``max_np`` set (``hvdrun --min-np/--max-np``), each
    attempt runs under the elastic membership launcher
    (:func:`run_membership`): rank deaths shrink the job in place and
    standbys rejoin, with NO relaunch as long as at least ``min_np``
    members survive.  Only when elastic continuation fails — the
    coordinator died, or survivors fell below ``min_np`` — does the
    attempt count as a failure and the full relaunch + checkpoint-resume
    fallback above kick in."""
    import time

    if report is None:
        def report(msg):
            print(msg, file=sys.stderr, flush=True)
    elastic = min_np is not None or max_np is not None
    if elastic:
        # Normalize the bounds HERE so the success verdict below uses the
        # same floor run_membership enforces (an unset --min-np means "all
        # launched ranks must finish", NOT "one survivor is enough").
        min_np, max_np = _elastic_bounds(np, min_np, max_np)
        _check_elastic_support(hosts_spec, tpu_pin)
    base_env = dict(env if env is not None else os.environ)
    results: List[RankResult] = []
    # `timeout` is the TOTAL wall-clock budget across every attempt (the
    # --timeout contract: "kill the job after this many seconds"), not a
    # per-attempt allowance that restarts would multiply.
    deadline = time.monotonic() + timeout
    for epoch in range(max_restarts + 1):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise subprocess.TimeoutExpired(list(cmd), timeout)
        run_env = dict(base_env)
        run_env["HVD_TPU_RESTART_EPOCH"] = str(epoch)
        try:
            if elastic:
                results = run_membership(cmd, np, min_np=min_np,
                                         max_np=max_np, env=run_env,
                                         timeout=remaining,
                                         capture=capture, host=host,
                                         max_rejoins=max_rejoins,
                                         report=report)
            elif hosts_spec:
                results = run_hosts(cmd, np, hosts_spec,
                                    port_base=port_base, env=run_env,
                                    timeout=remaining, capture=capture,
                                    tpu_pin=tpu_pin,
                                    tpu_topology=tpu_topology)
            else:
                results = run_command(cmd, np, env=run_env,
                                      timeout=remaining,
                                      capture=capture, host=host,
                                      tpu_pin=tpu_pin,
                                      tpu_topology=tpu_topology)
        except subprocess.TimeoutExpired:
            if epoch == max_restarts:
                raise
            report(f"hvdrun: job timed out (restart epoch {epoch}); "
                   f"restarting ({epoch + 1}/{max_restarts})")
            continue
        ok = (membership_succeeded(results, min_np) if elastic
              else all(r.returncode == 0 for r in results))
        if ok:
            return results, epoch
        if epoch < max_restarts:
            rpt = failure_report(results)
            report(f"hvdrun: job failed (restart epoch {epoch}):"
                   + (f"\n{rpt}" if rpt else "")
                   + f"\nhvdrun: restarting ({epoch + 1}/{max_restarts})")
    return results, max_restarts


def run_membership(cmd: Sequence[str], np: int,
                   min_np: Optional[int] = None,
                   max_np: Optional[int] = None,
                   env: Optional[Dict[str, str]] = None,
                   timeout: float = 300.0,
                   capture: bool = False,
                   host: str = "127.0.0.1",
                   rejoin_delay: float = 1.0,
                   max_rejoins: Optional[int] = None,
                   report: Callable[[str], None] = None) -> List[RankResult]:
    """Elastic membership launcher (``hvdrun --min-np/--max-np``,
    docs/fault-tolerance.md#elastic-membership).

    Launches ``np`` ranks with ``HVD_TPU_ELASTIC=1``.  Unlike
    :func:`run_command`, a dying rank does NOT trigger the kill cascade:
    the engine reshapes the job around the survivors, so the launcher
    keeps the job alive while at least ``min_np`` ranks (the coordinator
    included) are still running, and — while membership is below
    ``max_np`` — spawns standby replacements (``HVD_TPU_REJOIN=1``, a
    fresh data endpoint) that register with the live coordinator and are
    admitted at the next reshape barrier.

    Fatal cases kill everything and return failing results so an outer
    ``run_elastic(..., max_restarts=N)`` can fall back to the
    full-relaunch + checkpoint-resume path: the coordinator (launch rank
    0) dying, or the running count dropping below ``min_np``.

    Returns one :class:`RankResult` per process ever launched — the
    initial ranks keep their launch indices, standbys are numbered from
    ``np`` up.
    """
    import time

    if report is None:
        def report(msg):
            print(msg, file=sys.stderr, flush=True)
    min_np, max_np = _elastic_bounds(np, min_np, max_np)
    if max_rejoins is None:
        # Budget both the planned growth toward max_np (launching below
        # it is legitimate: -np 2 --max-np 6 starts small and grows) and
        # crash replacements, so initial backfill cannot exhaust the
        # budget real failures need later.
        max_rejoins = 2 * max_np
    coord, data = allocate_endpoints(np, host)
    base_env = dict(env if env is not None else os.environ)
    base_env["HVD_TPU_ELASTIC"] = "1"
    base_env["HVD_TPU_MIN_NP"] = str(min_np)

    procs: List = []
    tees: List = []

    def spawn(rank_env):
        p = subprocess.Popen(
            list(cmd), env=rank_env,
            stdout=subprocess.PIPE if capture else None,
            stderr=subprocess.PIPE,
            text=True, start_new_session=True)
        procs.append(p)
        tees.append(None if capture else _StderrTee(p.stderr))
        return p

    for r in range(np):
        spawn(make_rank_env(r, np, coord, data, base_env))

    grace_sec = _kill_grace_sec()
    deadline = time.monotonic() + timeout
    completion_deadline = None  # armed when the first rank finishes rc 0
    rejoin_at = None            # next standby spawn time
    rejoins_used = 0
    fatal = False
    reported_dead: set = set()
    first_dead = None  # slot of the CHRONOLOGICALLY first death observed
    try:
        while any(p.poll() is None for p in procs):
            now = time.monotonic()
            running = sum(1 for p in procs if p.poll() is None)
            completed = sum(1 for p in procs if p.returncode == 0)
            for i, p in enumerate(procs):
                if p.returncode not in (None, 0) and i not in reported_dead:
                    if first_dead is None:
                        first_dead = i
                    reported_dead.add(i)
                    # 1-based to match the "spawning standby N" line.
                    label = (f"rank {i}" if i < np
                             else f"standby {i - np + 1} (slot {i})")
                    report(f"hvdrun: {label} exited with "
                           f"{signal_name(p.returncode)}; "
                           f"{running} member(s) still running "
                           f"(elastic min-np {min_np})")
            if procs[0].poll() is not None and procs[0].returncode != 0:
                # The coordinator owns membership; without it nothing can
                # reshape.  Fall back to the outer restart path.
                report("hvdrun: coordinator (rank 0) died; elastic "
                       "continuation impossible")
                fatal = True
                break
            if completed:
                # Synchronous SPMD finishes in lockstep: once one member
                # completed, the rest (admitted standbys included) should
                # follow within the grace.  Stragglers past it are wedged.
                if completion_deadline is None:
                    completion_deadline = now + max(grace_sec, 5.0)
                if now >= completion_deadline:
                    # Wedged stragglers — and standbys still waiting for
                    # an admission that will never come — get killed, not
                    # waited out.
                    for p in procs:
                        if p.poll() is None:
                            _kill_rank(p)
                    break
            elif running < min_np:
                report(f"hvdrun: only {running} member(s) running "
                       f"(< min-np {min_np}); giving up on elastic "
                       f"continuation")
                fatal = True
                break
            elif running < max_np and rejoins_used < max_rejoins:
                # Backfill toward max-np with standbys.  The delay keeps a
                # crash-looping command from hot-spawning; each standby
                # gets a fresh endpoint so a dead rank's lingering socket
                # cannot poison the rejoin.
                if rejoin_at is None:
                    rejoin_at = now + rejoin_delay
                elif now >= rejoin_at:
                    rejoin_at = None
                    rejoins_used += 1
                    ep = f"{host}:{pick_free_port(host)}"
                    standby_env = dict(base_env)
                    standby_env.update({
                        "HVD_TPU_REJOIN": "1",
                        "HVD_TPU_RANK": "0", "HVD_TPU_SIZE": "1",
                        "HVD_TPU_LOCAL_RANK": "0", "HVD_TPU_LOCAL_SIZE": "1",
                        "HVD_TPU_COORD": coord, "HVD_TPU_DATA": ep,
                    })
                    report(f"hvdrun: spawning standby {rejoins_used} at {ep} "
                           f"({running}/{max_np} members running)")
                    spawn(standby_env)
            else:
                rejoin_at = None
            if now >= deadline:
                for p in procs:
                    if p.poll() is None:
                        _kill_rank(p)
                raise subprocess.TimeoutExpired(cmd, timeout)
            time.sleep(0.05)
    except BaseException:
        for p in procs:
            if p.poll() is None:
                _kill_rank(p)
        sweep_shm_segments(coord)
        raise
    if fatal:
        for p in procs:
            if p.poll() is None:
                _kill_rank(p)
    results = _collect_results(procs, tees)
    sweep_shm_segments(coord)
    # Flag the CHRONOLOGICALLY first death for the failure report — the
    # lowest-index nonzero exit is often the launcher's own fatal-path
    # kill cascade, not the root cause.  (Success itself is judged by
    # membership_succeeded: coordinator clean + >= min_np clean.)
    if first_dead is not None:
        results[first_dead].first_failure = True
    else:
        for r in results:
            if r.returncode != 0:
                r.first_failure = True
                break
    return results


def membership_succeeded(results: List[RankResult],
                         min_np: int) -> bool:
    """Whether an elastic run (``run_membership``) counts as success:
    the coordinator (slot 0) exited 0 and at least ``min_np`` members
    completed cleanly (deaths the job reshaped around do not fail it)."""
    if not results or results[0].returncode != 0:
        return False
    return sum(1 for r in results if r.returncode == 0) >= min_np


_FN_RUNNER = """\
import pickle, sys
with open(sys.argv[1], 'rb') as f:
    fn = pickle.load(f)
fn()
"""


def launch_fn(fn: Callable[[], None], np: int,
              env: Optional[Dict[str, str]] = None,
              timeout: float = 300.0) -> List[RankResult]:
    """Run a picklable zero-arg callable on every rank (test convenience)."""
    import pickle
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as f:
        pickle.dump(fn, f)
        pkl = f.name
    with tempfile.NamedTemporaryFile(
            mode="w", suffix=".py", delete=False) as f:
        f.write(_FN_RUNNER)
        runner = f.name
    try:
        return run_command([sys.executable, runner, pkl], np, env=env,
                           timeout=timeout, capture=True)
    finally:
        os.unlink(pkl)
        os.unlink(runner)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu training job (mpirun replacement).")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="number of ranks to launch")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host spec 'host1:slots,host2:slots' — ranks "
                             "fill hosts in contiguous blocks; remote hosts "
                             "are reached over ssh (the mpirun -H "
                             "replacement). Default: all ranks local.")
    parser.add_argument("--port-base", type=int, default=None,
                        help="with -H: coordinator port (data ports follow)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address for coordinator/data endpoints "
                             "(single-host mode)")
    parser.add_argument("--timeout", type=float, default=0.0,
                        help="kill the job after this many seconds (0 = none)")
    parser.add_argument("--timeline", default=None, metavar="DIR",
                        help="write one Chrome-trace file per rank under "
                             "DIR (rank0.json, rank1.json, ...; sets "
                             "HVD_TPU_TIMELINE=DIR).  Merge them with "
                             "tools/timeline_merge.py — see "
                             "docs/timeline.md")
    parser.add_argument("--postmortem-dir", default=None, metavar="DIR",
                        help="postmortem plane (docs/troubleshooting.md"
                             "#reading-a-postmortem): every rank writes a "
                             "rank-<N>.json crash/hang dump under DIR on "
                             "typed aborts, injected crashes, and fatal "
                             "exceptions (sets HVD_TPU_POSTMORTEM_DIR); "
                             "the failure report points at the first-"
                             "failing rank's dump.  Render with "
                             "tools/postmortem_dump.py DIR")
    parser.add_argument("--state-dir", default=None, metavar="DIR",
                        help="state plane (docs/fault-tolerance.md"
                             "#state-plane): spill each rank's async "
                             "shard snapshots under DIR (sets "
                             "HVD_TPU_STATE_DIR for every rank and "
                             "every --max-restarts relaunch); scripts "
                             "arm with hvd.state.arm().  Pair with "
                             "HVD_TPU_CKPT_KEEP to bound sharded-"
                             "checkpoint retention")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic membership "
                             "(docs/fault-tolerance.md#elastic-membership): "
                             "keep the job alive while at least this many "
                             "ranks survive — a dying rank shrinks the job "
                             "in place (survivors re-negotiate size/rank "
                             "and resync by root broadcast, no relaunch or "
                             "checkpoint reload); below min-np the "
                             "--max-restarts checkpoint fallback fires")
    parser.add_argument("--max-np", type=int, default=None,
                        help="with --min-np: while membership is below "
                             "this, spawn standby ranks that rejoin the "
                             "live job at the next reshape barrier "
                             "(default: -np)")
    parser.add_argument("--serve", action="store_true",
                        help="serving mode (docs/inference.md): the "
                             "command defaults to the serving entrypoint "
                             "(python -m horovod_tpu.serving); rank 0 "
                             "opens the HTTP front door on "
                             "HVD_TPU_SERVE_PORT / --serve-port.  With "
                             "--min-np the job shrinks around dead ranks "
                             "and keeps serving (standby rejoin is "
                             "disabled: a fresh rank cannot recover the "
                             "in-flight KV state)")
    parser.add_argument("--serve-port", type=int, default=None,
                        help="with --serve: the front-door port (sets "
                             "HVD_TPU_SERVE_PORT)")
    parser.add_argument("--net-fault-spec", default=None, metavar="SPEC",
                        help="network chaos harness (docs/fault-tolerance"
                             ".md#failure-detection): deterministic link-"
                             "fault injection for every rank (sets "
                             "HVD_TPU_NET_FAULT_SPEC), e.g. "
                             "'link=0-1:drop@after=2', "
                             "'partition=0,1/2,3@after=1', "
                             "'link=1-2:delay=5|jitter=3', "
                             "'link=0-3:flaky=0.05'; composes with "
                             "HVD_TPU_FAULT_SPEC process faults")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="on job failure (a rank died, or the engine "
                             "aborted on a dead/stalled rank), kill the "
                             "survivors and relaunch all ranks up to N "
                             "times with HVD_TPU_RESTART_EPOCH "
                             "incremented; training scripts resume from "
                             "their latest checkpoint (see "
                             "docs/fault-tolerance.md)")
    parser.add_argument("--tpu-pin", action="store_true",
                        default=None,
                        help="pin one TPU chip per rank by local_rank "
                             "(TPU_VISIBLE_CHIPS / TPU_PROCESS_BOUNDS; the "
                             "reference recipe's visible_device_list step). "
                             "Also enabled by HVD_TPU_PIN=1.")
    parser.add_argument("--tpu-topology", default=None,
                        help="per-host chip grid 'x,y[,z]' when it differs "
                             "from the built-in table (1/2/4/8 chips)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command, e.g. python train.py")
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        if not args.serve:
            parser.error("no command given")
        cmd = [sys.executable, "-m", "horovod_tpu.serving"]
    if args.serve_port is not None and not args.serve:
        parser.error("--serve-port requires --serve")
    from horovod_tpu.runner.tpu_pin import pinning_requested

    tpu_pin = pinning_requested(args.tpu_pin)
    env = None
    if args.serve_port is not None:
        env = dict(os.environ)
        env["HVD_TPU_SERVE_PORT"] = str(args.serve_port)
    if args.state_dir:
        os.makedirs(args.state_dir, exist_ok=True)
        env = dict(env if env is not None else os.environ)
        env["HVD_TPU_STATE_DIR"] = args.state_dir
    if args.net_fault_spec is not None:
        env = dict(env if env is not None else os.environ)
        env["HVD_TPU_NET_FAULT_SPEC"] = args.net_fault_spec
    if args.postmortem_dir:
        os.makedirs(args.postmortem_dir, exist_ok=True)
        env = dict(env if env is not None else os.environ)
        env["HVD_TPU_POSTMORTEM_DIR"] = args.postmortem_dir
        # The launcher's own failure_report reads the env default too.
        os.environ["HVD_TPU_POSTMORTEM_DIR"] = args.postmortem_dir
    if args.timeline:
        os.makedirs(args.timeline, exist_ok=True)
        env = dict(env if env is not None else os.environ)
        # Trailing separator forces the directory form on EVERY rank —
        # remote (ssh) hosts don't share the launcher's filesystem, so a
        # bare path that only exists locally would fall back to the
        # legacy single-file mode there; ranks mkdir the trailing-sep
        # form themselves.
        env["HVD_TPU_TIMELINE"] = args.timeline.rstrip(os.sep) + os.sep
    elastic = args.min_np is not None or args.max_np is not None
    if elastic:
        try:
            _elastic_bounds(args.num_proc, args.min_np, args.max_np)
            _check_elastic_support(args.hosts, tpu_pin)
        except ValueError as e:
            parser.error(str(e))
    try:
        results, restarts = run_elastic(
            cmd, args.num_proc, max_restarts=args.max_restarts,
            env=env, timeout=args.timeout or 3e7, host=args.host,
            hosts_spec=args.hosts, port_base=args.port_base,
            tpu_pin=tpu_pin, tpu_topology=args.tpu_topology,
            min_np=args.min_np, max_np=args.max_np,
            # Serving is shrink-only: an admitted standby would join with
            # empty KV pages and silently corrupt every sequence it
            # touches, so elastic serve jobs never spawn standbys.
            max_rejoins=0 if args.serve else None)
    except subprocess.TimeoutExpired:
        print("hvdrun: job timed out", file=sys.stderr)
        return 124
    # Unset --min-np with --max-np means "may grow, must not shrink": the
    # success floor is the full launch size, not one survivor.
    ok = (membership_succeeded(
        results,
        args.min_np if args.min_np is not None else args.num_proc)
          if elastic else all(r.returncode == 0 for r in results))
    if restarts and ok:
        print(f"hvdrun: job succeeded after {restarts} restart(s)",
              file=sys.stderr)
    if elastic and ok:
        # Initial ranks only: a standby the launcher itself reaped at the
        # completion deadline (spawned but never admitted before the job
        # finished) was never a member, so it is not "lost".
        lost = sum(1 for r in results
                   if r.returncode != 0 and r.rank < args.num_proc)
        if lost:
            print(f"hvdrun: job completed elastically ({lost} member(s) "
                  f"lost and reshaped around)", file=sys.stderr)
        return 0
    rc = 0
    report = failure_report(results)
    if report:
        print(f"hvdrun: {report}", file=sys.stderr)
    for r in results:
        if r.returncode != 0 and rc == 0:
            # Signal deaths have negative returncodes; report 128+sig
            # like a shell would so the job never masks as success.
            rc = r.returncode if r.returncode > 0 else 128 - r.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
