"""HTTP/JSON front door for the serving plane (rank 0).

Same stdlib-server shape as the metrics monitor (common/metrics.py): a
``ThreadingHTTPServer`` on a daemon thread, one handler thread per
in-flight request (the generate call long-polls the request's completion
event, so slow generations occupy a thread, not the engine).

Routes (docs/inference.md#request-api):

* ``POST /v1/generate`` — body ``{"tenant": str, "prompt_ids": [int],
  "max_new_tokens": int, "priority": int?}``; 200 with the generated
  tokens on completion.  Admission shedding is TYPED: 429 with
  ``{"error": {"type": "rejected", "reason": "queue_full" |
  "tenant_quota", ...}}`` (and a Retry-After header), 400 for
  ``too_long``/malformed bodies, 503 when the plane is down, 504 when the
  request outlives the long-poll bound.
* ``GET /v1/stats`` — the live ``metrics_snapshot()`` sections a serving
  operator needs (serving, cache, membership).
* ``GET /healthz`` — liveness + job identity.
* ``POST /shutdown`` — orderly drain: the engine broadcasts OP_STOP at
  the next tick and every rank leaves the serve loop.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from horovod_tpu.serving.scheduler import (AdmissionError, REJECT_TOO_LONG,
                                           Scheduler, ServeConfig,
                                           ServingUnavailableError)

_server_lock = threading.Lock()
_server = None  # (ThreadingHTTPServer, bound_port)


def start_server(scheduler: Scheduler, cfg: ServeConfig,
                 engine=None, host: str = "") -> int:
    """Serve the front door from a daemon thread; returns the bound port
    (``cfg.port`` 0 picks a free one).  Idempotent like the metrics
    monitor's ``start_monitor``."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    global _server
    with _server_lock:
        if _server is not None:
            return _server[1]

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, body: dict,
                       headers: Optional[dict] = None):
                payload = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    import horovod_tpu as hvd

                    self._reply(200, {
                        "ok": scheduler.failed is None,
                        "size": hvd.size() if hvd.is_initialized() else 0,
                        "membership_epoch": hvd.membership_epoch(),
                    })
                elif path == "/v1/stats":
                    from horovod_tpu.common import metrics_snapshot

                    snap = metrics_snapshot()
                    self._reply(200, {k: snap[k] for k in
                                      ("serving", "cache", "membership")})
                elif path == "/v1/trace":
                    # Request trace (docs/inference.md#request-traces):
                    # ordered spans for one request, live or retired.
                    from urllib.parse import parse_qs, urlparse

                    query = parse_qs(urlparse(self.path).query)
                    try:
                        request_id = int(query.get("id", [""])[0])
                    except ValueError:
                        self._reply(400, {"error": {
                            "type": "bad_request",
                            "detail": "trace needs a numeric ?id="}})
                        return
                    trace = scheduler.trace(request_id)
                    if trace is None:
                        self._reply(404, {"error": {
                            "type": "not_found", "id": request_id,
                            "detail": "unknown request id (never admitted,"
                                      " or evicted from the bounded trace"
                                      " store)"}})
                        return
                    self._reply(200, trace)
                else:
                    self.send_error(404)

            def do_POST(self):
                path = self.path.split("?")[0]
                if path == "/shutdown":
                    if engine is not None:
                        engine.request_stop()
                    self._reply(200, {"stopping": True})
                    return
                if path != "/v1/generate":
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(length) or b"{}")
                    tenant = str(body["tenant"])
                    prompt = [int(t) for t in body["prompt_ids"]]
                    max_new = int(body["max_new_tokens"])
                    priority = int(body.get("priority", 0))
                except (KeyError, TypeError, ValueError) as exc:
                    self._reply(400, {"error": {
                        "type": "bad_request",
                        "detail": f"malformed generate body: {exc}"}})
                    return
                try:
                    req = scheduler.submit(tenant, prompt, max_new,
                                           priority)
                except AdmissionError as exc:
                    code = 400 if exc.reason == REJECT_TOO_LONG else 429
                    self._reply(code, {"error": {
                        "type": "rejected", "reason": exc.reason,
                        "tenant": exc.tenant, "detail": str(exc)}},
                        headers=({"Retry-After": "1"} if code == 429
                                 else None))
                    return
                except ServingUnavailableError as exc:
                    self._reply(503, {"error": {
                        "type": "unavailable", "detail": str(exc)}})
                    return
                if not req.event.wait(cfg.request_timeout_sec):
                    self._reply(504, {"error": {
                        "type": "timeout", "id": req.id,
                        "detail": "generation did not finish within "
                                  f"{cfg.request_timeout_sec:g}s"}})
                    return
                if req.error is not None:
                    self._reply(503, {"error": {
                        "type": "unavailable", "id": req.id,
                        "detail": str(req.error)}})
                    return
                self._reply(200, req.to_result())

            def log_message(self, *args):  # keep request noise off stderr
                pass

        server = ThreadingHTTPServer((host, cfg.port), Handler)
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever,
                                  name="hvd-tpu-serve", daemon=True)
        thread.start()
        _server = (server, server.server_address[1])
        return _server[1]


def stop_server() -> None:
    global _server
    with _server_lock:
        if _server is None:
            return
        server, _ = _server
        _server = None
    server.shutdown()
    server.server_close()


def server_port() -> Optional[int]:
    with _server_lock:
        return _server[1] if _server else None
