"""Serving plane: multi-tenant continuous-batching inference over the
collective engine (docs/inference.md).

Three pieces on top of the subsystems PRs 1-6 built:

* a rank-0 HTTP/JSON front door with per-tenant admission quotas and a
  bounded queue that sheds load with typed 429s (serving/server.py);
* an iteration-level continuous-batching scheduler over a block-granular
  KV cache pool (serving/scheduler.py, serving/kv_cache.py), whose batch
  plan is broadcast each step through the ordinary named-collective path
  — the PR-4 negotiation response cache makes steady-state decode steps
  pay zero coordinator roundtrips;
* a per-rank decode engine driving models/transformer.py's cached-KV
  decode mode, with ring-attention bulk prefill for long prompts and
  elastic-reshape recovery (serving/engine.py, serving/prefill.py).

``python -m horovod_tpu.serving`` (or ``hvdrun --serve``) is the server
entrypoint.  The scheduler/pool core is importable without jax for pure
unit testing.
"""

from horovod_tpu.serving.kv_cache import BlockPool  # noqa: F401
from horovod_tpu.serving.scheduler import (  # noqa: F401
    AdmissionError,
    Plan,
    Scheduler,
    ServeConfig,
    ServingUnavailableError,
)

__all__ = [
    "AdmissionError",
    "BlockPool",
    "Plan",
    "Scheduler",
    "ServeConfig",
    "ServingUnavailableError",
]
