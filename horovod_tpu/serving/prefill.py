"""Bulk (ring-attention) prefill for long prompts (docs/inference.md).

Chunked prefill (the default) walks a prompt through the decode step
``prefill_chunk`` tokens at a time — simple, fixed-shape, but O(prompt)
steps.  For long contexts the serving plane instead runs ONE sequence-
sharded forward over :func:`~horovod_tpu.ops.ring_attention`: the prompt
is split over the device mesh's sequence axis, each shard computes its
layers' K/V locally (projections are position-local; only attention
communicates, around the ring), and the captured per-layer K/V is written
straight into the KV pages.  On a TPU pod slice the mesh spans ranks over
ICI; on a host (and in the CPU test environment) it spans the local
devices.  Enabled by ``HVD_TPU_SERVE_RING_MIN_TOKENS`` > 0 for prompts at
least that long.

The prompt itself cannot ride the fixed-size batch plan, so it travels in
a side broadcast padded to a bucketed length — only a handful of extra
negotiation-cache signatures ever exist, and steady-state decode stays on
the single ``serve.plan`` signature.
"""

from __future__ import annotations

import math
from typing import List, Tuple

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.serving import engine as _engine

# Prompt-buffer bucket: multiples of 256 keep the side-broadcast signature
# count tiny and divide evenly by any power-of-two sequence mesh <= 256.
PROMPT_BUCKET = 256


def bucket_len(n: int) -> int:
    return max(PROMPT_BUCKET, math.ceil(n / PROMPT_BUCKET) * PROMPT_BUCKET)


def broadcast_prompt(feed: List[int], real_len: int) -> Tuple[np.ndarray,
                                                              int]:
    """Root-broadcast a bulk-prefill prompt in a bucketed buffer (rank 0
    passes the tokens; workers pass the empty buffer and receive)."""
    buf = np.zeros(bucket_len(real_len), np.int32)
    if feed:
        buf[:real_len] = feed[:real_len]
    out = hvd.broadcast(buf, 0, name=f"serve.prompt.{len(buf)}")
    return out, real_len


def scatter_bulk(pages, k_all, v_all, table, real_len: int, trash: int):
    """Write a captured whole-prompt K/V into the pages.

    ``k_all``/``v_all``: ``(L, 1, heads, padded_len, head_dim)`` from the
    sharded forward; positions past ``real_len`` (bucket padding) are
    routed to the trash block."""
    import jax.numpy as jnp

    bt = pages.shape[3]
    padded = k_all.shape[3]
    pos = np.arange(padded)
    slots = np.minimum(pos // bt, len(table) - 1)
    blocks = np.where(pos < real_len, np.asarray(table)[slots], trash)
    off = pos % bt
    new_kv = jnp.stack([k_all[:, 0], v_all[:, 0]], axis=1)  # (L,2,h,P,hd)
    new_kv = jnp.swapaxes(new_kv, 2, 3)                     # (L,2,P,h,hd)
    return pages.at[:, :, jnp.asarray(blocks), jnp.asarray(off)].set(new_kv)


class RingPrefill:
    """Compiled whole-prompt prefill, one executable per bucketed length.

    Picks the largest power-of-two sequence mesh the local devices allow
    (1 device = plain single-shard forward, same capture path)."""

    def __init__(self, spec: "_engine.ModelSpec", cfg, params):
        import jax

        self.spec = spec
        self.params = params
        n_dev = len(jax.devices())
        self.n_sp = 1 << (max(n_dev, 1).bit_length() - 1)
        self._compiled = {}

    def _extract_kv(self, inter):
        """Stack the sown per-layer (k, v) into (L, b, h, s, hd) pairs."""
        import jax.numpy as jnp

        ks, vs = [], []
        for i in range(self.spec.n_layers):
            k, v = inter[f"layer_{i}"]["attn"]["kv"][0]
            ks.append(k)
            vs.append(v)
        return jnp.stack(ks), jnp.stack(vs)

    def _build(self, padded: int):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        from horovod_tpu.jax.train import shard_map

        if self.n_sp == 1 or padded % self.n_sp:
            model = _engine.build_model(self.spec, capture_kv=True)

            def single(tokens):
                logits, state = model.apply(
                    {"params": self.params}, tokens,
                    mutable=["intermediates"])
                k, v = self._extract_kv(state["intermediates"])
                return logits, k, v

            return jax.jit(single)

        mesh = Mesh(np.array(jax.devices()[:self.n_sp]), ("sp",))
        model = _engine.build_model(self.spec, seq_axis="sp",
                                    capture_kv=True)

        def shard(tokens):
            logits, state = model.apply(
                {"params": self.params}, tokens, mutable=["intermediates"])
            k, v = self._extract_kv(state["intermediates"])
            return logits, k, v

        mapped = shard_map(
            shard, mesh,
            in_specs=(P(None, "sp"),),
            out_specs=(P(None, "sp", None),
                       P(None, None, None, "sp", None),
                       P(None, None, None, "sp", None)))
        return jax.jit(mapped)

    def __call__(self, buf: np.ndarray, real_len: int):
        """Returns ``(k_all, v_all, sampled)``: the captured K/V for the
        whole padded prompt and the greedy token after its last real
        position."""
        import jax.numpy as jnp

        padded = len(buf)
        fn = self._compiled.get(padded)
        if fn is None:
            fn = self._compiled[padded] = self._build(padded)
        logits, k_all, v_all = fn(jnp.asarray(buf, jnp.int32)[None, :])
        sampled = int(jnp.argmax(logits[0, real_len - 1]))
        return k_all, v_all, sampled
