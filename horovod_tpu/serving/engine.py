"""Per-rank serving engine: the decode loop under the batch-plan broadcast.

Every rank runs the same loop: receive rank 0's packed batch plan through
the ordinary named-collective path (``hvd.broadcast`` of a FIXED-shape
int32 array, name ``serve.plan`` — so after the first step the PR-4
negotiation response cache replays the agreement and steady-state decode
steps pay zero coordinator roundtrips), execute the jitted decode step
against the local page buffer, and loop.  Rank 0 additionally owns the
scheduler and samples the next token from its own logits; the sample
travels to the workers inside the NEXT plan (they never sample), so every
rank's KV pages stay bit-identical by construction.

Robustness: a :class:`~horovod_tpu.MembershipChangedError` on the plan
broadcast means the elastic job reshaped mid-decode.  Survivor pages and
scheduler state are both intact and the cancelled step never executed
anywhere (the reshape barrier poisons in-flight collectives on every rank
consistently), so each rank simply acks the reshape and re-enters the
loop; rank 0 re-plans the identical step and in-flight requests resume.
Fatal errors (``RanksDownError`` below min-np, timeouts) fail every
in-flight request typed — never hang (docs/inference.md).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, List, Optional

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import metrics
from horovod_tpu.serving import kv_cache, scheduler as sched


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The served TransformerLM's shape (env: ``HVD_TPU_SERVE_*``).
    Defaults are a test-scale model; production points ``ckpt`` at a
    checkpoint whose tree matches the spec (docs/inference.md)."""

    vocab: int = 512
    d_model: int = 64
    n_layers: int = 2
    n_heads: int = 2
    dtype: str = "float32"
    seed: int = 0
    ckpt: str = ""

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def from_env() -> "ModelSpec":
        d = ModelSpec()
        return ModelSpec(
            vocab=int(os.environ.get("HVD_TPU_SERVE_VOCAB") or d.vocab),
            d_model=int(os.environ.get("HVD_TPU_SERVE_D_MODEL")
                        or d.d_model),
            n_layers=int(os.environ.get("HVD_TPU_SERVE_LAYERS")
                         or d.n_layers),
            n_heads=int(os.environ.get("HVD_TPU_SERVE_HEADS")
                        or d.n_heads),
            dtype=os.environ.get("HVD_TPU_SERVE_DTYPE") or d.dtype,
            seed=int(os.environ.get("HVD_TPU_SERVE_SEED") or d.seed),
            ckpt=os.environ.get("HVD_TPU_SERVE_CKPT") or d.ckpt,
        )


def build_model(spec: ModelSpec, seq_axis: Optional[str] = None,
                capture_kv: bool = False):
    """The served model (and its sequence-parallel prefill twin — the
    parameter tree is identical, only the attention communication pattern
    differs).  ``use_flash=False``: serving never runs the training-path
    Pallas kernel — decode uses the cached-KV path, prefill the blockwise
    or ring path — so interpret-mode kernel compiles are never paid."""
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerLM

    return TransformerLM(
        vocab_size=spec.vocab, d_model=spec.d_model,
        n_layers=spec.n_layers, n_heads=spec.n_heads,
        dtype=jnp.dtype(spec.dtype), logits_dtype=jnp.float32,
        use_flash=False, seq_axis=seq_axis, capture_kv=capture_kv)


def init_params(spec: ModelSpec):
    """Deterministic parameters: from ``spec.ckpt`` when set (the
    ``jax.train.save_checkpoint`` pickle format), else seeded random init
    — identical on every rank, and root-broadcast after init anyway so a
    rank-locally-loaded checkpoint cannot diverge the job."""
    import jax

    if spec.ckpt:
        from horovod_tpu.jax.train import load_latest_checkpoint

        loaded = (load_latest_checkpoint(spec.ckpt)
                  if os.path.isdir(spec.ckpt) else None)
        if loaded is None:
            import pickle

            with open(spec.ckpt, "rb") as f:
                loaded = pickle.load(f)
        tree = loaded[1] if isinstance(loaded, tuple) else loaded
        return tree.get("params", tree) if isinstance(tree, dict) else tree
    model = build_model(spec)
    tokens = np.zeros((1, 4), np.int32)
    return model.init(jax.random.PRNGKey(spec.seed), tokens)["params"]


def broadcast_params(params):
    """Root-broadcast every parameter leaf from rank 0 (numbered names:
    the signatures are stable, so even these warm the response cache)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    synced = []
    for i, leaf in enumerate(leaves):
        arr = np.ascontiguousarray(np.asarray(leaf))
        out = hvd.broadcast(arr, 0, name=f"serve.param.{i}")
        synced.append(out.reshape(arr.shape).astype(arr.dtype))
    return jax.tree_util.tree_unflatten(treedef, synced)


def make_step_fn(model, spec: ModelSpec, cfg: sched.ServeConfig) -> Callable:
    """The jitted decode step: gather each slot's paged KV context,
    run the model's cached-decode path over the (fixed-shape) token
    chunk, scatter the fresh K/V back into the pages, and return greedy
    next-token candidates per slot.  All shapes are static, so this
    compiles exactly once per server lifetime."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from horovod_tpu.models import DecodeContext

    ctx_len = cfg.max_blocks_per_seq * cfg.block_tokens

    @partial(jax.jit, donate_argnums=(0,))
    def step(pages, params, tokens, n_new, lengths, tables):
        k_ctx, v_ctx = kv_cache.gather_context(pages, tables)
        ctx_mask = jnp.arange(ctx_len)[None, :] < lengths[:, None]
        positions = lengths[:, None] + jnp.arange(tokens.shape[1])[None, :]
        logits, (k_new, v_new) = model.apply(
            {"params": params}, tokens,
            decode_ctx=DecodeContext(k_ctx, v_ctx, ctx_mask, positions))
        pages = kv_cache.scatter_new(pages, k_new, v_new, tables,
                                     lengths, n_new)
        idx = jnp.clip(n_new - 1, 0, tokens.shape[1] - 1)
        sampled = jnp.take_along_axis(jnp.argmax(logits, axis=-1),
                                      idx[:, None], axis=1)[:, 0]
        return pages, sampled

    return step


def reference_decode(model, params, prompt_ids, max_new_tokens: int,
                     eos_id: int = -1) -> List[int]:
    """Greedy decode by repeated FULL-context forward — the semantic
    ground truth the cached/paged path must reproduce (tests, and the
    bench's correctness spot-check).  The buffer is padded to the final
    length once (causal attention makes trailing padding invisible to
    earlier positions), so the whole decode compiles a single forward
    instead of one per length."""
    import jax
    import jax.numpy as jnp

    total = len(prompt_ids) + max_new_tokens
    apply = jax.jit(lambda t: model.apply({"params": params}, t))
    tokens = list(prompt_ids)
    out = []
    for _ in range(max_new_tokens):
        buf = jnp.asarray([tokens + [0] * (total - len(tokens))],
                          jnp.int32)
        logits = apply(buf)
        tok = int(jnp.argmax(logits[0, len(tokens) - 1]))
        out.append(tok)
        if eos_id >= 0 and tok == eos_id:
            break
        tokens.append(tok)
    return out


class ServingEngine:
    """One rank's serving loop.  Rank 0 owns ``scheduler`` (and the HTTP
    front door sits on top of it); workers pass ``scheduler=None``."""

    def __init__(self, spec: ModelSpec, cfg: sched.ServeConfig, params,
                 scheduler: Optional[sched.Scheduler] = None):
        self.spec = spec
        self.cfg = cfg
        self.model = build_model(spec)
        self.params = params
        self.scheduler = scheduler
        self._step_fn = make_step_fn(self.model, spec, cfg)
        self._stop = threading.Event()
        self._trash = cfg.num_blocks  # page index masked writes land in
        import jax.numpy as jnp

        self.pages = kv_cache.init_pages(
            spec.n_layers, spec.n_heads, spec.head_dim, cfg.num_blocks,
            cfg.block_tokens, jnp.dtype(spec.dtype))
        self._prefill = None  # lazy ring-prefill helper (serving/prefill.py)

    def request_stop(self) -> None:
        """Ask the loop to broadcast OP_STOP at the next tick (rank 0;
        on workers it only exits the local loop — the plan broadcast is
        what actually releases them)."""
        self._stop.set()

    # -- plan execution ---------------------------------------------------

    def _tables_array(self, plan: sched.Plan) -> np.ndarray:
        tables = np.full((self.cfg.max_batch, self.cfg.max_blocks_per_seq),
                         self._trash, np.int32)
        for sp in plan.slots:
            for i, b in enumerate(sp.table):
                if b >= 0:
                    tables[sp.slot, i] = b
        return tables

    def _execute(self, plan: sched.Plan) -> np.ndarray:
        """Run one planned step; returns per-slot sampled tokens."""
        cfg = self.cfg
        tokens = np.zeros((cfg.max_batch, cfg.prefill_chunk), np.int32)
        n_new = np.zeros(cfg.max_batch, np.int32)
        lengths = np.zeros(cfg.max_batch, np.int32)
        tables = self._tables_array(plan)
        for sp in plan.slots:
            lengths[sp.slot] = sp.length
            if sp.bulk_len:
                continue  # handled by the bulk-prefill path below
            tokens[sp.slot, :sp.n_new] = sp.tokens
            n_new[sp.slot] = sp.n_new
        self.pages, sampled = self._step_fn(
            self.pages, self.params, tokens, n_new, lengths, tables)
        sampled = np.array(sampled)  # writable: bulk slots overwrite below
        for sp in plan.slots:
            if sp.bulk_len:
                sampled[sp.slot] = self._bulk_prefill(sp, tables[sp.slot])
        return sampled

    def _bulk_prefill(self, sp: sched.SlotPlan, table: np.ndarray) -> int:
        """Whole-prompt prefill for one slot in a single sharded forward
        (ops/ring_attention over the local device mesh), instead of
        chunk-by-chunk: the prompt travels in a side broadcast (bucketed
        length, so only a handful of extra cache signatures exist), every
        rank writes the captured K/V into its pages, and the last real
        position's logit is the first sampled token."""
        from horovod_tpu.serving import prefill

        if self._prefill is None:
            self._prefill = prefill.RingPrefill(self.spec, self.cfg,
                                                self.params)
        if self.scheduler is not None:
            feed = self.scheduler.bulk_tokens(sp.request_id)
        else:
            feed = []
        buf, real_len = prefill.broadcast_prompt(feed, sp.bulk_len)
        k_all, v_all, sampled = self._prefill(buf, real_len)
        self.pages = prefill.scatter_bulk(self.pages, k_all, v_all,
                                          table, real_len, self._trash)
        return sampled

    # -- the loop ---------------------------------------------------------

    def run(self) -> None:
        """One rank's serve loop.  ANY exception that kills it fails
        in-flight requests typed first (never hang) — the per-iteration
        handlers below cover the collective paths; this net covers the
        rest (planning, packing, a bad checkpoint's first apply)."""
        try:
            self._loop()
        except Exception as exc:
            if self.scheduler is not None:
                self.scheduler.fail_all(exc)
            raise

    def _loop(self) -> None:
        cfg = self.cfg
        rank0 = hvd.rank() == 0
        plan_shape = sched.plan_size(cfg)
        registry = metrics.registry
        while True:
            if rank0:
                if self._stop.is_set():
                    buf = sched.pack_control(cfg, sched.OP_STOP)
                    plan = None
                else:
                    plan = (self.scheduler.step_plan()
                            if self.scheduler else None)
                    buf = (sched.pack_plan(cfg, plan) if plan
                           else sched.pack_control(cfg, sched.OP_IDLE))
            else:
                buf = np.zeros(plan_shape, np.int32)
                plan = None
            try:
                wire = hvd.broadcast(buf, 0, name="serve.plan")
            except hvd.MembershipChangedError:
                # Reshape mid-decode: the step never ran anywhere; ack
                # and re-plan (docs/inference.md#reshape-semantics).
                hvd.membership_ack()
                if rank0 and self.scheduler:
                    self.scheduler.reform([])
                continue
            opcode = int(wire[0])
            if opcode == sched.OP_STOP:
                return
            if opcode == sched.OP_IDLE:
                if rank0:
                    time.sleep(cfg.idle_sleep_sec)
                continue
            if not rank0:
                plan = sched.unpack_plan(cfg, wire)
            t0 = time.perf_counter()
            try:
                sampled = self._execute(plan)
            except hvd.MembershipChangedError:
                # The bulk-prefill side broadcast got cancelled by a
                # reshape.  The page writes a partially-executed step
                # already made are idempotent (same values to the same
                # positions) and scheduler state only advances in
                # complete_step, so re-planning re-runs the identical
                # step safely.
                hvd.membership_ack()
                if rank0 and self.scheduler:
                    self.scheduler.reform([])
                continue
            if registry.enabled:
                registry.observe("step_sec", time.perf_counter() - t0)
            if rank0 and self.scheduler:
                finished = self.scheduler.complete_step(plan, sampled)
                # Request traces feed the PR-3 timeline too: one instant
                # per retirement on the "serving" row (no-op when the
                # timeline is off), so a merged trace shows request
                # completions against the collective rows.
                if finished and hvd.timeline_enabled():
                    for req in finished:
                        hvd.trace_marker(
                            f"req.{req.id}.retired"
                            f"[{len(req.generated)}tok]",
                            row="serving")
