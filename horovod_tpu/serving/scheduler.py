"""Continuous-batching scheduler: the serving plane's rank-0 brain.

Iteration-level scheduling (Orca, Yu et al., OSDI'22): every decode step
the batch is re-formed from whatever requests are live — new requests
join at step boundaries (chunked prefill, so a long prompt cannot stall
the running decodes), finished ones retire immediately and their KV
blocks return to the pool (no head-of-line blocking on the longest
sequence).  The scheduler is deliberately pure Python with no jax/engine
dependency: every policy decision (admission, quotas, priority, block
accounting, preemption, retirement) is unit-testable in-process
(tests/test_serving.py), and the engine consumes it only through
:class:`Plan` — a fixed-shape int32 array broadcast from rank 0 through
the ordinary named-collective path, so the PR-4 response cache makes
steady-state decode steps pay zero coordinator roundtrips.

Admission is bounded end to end: a global queue cap and a per-tenant
in-flight cap shed load with a typed rejection (the HTTP front door turns
it into a 429) instead of growing queues unboundedly.
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from horovod_tpu.common import metrics
from horovod_tpu.serving.kv_cache import BlockPool

# Batch-plan opcodes (plan[0]); workers follow rank 0's broadcast.
OP_IDLE = 0   # nothing to run this tick (workers just loop)
OP_STEP = 1   # run the decode step described by the slot records
OP_STOP = 2   # orderly shutdown: every rank leaves the serve loop

# Typed admission-rejection reasons (HTTP 429 / 400 bodies).
REJECT_QUEUE_FULL = "queue_full"
REJECT_TENANT_QUOTA = "tenant_quota"
REJECT_TOO_LONG = "too_long"

# Request-trace bounds (postmortem plane, docs/inference.md#request-traces):
# per-request span cap (a 100k-token decode must not grow a span list
# unboundedly — overflow is counted, terminal events always land) and the
# completed-trace store size served by GET /v1/trace?id=.
_MAX_SPANS = 512
_MAX_TRACES = 256
_TERMINAL_SPANS = ("retired", "preempted", "failed")


class AdmissionError(Exception):
    """A request was shed at admission.  ``reason`` is one of the
    ``REJECT_*`` constants; the front door maps it to a typed 429 (or 400
    for ``too_long``, which retrying cannot fix)."""

    def __init__(self, reason: str, tenant: str, detail: str):
        super().__init__(detail)
        self.reason = reason
        self.tenant = tenant


class ServingUnavailableError(Exception):
    """The serving plane lost its engine (fatal collective error, below
    elastic min-np, shutdown): in-flight and new requests fail typed —
    never hang (docs/inference.md#reshape-semantics)."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-plane shape knobs (env: ``HVD_TPU_SERVE_*``; see
    docs/inference.md for the KV-cache sizing recipe)."""

    max_batch: int = 8             # decode batch slots
    prefill_chunk: int = 16        # prompt tokens consumed per step/slot
    block_tokens: int = 16         # tokens per KV block
    num_blocks: int = 128          # KV block pool size (all layers share)
    max_blocks_per_seq: int = 16   # per-request context cap, in blocks
    queue_limit: int = 64          # global admission queue bound
    tenant_max_inflight: int = 16  # per-tenant queued+active cap
    eos_id: int = -1               # stop token (< 0: length-only stop)
    idle_sleep_sec: float = 0.005  # rank-0 throttle between idle ticks
    request_timeout_sec: float = 300.0  # front-door long-poll bound
    ring_min_tokens: int = 0       # >=: bulk ring-prefill (0 = chunked)
    port: int = 8780               # HTTP front door (rank 0)

    @property
    def max_seq(self) -> int:
        """Per-request context ceiling (prompt + generated), tokens."""
        return self.block_tokens * self.max_blocks_per_seq

    @staticmethod
    def from_env() -> "ServeConfig":
        def _int(name, default):
            return int(os.environ.get(f"HVD_TPU_SERVE_{name}") or default)

        def _float(name, default):
            return float(os.environ.get(f"HVD_TPU_SERVE_{name}") or default)

        d = ServeConfig()
        return ServeConfig(
            max_batch=_int("MAX_BATCH", d.max_batch),
            prefill_chunk=_int("PREFILL_CHUNK", d.prefill_chunk),
            block_tokens=_int("BLOCK_TOKENS", d.block_tokens),
            num_blocks=_int("KV_BLOCKS", d.num_blocks),
            max_blocks_per_seq=_int("MAX_BLOCKS_PER_SEQ",
                                    d.max_blocks_per_seq),
            queue_limit=_int("QUEUE", d.queue_limit),
            tenant_max_inflight=_int("TENANT_INFLIGHT",
                                     d.tenant_max_inflight),
            eos_id=_int("EOS", d.eos_id),
            idle_sleep_sec=_float("IDLE_SLEEP_SEC", d.idle_sleep_sec),
            request_timeout_sec=_float("REQUEST_TIMEOUT_SEC",
                                       d.request_timeout_sec),
            ring_min_tokens=_int("RING_MIN_TOKENS", d.ring_min_tokens),
            port=_int("PORT", d.port),
        )


# Request lifecycle states.
QUEUED, ACTIVE, DONE, FAILED = "queued", "active", "done", "failed"


class Request:
    """One generate request.  The front door blocks on ``event``; the
    scheduler owns every other field under its lock."""

    _ids = itertools.count()

    def __init__(self, tenant: str, prompt_ids: Sequence[int],
                 max_new_tokens: int, priority: int = 0):
        self.id = next(Request._ids)
        self.tenant = tenant
        self.prompt_ids = [int(t) for t in prompt_ids]
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.state = QUEUED
        self.generated: List[int] = []
        self.blocks: List[int] = []
        self.slot: Optional[int] = None
        # Tokens already written to the KV cache.  The feed (prompt, then
        # generated tokens re-fed one per decode step) restarts from 0
        # after a preemption — generated tokens are kept, so generation
        # resumes exactly where it stopped once re-prefilled.
        self.filled = 0
        self.finish_seq: Optional[int] = None  # retirement order stamp
        self.error: Optional[Exception] = None
        self.event = threading.Event()
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # Request trace (docs/inference.md#request-traces): ordered span
        # records through the lifecycle, served by GET /v1/trace?id= and
        # landed on the PR-3 timeline at retirement.  Bounded; terminal
        # events always record.
        self.spans: List[dict] = [{"event": "submitted", "t_ms": 0.0}]
        self.dropped_spans = 0

    def span(self, event: str, now: Optional[float] = None,
             **fields) -> None:
        if len(self.spans) >= _MAX_SPANS and event not in _TERMINAL_SPANS:
            self.dropped_spans += 1
            return
        rec = {"event": event,
               "t_ms": round(((now if now is not None else time.monotonic())
                              - self.t_submit) * 1e3, 3)}
        rec.update(fields)
        self.spans.append(rec)

    @property
    def feed(self) -> List[int]:
        """The token stream that must reach the cache: the prompt, then
        every generated token except the last (which only needs to be fed
        back if generation continues)."""
        return self.prompt_ids + self.generated

    def to_result(self) -> dict:
        return {
            "id": self.id,
            "tenant": self.tenant,
            "prompt_tokens": len(self.prompt_ids),
            "tokens": list(self.generated),
            "finish_seq": self.finish_seq,
            "ttft_ms": (round((self.t_first_token - self.t_submit) * 1e3, 3)
                        if self.t_first_token else None),
            "latency_ms": (round((self.t_done - self.t_submit) * 1e3, 3)
                           if self.t_done else None),
        }


@dataclasses.dataclass
class SlotPlan:
    slot: int
    request_id: int
    tokens: List[int]      # tokens to embed this step (<= prefill_chunk)
    n_new: int             # how many of `tokens` are real
    length: int            # cache length BEFORE this step
    table: List[int]       # allocated block ids, cache order
    bulk_len: int = 0      # > 0: bulk (ring) prefill of this many tokens
    samples: bool = False  # does this step's last logit produce a token?


@dataclasses.dataclass
class Plan:
    opcode: int
    step: int
    slots: List[SlotPlan] = dataclasses.field(default_factory=list)


def plan_size(cfg: ServeConfig) -> int:
    """int32 words in a packed plan — fixed for a given config, so the
    broadcast signature never changes and the negotiation response cache
    hits on every steady-state step."""
    return 2 + cfg.max_batch * (5 + cfg.prefill_chunk
                                + cfg.max_blocks_per_seq)


def pack_plan(cfg: ServeConfig, plan: Plan) -> np.ndarray:
    arr = np.zeros(plan_size(cfg), np.int32)
    arr[0] = plan.opcode
    arr[1] = plan.step
    width = 5 + cfg.prefill_chunk + cfg.max_blocks_per_seq
    for sp in plan.slots:
        base = 2 + sp.slot * width
        arr[base] = 1
        arr[base + 1] = sp.n_new
        arr[base + 2] = sp.length
        arr[base + 3] = sp.bulk_len
        arr[base + 4] = int(sp.samples)
        arr[base + 5:base + 5 + len(sp.tokens)] = sp.tokens
        tab = base + 5 + cfg.prefill_chunk
        arr[tab:tab + cfg.max_blocks_per_seq] = -1
        arr[tab:tab + len(sp.table)] = sp.table
    return arr


def unpack_plan(cfg: ServeConfig, arr: np.ndarray) -> Plan:
    plan = Plan(opcode=int(arr[0]), step=int(arr[1]))
    width = 5 + cfg.prefill_chunk + cfg.max_blocks_per_seq
    for slot in range(cfg.max_batch):
        base = 2 + slot * width
        if not arr[base]:
            continue
        n_new = int(arr[base + 1])
        tab = base + 5 + cfg.prefill_chunk
        table = [int(b) for b in arr[tab:tab + cfg.max_blocks_per_seq]]
        plan.slots.append(SlotPlan(
            slot=slot, request_id=-1,
            tokens=[int(t) for t in arr[base + 5:base + 5 + n_new]],
            n_new=n_new, length=int(arr[base + 2]),
            table=table, bulk_len=int(arr[base + 3]),
            samples=bool(arr[base + 4])))
    return plan


def pack_control(cfg: ServeConfig, opcode: int, step: int = 0) -> np.ndarray:
    arr = np.zeros(plan_size(cfg), np.int32)
    arr[0] = opcode
    arr[1] = step
    return arr


class Scheduler:
    """The continuous-batching core.  Thread-safe: the front door submits
    from HTTP handler threads while the engine loop calls
    ``step_plan``/``complete_step``; one lock covers all state."""

    def __init__(self, cfg: ServeConfig, pool: Optional[BlockPool] = None):
        self.cfg = cfg
        self.pool = pool or BlockPool(cfg.num_blocks, cfg.block_tokens)
        self._lock = threading.Lock()
        self._queue: List[tuple] = []      # heap of (-priority, seq, req)
        self._submit_seq = itertools.count()
        self._slots: List[Optional[Request]] = [None] * cfg.max_batch
        self._by_id: Dict[int, Request] = {}
        self._step = 0
        self._finish_seq = itertools.count()
        self._failed: Optional[Exception] = None
        self._reg = metrics.registry
        # Completed-request traces (retired/failed), bounded FIFO — the
        # /v1/trace route serves live requests from _by_id and finished
        # ones from here.
        self._traces: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()

    # -- admission --------------------------------------------------------

    def submit(self, tenant: str, prompt_ids: Sequence[int],
               max_new_tokens: int, priority: int = 0) -> Request:
        """Admit a request or shed it with a typed
        :class:`AdmissionError`.  Records per-tenant counters either way.
        """
        tenant = str(tenant)
        req = Request(tenant, prompt_ids, max_new_tokens, priority)
        with self._lock:
            if self._failed is not None:
                # Not counted as a request: the lifecycle invariant is
                # requests == admitted + rejected, and a down plane is
                # neither (docs/metrics.md).
                raise ServingUnavailableError(
                    f"serving plane is down: {self._failed}")
            self._reg.record_serving("requests", tenant)
            if not req.prompt_ids or req.max_new_tokens < 1:
                self._reg.record_serving("rejected", tenant)
                raise AdmissionError(
                    REJECT_TOO_LONG, tenant,
                    "need a non-empty prompt and max_new_tokens >= 1")
            total = len(req.prompt_ids) + req.max_new_tokens
            if (total > self.cfg.max_seq
                    or self.pool.blocks_for_tokens(total)
                    > self.pool.num_blocks):
                # The pool check prevents a livelock: a request the WHOLE
                # pool cannot hold would preempt everything and still
                # never finish.
                self._reg.record_serving("rejected", tenant)
                raise AdmissionError(
                    REJECT_TOO_LONG, tenant,
                    f"prompt ({len(req.prompt_ids)}) + max_new_tokens "
                    f"({req.max_new_tokens}) exceeds the context cap "
                    f"(max_seq {self.cfg.max_seq}, pool "
                    f"{self.pool.num_blocks} blocks)")
            if len(self._queue) >= self.cfg.queue_limit:
                self._reg.record_serving("rejected", tenant)
                raise AdmissionError(
                    REJECT_QUEUE_FULL, tenant,
                    f"admission queue is full ({self.cfg.queue_limit})")
            inflight = sum(1 for r in self._by_id.values()
                           if r.tenant == tenant
                           and r.state in (QUEUED, ACTIVE))
            if inflight >= self.cfg.tenant_max_inflight:
                self._reg.record_serving("rejected", tenant)
                raise AdmissionError(
                    REJECT_TENANT_QUOTA, tenant,
                    f"tenant '{tenant}' already has {inflight} requests "
                    f"in flight (cap {self.cfg.tenant_max_inflight})")
            self._by_id[req.id] = req
            heapq.heappush(self._queue,
                           (-req.priority, next(self._submit_seq), req))
            req.span("admitted")
            self._reg.record_serving("admitted", tenant)
            self._reg.record_serving_tokens(tenant, "prompt",
                                            len(req.prompt_ids))
            self._update_gauges()
        return req

    # -- step planning ----------------------------------------------------

    def step_plan(self) -> Optional[Plan]:
        """Form the next iteration's batch: join queued requests into
        free slots (priority order, chunked or bulk prefill), then emit
        one :class:`SlotPlan` per live slot.  Returns None when there is
        nothing to run (idle tick).  Re-entrant after a membership
        reshape: a re-issued call plans the identical step (block
        allocation only ever covers the shortfall)."""
        with self._lock:
            if self._failed is not None:
                return None
            self._join_locked()
            slots = []
            bulk_used = False
            for slot, req in enumerate(self._slots):
                if req is None:
                    continue
                feed = req.feed
                remaining = len(feed) - req.filled
                assert remaining >= 1, (req.id, req.state)
                bulk = (not bulk_used and req.filled == 0
                        and self.cfg.ring_min_tokens > 0
                        and remaining >= self.cfg.ring_min_tokens)
                n_new = remaining if bulk else min(self.cfg.prefill_chunk,
                                                  remaining)
                if not self._ensure_blocks_locked(req, req.filled + n_new):
                    continue  # stayed short of blocks (or got preempted)
                if req.slot is None:
                    continue  # preempted while making room for others
                samples = req.filled + n_new == len(feed)
                slots.append(SlotPlan(
                    slot=slot, request_id=req.id,
                    tokens=[] if bulk else feed[req.filled:
                                               req.filled + n_new],
                    n_new=0 if bulk else n_new,
                    length=req.filled, table=list(req.blocks),
                    bulk_len=n_new if bulk else 0, samples=samples))
                bulk_used = bulk_used or bulk
            if not slots:
                return None
            self._step += 1
            return Plan(opcode=OP_STEP, step=self._step, slots=slots)

    def bulk_tokens(self, request_id: int) -> List[int]:
        """The full feed of a bulk-prefill slot (rank 0 broadcasts it to
        the workers outside the fixed-size plan)."""
        with self._lock:
            return list(self._by_id[request_id].feed)

    def _join_locked(self) -> None:
        while self._queue and None in self._slots:
            _, _, req = self._queue[0]
            if req.state != QUEUED:     # preempt-requeue left a stale entry
                heapq.heappop(self._queue)
                continue
            first = min(self.cfg.prefill_chunk, len(req.feed))
            need = self.pool.blocks_for_tokens(first)
            blocks = self.pool.alloc(need)
            if blocks is None:
                break                   # pool exhausted: stays queued
            heapq.heappop(self._queue)
            req.blocks = blocks
            req.state = ACTIVE
            req.slot = self._slots.index(None)
            self._slots[req.slot] = req
            req.span("activated", slot=req.slot)
            self._update_gauges()

    def _ensure_blocks_locked(self, req: Request, want_tokens: int) -> bool:
        """Grow ``req``'s block table to cover ``want_tokens`` cache
        entries, preempting lower-priority work if the pool is dry.
        Only ever allocates the shortfall, so replanning the same step
        (after a membership reshape) is idempotent."""
        need = self.pool.blocks_for_tokens(want_tokens) - len(req.blocks)
        if need <= 0:
            return True
        while True:
            got = self.pool.alloc(need)
            if got is not None:
                req.blocks.extend(got)
                return True
            victim = self._preempt_candidate_locked(req)
            if victim is None:
                return False
            self._preempt_locked(victim)
            if victim is req:
                return False

    def _preempt_candidate_locked(self, needer: Request):
        """Lowest-priority, youngest active request — the needer itself
        is a legal victim only if nothing ranks below it."""
        active = [r for r in self._slots if r is not None]
        if not active:
            return None
        victim = min(active, key=lambda r: (r.priority, -r.t_submit,
                                            -r.id))
        if victim is needer and len(active) == 1:
            return None  # alone and starved: stay put, retry next step
        return victim

    def _preempt_locked(self, req: Request) -> None:
        self.pool.free(req.blocks)
        req.blocks = []
        req.filled = 0
        self._slots[req.slot] = None
        req.slot = None
        req.state = QUEUED
        heapq.heappush(self._queue,
                       (-req.priority, next(self._submit_seq), req))
        req.span("preempted")
        self._reg.record_serving("preempted", req.tenant)
        self._update_gauges()

    # -- step completion --------------------------------------------------

    def complete_step(self, plan: Plan,
                      sampled: Sequence[int]) -> List[Request]:
        """Fold a completed step back in: advance fill positions, append
        sampled tokens where the step produced one, retire finished
        requests (freeing their blocks immediately).  ``sampled`` is
        indexed by batch slot.  Returns the requests retired this step."""
        finished = []
        now = time.monotonic()
        with self._lock:
            # Step accounting lives HERE, not in step_plan: a plan whose
            # broadcast a reshape cancelled is re-planned and must count
            # once — steps means steps EXECUTED.
            self._reg.record_serving_step(len(plan.slots),
                                          self.cfg.max_batch)
            for sp in plan.slots:
                req = self._slots[sp.slot]
                if req is None or req.id != sp.request_id:
                    continue  # retired/preempted under a replan
                was_prefill = req.filled < len(req.prompt_ids)
                req.filled += sp.n_new or sp.bulk_len
                req.span("prefill_chunk" if was_prefill else "decode_step",
                         now, step=plan.step,
                         tokens=sp.n_new or sp.bulk_len)
                if not sp.samples:
                    continue
                tok = int(sampled[sp.slot])
                if req.t_first_token is None:
                    req.t_first_token = now
                    self._reg.observe("serving_ttft_sec",
                                      now - req.t_submit)
                req.generated.append(tok)
                self._reg.record_serving_tokens(req.tenant, "generated", 1)
                eos = self.cfg.eos_id >= 0 and tok == self.cfg.eos_id
                if (eos or len(req.generated) >= req.max_new_tokens
                        or len(req.feed) >= self.cfg.max_seq):
                    self._retire_locked(req, now)
                    finished.append(req)
            self._update_gauges()
        return finished

    def _retire_locked(self, req: Request, now: float) -> None:
        self.pool.free(req.blocks)
        req.blocks = []
        self._slots[req.slot] = None
        req.slot = None
        req.state = DONE
        req.t_done = now
        req.finish_seq = next(self._finish_seq)
        req.span("retired", now, generated=len(req.generated))
        self._store_trace_locked(req)
        self._reg.record_serving("retired", req.tenant)
        self._reg.observe("serving_token_sec",
                          (now - req.t_submit)
                          / max(len(req.generated), 1))
        del self._by_id[req.id]
        req.event.set()

    def _store_trace_locked(self, req: Request) -> None:
        self._traces[req.id] = {
            "id": req.id, "tenant": req.tenant, "state": req.state,
            "finish_seq": req.finish_seq,
            "spans": [dict(s) for s in req.spans],
            "dropped_spans": req.dropped_spans,
        }
        while len(self._traces) > _MAX_TRACES:
            self._traces.popitem(last=False)

    def trace(self, request_id: int) -> Optional[dict]:
        """Ordered span records for one request — live (still queued or
        decoding) or finished (bounded store).  None when unknown (never
        admitted, or evicted from the store)."""
        with self._lock:
            req = self._by_id.get(request_id)
            if req is not None:
                return {"id": req.id, "tenant": req.tenant,
                        "state": req.state, "finish_seq": req.finish_seq,
                        "spans": [dict(s) for s in req.spans],
                        "dropped_spans": req.dropped_spans}
            entry = self._traces.get(request_id)
            if entry is None:
                return None
            return dict(entry, spans=[dict(s) for s in entry["spans"]])

    # -- robustness -------------------------------------------------------

    def reform(self, lost_ranks: Sequence[int]) -> None:
        """A membership reshape cancelled the in-flight step.  Survivor
        KV pages and scheduler state are both intact, so nothing is
        dropped: the next ``step_plan`` re-forms the identical batch and
        in-flight requests resume (docs/inference.md#reshape-semantics).
        """
        with self._lock:
            self._reg.record_serving("reformed")
            for req in self._slots:
                if req is not None:
                    req.span("reformed")

    def fail_all(self, exc: Exception) -> None:
        """The plane is down (fatal collective error or shutdown): fail
        every in-flight request typed and reject future submissions."""
        with self._lock:
            self._failed = exc
            for req in list(self._by_id.values()):
                req.state = FAILED
                req.error = ServingUnavailableError(
                    f"request {req.id} aborted: {exc}")
                if req.blocks:
                    self.pool.free(req.blocks)
                    req.blocks = []
                if req.slot is not None:
                    self._slots[req.slot] = None
                    req.slot = None
                req.span("failed", error=str(exc)[:200])
                self._store_trace_locked(req)
                self._reg.record_serving("failed", req.tenant)
                req.event.set()
            self._by_id.clear()
            self._queue.clear()
            self._update_gauges()

    # -- introspection ----------------------------------------------------

    @property
    def failed(self) -> Optional[Exception]:
        return self._failed

    def idle(self) -> bool:
        with self._lock:
            return not self._queue and not any(self._slots)

    def _update_gauges(self) -> None:
        self._reg.set_serving_gauges(
            queue_depth=len([e for e in self._queue
                             if e[2].state == QUEUED]),
            active=sum(1 for r in self._slots if r is not None),
            batch_slots=self.cfg.max_batch,
            kv_blocks_in_use=self.pool.blocks_in_use,
            kv_blocks_total=self.pool.num_blocks)
