"""Serving-rank entrypoint: ``hvdrun --serve`` launches this module on
every rank (``python -m horovod_tpu.serving``; docs/inference.md).

Rank 0 opens the HTTP front door (``HVD_TPU_SERVE_PORT``) over the
scheduler; every rank joins the decode loop.  The process exits 0 on an
orderly ``POST /shutdown`` drain; fatal collective errors exit nonzero so
the launcher's restart/elastic accounting sees them.
"""

from __future__ import annotations

import sys


def main() -> int:
    import horovod_tpu as hvd
    from horovod_tpu.serving import server as _server
    from horovod_tpu.serving.engine import (ModelSpec, ServingEngine,
                                            broadcast_params, init_params)
    from horovod_tpu.serving.scheduler import Scheduler, ServeConfig

    if hvd.restart_epoch() or __import__("os").environ.get(
            "HVD_TPU_REJOIN"):
        # A relaunched or standby serving rank has no way to recover the
        # in-flight KV state; serving composes with --min-np (shrink and
        # continue) but not with standby rejoin (docs/inference.md).
        print("horovod_tpu.serving: standby/restarted serve ranks are "
              "not supported; launch fresh", file=sys.stderr)
        return 3
    hvd.init()
    cfg = ServeConfig.from_env()
    spec = ModelSpec.from_env()
    params = broadcast_params(init_params(spec))
    rank0 = hvd.rank() == 0
    scheduler = Scheduler(cfg) if rank0 else None
    engine = ServingEngine(spec, cfg, params, scheduler)
    port = None
    if rank0:
        port = _server.start_server(scheduler, cfg, engine=engine)
        print(f"horovod_tpu.serving: listening on port {port} "
              f"(size {hvd.size()}, model {spec.n_layers}L/"
              f"{spec.d_model}d/vocab {spec.vocab})", flush=True)
    try:
        engine.run()
    finally:
        if rank0:
            if scheduler.failed is None:
                from horovod_tpu.serving.scheduler import \
                    ServingUnavailableError

                scheduler.fail_all(
                    ServingUnavailableError("server shut down"))
            _server.stop_server()
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
