"""Block-granular KV cache for the serving plane (docs/inference.md).

Two halves, split so the scheduler stays a pure-Python unit:

* :class:`BlockPool` — host-side bookkeeping: a fixed population of
  fixed-size token blocks, allocated all-or-nothing per request growth and
  freed on retirement.  Pool exhaustion is an admission/scheduling signal
  (requests stay queued, running requests preempt), never a crash — the
  vLLM/PagedAttention memory model (Kwon et al., SOSP'23) over our engine.

* The paged device store — ONE packed buffer for every layer's K and V
  (``(n_layers, 2, num_blocks + 1, block_tokens, heads, head_dim)``), the
  TreePacker move (models/packing.py) applied to the KV cache: 2·L·B
  per-sequence tensors become one array, gathered per step by block table
  and scattered by (block, offset).  The last block is a write-off target:
  masked lanes of a scatter and table padding both land there, so the
  jitted decode step keeps a fixed shape regardless of which slots are
  live.  :func:`gather_context` / :func:`scatter_new` are pure ``jnp``
  functions used inside the engine's jitted step.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence


class BlockPool:
    """Fixed pool of KV blocks, ``block_tokens`` tokens each.

    Allocation is all-or-nothing (a partial grant would leave a request
    unable to run but holding memory) and LIFO on the free list, so block
    ids stay deterministic across ranks replaying the same admission
    sequence — the scheduler's block tables travel in the broadcast batch
    plan, so determinism here is convenience (debuggability), not
    correctness.
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks < 1 or block_tokens < 1:
            raise ValueError(
                f"need num_blocks >= 1 and block_tokens >= 1, got "
                f"{num_blocks}/{block_tokens}")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._in_use = 0
        self.peak_in_use = 0

    @property
    def blocks_in_use(self) -> int:
        return self._in_use

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    def blocks_for_tokens(self, tokens: int) -> int:
        """Blocks needed to hold `tokens` cache entries."""
        return max(0, math.ceil(tokens / self.block_tokens))

    def alloc(self, n: int) -> Optional[List[int]]:
        """`n` fresh block ids, or None when the pool cannot satisfy all
        of them (all-or-nothing; the caller queues or preempts)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        taken = [self._free.pop() for _ in range(n)]
        self._in_use += n
        self.peak_in_use = max(self.peak_in_use, self._in_use)
        return taken

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if not (0 <= b < self.num_blocks):
                raise ValueError(f"block id {b} out of range")
            self._free.append(b)
        self._in_use -= len(blocks)
        assert self._in_use >= 0, "double free"


# ---------------------------------------------------------------------------
# Paged device store (jax; imported lazily so the pure scheduler/pool units
# never pull jax in).
# ---------------------------------------------------------------------------


def init_pages(n_layers: int, n_heads: int, head_dim: int, num_blocks: int,
               block_tokens: int, dtype):
    """The packed page buffer: ``(L, 2, num_blocks + 1, bt, h, hd)``
    zeros; index 0 of axis 1 is K, index 1 is V; block ``num_blocks`` is
    the trash block (see module docstring)."""
    import jax.numpy as jnp

    return jnp.zeros((n_layers, 2, num_blocks + 1, block_tokens,
                      n_heads, head_dim), dtype)


def gather_context(pages, tables):
    """Per-layer K/V context for a decode batch.

    ``tables``: ``(B, max_blocks)`` int32 block ids, padded with the
    trash block.  Returns ``(k_ctx, v_ctx)``, each ``(L, B, heads,
    max_blocks * block_tokens, head_dim)`` — position ``p`` of the
    flattened axis is token ``p`` of that row's cache (tables are kept in
    token order), so the caller's validity mask is just ``p < length``.
    """
    import jax.numpy as jnp

    n_layers, _, _, bt, h, hd = pages.shape
    batch, nb = tables.shape
    ctx = pages[:, :, tables]                       # (L, 2, B, nb, bt, h, hd)
    ctx = ctx.reshape(n_layers, 2, batch, nb * bt, h, hd)
    ctx = jnp.swapaxes(ctx, 3, 4)                   # (L, 2, B, h, S, hd)
    return ctx[:, 0], ctx[:, 1]


def scatter_new(pages, k_new, v_new, tables, lengths, n_new):
    """Write a step's fresh K/V into the pages.

    ``k_new``/``v_new``: ``(L, B, heads, chunk, head_dim)`` (the model's
    decode output).  Row ``b``'s token ``j`` lands at cache position
    ``lengths[b] + j``; lanes with ``j >= n_new[b]`` (padding, idle
    slots) are routed to the trash block, so the write is shape-static.
    """
    import jax.numpy as jnp

    bt = pages.shape[3]
    trash = pages.shape[2] - 1
    chunk = k_new.shape[3]
    pos = lengths[:, None] + jnp.arange(chunk)[None, :]        # (B, chunk)
    block_slot = pos // bt
    # Clip before take_along_axis: an idle slot's garbage position could
    # index past the table; its write is trash-routed below anyway.
    block_slot = jnp.clip(block_slot, 0, tables.shape[1] - 1)
    block = jnp.take_along_axis(tables, block_slot, axis=1)
    off = pos % bt
    valid = jnp.arange(chunk)[None, :] < n_new[:, None]
    block = jnp.where(valid, block, trash)
    # new_kv -> (L, 2, B, chunk, h, hd) to line up with the advanced-index
    # result shape of pages[:, :, block, off].
    new_kv = jnp.stack([k_new, v_new], axis=1)
    new_kv = jnp.swapaxes(new_kv, 3, 4)
    return pages.at[:, :, block, off].set(new_kv)
