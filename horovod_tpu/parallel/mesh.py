"""Mesh construction and data sharding helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_parallel_mesh(devices: Optional[Sequence] = None,
                       axis_name: str = "hvd") -> Mesh:
    """A 1-D mesh over all (or the given) devices — pure data parallelism,
    the single strategy the reference implements (SURVEY §2.7)."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def hierarchical_mesh(devices: Optional[Sequence] = None,
                      outer_axis: str = "dcn",
                      inner_axis: str = "ici",
                      num_slices: Optional[int] = None) -> Mesh:
    """A 2-D (hosts/slices × chips-per-slice) mesh.

    The TPU analogue of the reference's `cross_comm` × `local_comm` split:
    reductions along ``inner_axis`` stay on ICI; the ``outer_axis`` step
    crosses DCN.  ``num_slices`` defaults to the process count (one process
    per host) or to the device `slice_index` topology when available.

    This is the XLA-compiled mirror of the engine's two-level allreduce
    (``HOROVOD_HIERARCHICAL_ALLREDUCE``,
    docs/performance.md#two-level-topology): a ``psum`` over
    ``(inner, outer)`` lowers to reduce-scatter-on-ICI →
    cross-slice-on-DCN → allgather-on-ICI, the same decomposition the
    TCP engine runs by hand — every inner-axis member drives its own
    shard's DCN stream, not a single per-slice leader.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if num_slices is None:
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        num_slices = len(slice_ids) if len(slice_ids) > 1 else (
            jax.process_count() if jax.process_count() > 1 else 1)
    if len(devices) % num_slices:
        raise ValueError(
            f"{len(devices)} devices do not divide into {num_slices} slices")
    arr = np.array(devices).reshape(num_slices, len(devices) // num_slices)
    return Mesh(arr, (outer_axis, inner_axis))


def shard_batch(mesh: Mesh, batch, axis_name: Optional[str] = None):
    """Place a host batch onto the mesh, sharded along its leading dim.

    ``axis_name`` defaults to all mesh axes (fully data-parallel layout over
    a hierarchical mesh).  The per-worker data sharding the reference gets
    from `DistributedSampler` / per-rank input pipelines happens here instead
    via sharded `device_put`.
    """
    axes = (axis_name,) if axis_name else tuple(mesh.axis_names)
    sharding = NamedSharding(mesh, P(axes))
    return jax.device_put(batch, sharding)


def replicate(mesh: Mesh, tree):
    """Fully replicate a pytree (parameters, optimizer state) on the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)
