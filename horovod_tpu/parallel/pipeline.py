"""Pipeline parallelism: 1F1B schedules over the p2p plane (docs/pipeline.md).

The world is arranged as a ``stages x data-parallel`` grid
(:class:`PipelineGrid`): collectives scoped to one stage's
:func:`~horovod_tpu.common.stage_group` reduce along the DP axis, while
activations and activation-gradients cross stages over the engine's
point-to-point plane (``hvd.send``/``hvd.recv``).  The schedule layer is
pure Python — :func:`schedule_1f1b` and :func:`schedule_interleaved`
emit per-stage action lists, :func:`simulate_schedule` model-checks any
schedule's cross-stage dependencies in-process — so schedule bugs are
unit-test failures, not 4-rank hangs.

Micro-batch activations travel on **fixed-shape float32 buckets**: every
cycle re-announces the same (name, shape, dtype) sequence, so after the
first step the PR-4 response cache serves the negotiation and the PR-13
zero-frame steady state can take over the control plane entirely.

:class:`TransformerStage` partitions ``models/transformer.py`` by layer
range under the SAME parameter names, so :func:`partition_params` slices
a full-model checkpoint into per-stage trees exactly.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Optional, Sequence

import numpy as np

__all__ = [
    "PipeAction", "schedule_1f1b", "schedule_interleaved",
    "bubble_fraction", "simulate_schedule", "PipelineGrid",
    "TransformerStage", "partition_transformer", "partition_params",
    "LocalTransport", "EngineTransport", "PipelineRunner",
    "run_local_pipeline",
]


class PipeAction(NamedTuple):
    """One slot of a stage's schedule: run ``kind`` ("fwd"/"bwd") for
    micro-batch ``microbatch`` of model chunk ``chunk`` (always 0 without
    interleaving)."""

    kind: str
    microbatch: int
    chunk: int = 0


def schedule_1f1b(stage: int, n_stages: int, n_micro: int) -> List[PipeAction]:
    """The non-interleaved 1F1B schedule for one stage.

    Warmup runs ``n_stages - 1 - stage`` forwards, the steady state
    alternates one-forward-one-backward (peak activation stash is
    ``warmup + 1`` micro-batches instead of GPipe's ``n_micro``), and the
    cooldown drains the remaining backwards.
    """
    if not (0 <= stage < n_stages):
        raise ValueError(f"stage {stage} out of range for {n_stages} stages")
    if n_micro < 1:
        raise ValueError(f"need at least one micro-batch, got {n_micro}")
    warmup = min(n_stages - 1 - stage, n_micro)
    actions = [PipeAction("fwd", i) for i in range(warmup)]
    fwd, bwd = warmup, 0
    for _ in range(n_micro - warmup):
        actions.append(PipeAction("fwd", fwd))
        fwd += 1
        actions.append(PipeAction("bwd", bwd))
        bwd += 1
    for _ in range(warmup):
        actions.append(PipeAction("bwd", bwd))
        bwd += 1
    return actions


def schedule_interleaved(stage: int, n_stages: int, n_micro: int,
                         n_chunks: int) -> List[PipeAction]:
    """The interleaved (virtual-stage) 1F1B schedule.

    Each rank holds ``n_chunks`` model chunks; virtual stage
    ``v = chunk * n_stages + stage`` shrinks the bubble by ``1/n_chunks``
    at the price of more p2p traffic.  Micro-batches advance in groups of
    ``n_stages`` per chunk (the Megatron-LM ordering), which requires
    ``n_micro`` to divide evenly.
    """
    if n_chunks == 1:
        return schedule_1f1b(stage, n_stages, n_micro)
    if n_chunks < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    if n_micro % n_stages:
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) divisible by "
            f"n_stages ({n_stages})")
    total = n_micro * n_chunks
    group = n_stages * n_chunks

    def fwd_at(k: int) -> PipeAction:
        chunk = (k // n_stages) % n_chunks
        mb = (k // group) * n_stages + k % n_stages
        return PipeAction("fwd", mb, chunk)

    def bwd_at(k: int) -> PipeAction:
        chunk = n_chunks - 1 - (k // n_stages) % n_chunks
        mb = (k // group) * n_stages + k % n_stages
        return PipeAction("bwd", mb, chunk)

    warmup = min((n_stages - stage - 1) * 2 + (n_chunks - 1) * n_stages,
                 total)
    actions = [fwd_at(k) for k in range(warmup)]
    fwd, bwd = warmup, 0
    for _ in range(total - warmup):
        actions.append(fwd_at(fwd))
        fwd += 1
        actions.append(bwd_at(bwd))
        bwd += 1
    for _ in range(warmup):
        actions.append(bwd_at(bwd))
        bwd += 1
    return actions


def bubble_fraction(n_stages: int, n_micro: int, n_chunks: int = 1) -> float:
    """Idle fraction of the 1F1B pipeline: ``(S-1) / (S-1 + M*V)`` —
    the warmup/cooldown ramps amortized over the micro-batch stream."""
    ramp = n_stages - 1
    return ramp / (ramp + n_micro * n_chunks)


def simulate_schedule(n_stages: int, n_micro: int, n_chunks: int = 1,
                      schedule_fn: Optional[Callable] = None) -> int:
    """Model-check a schedule's cross-stage dependencies in-process.

    Runs every stage's action list against the data-dependency rules —
    a forward needs the previous virtual stage's forward of the same
    micro-batch, a backward needs the local forward plus the next virtual
    stage's backward — and raises on deadlock, double execution, or an
    unexecuted action.  Returns the number of lock-step ticks (each tick
    every stage executes at most one ready action): the wall-clock shape
    the bubble fraction predicts.
    """
    if schedule_fn is None:
        schedule_fn = (schedule_1f1b if n_chunks == 1 else
                       lambda s, S, M: schedule_interleaved(s, S, M,
                                                            n_chunks))
    plans = [schedule_fn(s, n_stages, n_micro) for s in range(n_stages)]
    cursor = [0] * n_stages
    done = set()  # (kind, microbatch, virtual_stage)
    last_virtual = n_stages * n_chunks - 1
    ticks = 0
    while any(cursor[s] < len(plans[s]) for s in range(n_stages)):
        progressed = False
        for s in range(n_stages):
            if cursor[s] >= len(plans[s]):
                continue
            kind, mb, chunk = plans[s][cursor[s]]
            v = chunk * n_stages + s
            if kind == "fwd":
                ready = v == 0 or ("fwd", mb, v - 1) in done
            else:
                ready = ("fwd", mb, v) in done and (
                    v == last_virtual or ("bwd", mb, v + 1) in done)
            if not ready:
                continue
            key = (kind, mb, v)
            if key in done:
                raise AssertionError(f"duplicate action {key} at stage {s}")
            done.add(key)
            cursor[s] += 1
            progressed = True
        if not progressed:
            stuck = {s: plans[s][cursor[s]] for s in range(n_stages)
                     if cursor[s] < len(plans[s])}
            raise AssertionError(f"schedule deadlock; blocked on {stuck}")
        ticks += 1
    expected = 2 * n_micro * n_chunks * n_stages
    if len(done) != expected:
        raise AssertionError(
            f"schedule executed {len(done)} actions, expected {expected}")
    return ticks


class PipelineGrid:
    """Rank layout of a ``stages x data-parallel`` job.

    Stage-major and contiguous: stage ``s`` owns global ranks
    ``[s*dp, (s+1)*dp)``, so a stage's DP group is a consecutive rank
    range and the pipeline peer at the same DP index is ``rank ± dp``.
    Contiguity matters for transport reuse: with ranks packed per host,
    a stage's DP collectives stay on intra-host shm rings while only the
    stage boundary crosses hosts.
    """

    def __init__(self, n_stages: int, world: int, rank: int):
        if n_stages < 1:
            raise ValueError(f"n_stages must be >= 1, got {n_stages}")
        if world % n_stages:
            raise ValueError(
                f"world size {world} does not divide into {n_stages} "
                f"pipeline stages")
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        self.n_stages = n_stages
        self.world = world
        self.rank = rank
        self.dp = world // n_stages
        self.stage = rank // self.dp
        self.dp_index = rank % self.dp

    def stage_ranks(self, stage: Optional[int] = None) -> List[int]:
        stage = self.stage if stage is None else stage
        return list(range(stage * self.dp, (stage + 1) * self.dp))

    def rank_of(self, stage: int, dp_index: Optional[int] = None) -> int:
        dp_index = self.dp_index if dp_index is None else dp_index
        return stage * self.dp + dp_index

    def stage_of(self, rank: int) -> int:
        return rank // self.dp

    @property
    def next_rank(self) -> int:
        """Peer holding the next pipeline stage (wraps for interleaved
        chunk boundaries: the last stage's forward feeds stage 0's next
        chunk)."""
        return self.rank_of((self.stage + 1) % self.n_stages)

    @property
    def prev_rank(self) -> int:
        return self.rank_of((self.stage - 1) % self.n_stages)


# ---------------------------------------------------------------------------
# Transformer partitioning (models/transformer.py -> stage submodules).
# ---------------------------------------------------------------------------

def _split_layers(n_layers: int, n_virtual: int) -> List[List[int]]:
    """Contiguous, near-even layer assignment: the first
    ``n_layers % n_virtual`` virtual stages take one extra layer."""
    if n_virtual > n_layers:
        raise ValueError(
            f"cannot split {n_layers} layers over {n_virtual} virtual "
            f"stages (stages x chunks)")
    base, extra = divmod(n_layers, n_virtual)
    out, at = [], 0
    for v in range(n_virtual):
        n = base + (1 if v < extra else 0)
        out.append(list(range(at, at + n)))
        at += n
    return out


def TransformerStage(*args, **kwargs):  # noqa: N802 - class factory
    """Deferred import wrapper so ``pipeline``'s schedule layer stays
    importable without flax; see :func:`_build_stage_cls`."""
    return _build_stage_cls()(*args, **kwargs)


_STAGE_CLS = None


def _build_stage_cls():
    global _STAGE_CLS
    if _STAGE_CLS is not None:
        return _STAGE_CLS
    import flax.linen as nn
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import Block

    class _TransformerStage(nn.Module):
        """One pipeline stage of ``TransformerLM``: a contiguous layer
        range, plus the embedding on the first virtual stage and
        final-norm + lm-head on the last.  Parameter names match the
        full model exactly (``embed``, ``layer_<i>``, ``final_norm``,
        ``lm_head_kernel``), so :func:`partition_params` slices a
        full-model tree into loadable stage trees."""

        vocab_size: int
        d_model: int
        n_heads: int
        layer_ids: tuple
        d_ff: Optional[int] = None
        dtype: Any = jnp.bfloat16
        is_first: bool = False
        is_last: bool = False
        use_flash: bool = True

        @nn.compact
        def __call__(self, x):
            d_ff = self.d_ff or 4 * self.d_model
            if self.is_first:
                x = nn.Embed(self.vocab_size, self.d_model,
                             dtype=self.dtype, name="embed")(x)
            for i in self.layer_ids:
                x = Block(self.n_heads, d_ff, self.dtype,
                          use_flash=self.use_flash, name=f"layer_{i}")(x)
            if self.is_last:
                x = nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)
                w = self.param(
                    "lm_head_kernel",
                    nn.initializers.variance_scaling(
                        1.0, "fan_in", "truncated_normal"),
                    (self.d_model, self.vocab_size), jnp.float32)
                x = jnp.einsum(
                    "bsd,dv->bsv", x.astype(self.dtype),
                    w.astype(self.dtype),
                    preferred_element_type=jnp.float32)
            return x

    _STAGE_CLS = _TransformerStage
    return _STAGE_CLS


def partition_transformer(vocab_size: int, d_model: int, n_layers: int,
                          n_heads: int, n_stages: int, n_chunks: int = 1,
                          d_ff: Optional[int] = None,
                          dtype: Any = None, use_flash: bool = True
                          ) -> List[List[Any]]:
    """Stage submodules for a ``TransformerLM`` split over
    ``n_stages x n_chunks`` virtual stages; returns
    ``modules[stage][chunk]`` (virtual order ``chunk * n_stages +
    stage``, matching the interleaved schedule)."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    n_virtual = n_stages * n_chunks
    layers = _split_layers(n_layers, n_virtual)
    cls = _build_stage_cls()
    out: List[List[Any]] = [[] for _ in range(n_stages)]
    for stage in range(n_stages):
        for chunk in range(n_chunks):
            v = chunk * n_stages + stage
            out[stage].append(cls(
                vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
                layer_ids=tuple(layers[v]), d_ff=d_ff, dtype=dtype,
                is_first=(v == 0), is_last=(v == n_virtual - 1),
                use_flash=use_flash))
    return out


def partition_params(full_params: dict, n_layers: int, n_stages: int,
                     n_chunks: int = 1) -> List[List[dict]]:
    """Slice a full ``TransformerLM`` param tree into per-virtual-stage
    trees (``params[stage][chunk]``) by the same layer split
    :func:`partition_transformer` uses.  Loss parity against the
    unpartitioned model is then exact: identical parameters, identical
    math, just distributed."""
    n_virtual = n_stages * n_chunks
    layers = _split_layers(n_layers, n_virtual)
    out: List[List[dict]] = [[] for _ in range(n_stages)]
    for stage in range(n_stages):
        for chunk in range(n_chunks):
            v = chunk * n_stages + stage
            tree = {f"layer_{i}": full_params[f"layer_{i}"]
                    for i in layers[v]}
            if v == 0:
                tree["embed"] = full_params["embed"]
            if v == n_virtual - 1:
                tree["final_norm"] = full_params["final_norm"]
                tree["lm_head_kernel"] = full_params["lm_head_kernel"]
            out[stage].append(tree)
    return out


# ---------------------------------------------------------------------------
# Transports: where activations/grads travel.
# ---------------------------------------------------------------------------

class LocalTransport:
    """In-process transport for unit tests: every stage runner shares one
    instance, sends append to named queues, receives drain them.  Peer
    ranks are ignored — the canonical tensor names are globally unique
    per step, exactly as on the wire."""

    def __init__(self):
        from collections import defaultdict, deque

        self._queues = defaultdict(deque)

    def send(self, array: np.ndarray, peer: int, name: str) -> None:
        self._queues[name].append(np.array(array, copy=True))

    def can_recv(self, name: str) -> bool:
        return bool(self._queues.get(name))

    def recv(self, out: np.ndarray, peer: int, name: str) -> None:
        out[...] = self._queues[name].popleft()

    def flush(self) -> None:
        pass


class EngineTransport:
    """The real thing: p2p over the engine (docs/pipeline.md).  Sends are
    enqueued asynchronously and flushed at step end — a blocking send
    would deadlock against the blocking receive the 1F1B steady state
    interleaves it with; the engine's paired-readiness negotiation
    orders the actual transfers."""

    def __init__(self, tag: int = 0):
        self.tag = tag
        self._pending: list = []

    def send(self, array: np.ndarray, peer: int, name: str) -> None:
        from horovod_tpu import common as hvd

        # Keep the buffer referenced until flush: the engine reads it at
        # execute time, after this call returned.
        buf = np.ascontiguousarray(array)
        self._pending.append(hvd.send_async(buf, peer, self.tag, name))

    def can_recv(self, name: str) -> bool:
        return True  # recv() blocks; the engine thread makes progress

    def recv(self, out: np.ndarray, peer: int, name: str) -> None:
        from horovod_tpu import common as hvd

        hvd.recv(out, peer, self.tag, name)

    def flush(self) -> None:
        pending, self._pending = self._pending, []
        for handle in pending:
            handle.wait()


# ---------------------------------------------------------------------------
# The runner: one rank's schedule execution.
# ---------------------------------------------------------------------------

class PipelineRunner:
    """Execute a 1F1B (or interleaved) schedule for one rank's stage.

    ``stages``/``params`` are per-chunk lists (length 1 without
    interleaving).  The runner stashes one VJP closure per in-flight
    micro-batch (the 1F1B stash bound: ``warmup + 1``), accumulates
    parameter gradients per chunk, and moves activations on fixed-shape
    float32 buckets through the given transport.  ``loss_fn(logits,
    targets)`` runs on the last virtual stage only.

    A mid-schedule stage death surfaces from the engine as
    :class:`~horovod_tpu.common.RanksDownError`; the runner re-raises it
    naming the dead *stage* so pipeline operators see grid coordinates,
    not just rank numbers.
    """

    def __init__(self, stages: Sequence, params: Sequence, grid: PipelineGrid,
                 n_micro: int, transport, loss_fn=None,
                 prefix: str = "pipe"):
        if len(stages) != len(params):
            raise ValueError("stages and params must pair per chunk")
        self.stages = list(stages)
        self.params = list(params)
        self.grid = grid
        self.n_chunks = len(self.stages)
        self.n_micro = n_micro
        self.transport = transport
        self.loss_fn = loss_fn
        if grid.stage == grid.n_stages - 1 and loss_fn is None:
            raise ValueError(
                "the last pipeline stage computes the loss: pass loss_fn=")
        self.prefix = prefix
        self.schedule = schedule_interleaved(
            grid.stage, grid.n_stages, n_micro, self.n_chunks)
        self._n_virtual = grid.n_stages * self.n_chunks
        self._reset()

    def _reset(self):
        self._cursor = 0
        self._stash = {}           # (mb, chunk) -> vjp closure
        self._grads = [None] * self.n_chunks
        self._losses: list = []
        self._inputs = None
        self._targets = None
        self._recv_buf = {}        # chunk -> reusable activation bucket

    def _virtual(self, chunk: int) -> int:
        return chunk * self.grid.n_stages + self.grid.stage

    def _fwd_name(self, v: int, mb: int) -> str:
        # Named by the RECEIVING virtual stage: both ends derive it from
        # the edge, so the sender of v-1 and the receiver at v agree.
        return f"{self.prefix}.fwd.v{v}.mb{mb}"

    def _bwd_name(self, v: int, mb: int) -> str:
        return f"{self.prefix}.bwd.v{v}.mb{mb}"

    def _needed_recv(self, action: PipeAction) -> Optional[str]:
        v = self._virtual(action.chunk)
        if action.kind == "fwd":
            return None if v == 0 else self._fwd_name(v, action.microbatch)
        return (None if v == self._n_virtual - 1
                else self._bwd_name(v, action.microbatch))

    # -- step drivers -------------------------------------------------------

    def begin_step(self, inputs=None, targets=None) -> None:
        """Arm one optimization step.  ``inputs`` (first stage) and
        ``targets`` (last stage) are full per-DP-rank batches, split
        into ``n_micro`` equal micro-batches along axis 0."""
        self._reset()
        if inputs is not None:
            if inputs.shape[0] % self.n_micro:
                raise ValueError(
                    f"batch dim {inputs.shape[0]} does not split into "
                    f"{self.n_micro} micro-batches")
            self._inputs = np.split(np.asarray(inputs), self.n_micro)
        if targets is not None:
            if targets.shape[0] % self.n_micro:
                raise ValueError(
                    f"target dim {targets.shape[0]} does not split into "
                    f"{self.n_micro} micro-batches")
            self._targets = np.split(np.asarray(targets), self.n_micro)

    @property
    def done(self) -> bool:
        return self._cursor >= len(self.schedule)

    def try_next(self) -> bool:
        """Execute the next scheduled action if its input is available
        (cooperative mode: the in-process driver round-robins stages).
        Returns False when blocked or done."""
        if self.done:
            return False
        action = self.schedule[self._cursor]
        needed = self._needed_recv(action)
        if needed is not None and not self.transport.can_recv(needed):
            return False
        self._execute(action)
        self._cursor += 1
        return True

    def finish_step(self):
        """``(loss, grads)`` after the schedule drained: mean micro-batch
        loss on the last virtual stage (None elsewhere), per-chunk
        parameter-gradient trees everywhere."""
        if not self.done:
            raise RuntimeError(
                f"schedule not drained: {self._cursor}/"
                f"{len(self.schedule)} actions done")
        self.transport.flush()
        loss = (float(np.mean(self._losses)) if self._losses else None)
        return loss, self._grads

    def step(self, inputs=None, targets=None):
        """Blocking end-to-end step (engine transport): run the whole
        schedule, return :meth:`finish_step`'s ``(loss, grads)``."""
        from horovod_tpu.common import RanksDownError

        self.begin_step(inputs, targets)
        try:
            while not self.done:
                if not self.try_next():
                    raise RuntimeError(
                        "pipeline blocked with a non-blocking transport; "
                        "use run_local_pipeline to drive multiple stages "
                        "in one process")
            return self.finish_step()
        except RanksDownError as exc:
            stages = sorted({self.grid.stage_of(r) for r in exc.ranks})
            named = ", ".join(f"stage {s} (ranks "
                              f"{self.grid.stage_ranks(s)})"
                              for s in stages) or "unknown stage"
            raise RanksDownError(
                f"pipeline aborted mid-schedule at action "
                f"{self._cursor}/{len(self.schedule)}: {named} died: "
                f"{exc}", exc.ranks) from exc

    # -- action execution ---------------------------------------------------

    def _bucket(self, chunk: int, shape, dtype) -> np.ndarray:
        """The fixed-shape receive bucket for this chunk — allocated
        once, reused every micro-batch, so the announced (name, shape,
        dtype) stream repeats exactly and stays cacheable."""
        buf = self._recv_buf.get(chunk)
        if buf is None or buf.shape != tuple(shape) or buf.dtype != dtype:
            buf = np.empty(shape, dtype)
            self._recv_buf[chunk] = buf
        return buf

    def _stage_fn(self, chunk: int, v: int, mb: int):
        import jax.numpy as jnp

        stage = self.stages[chunk]
        if v == self._n_virtual - 1 and self.loss_fn is not None:
            if self._targets is None:
                raise ValueError(
                    "last pipeline stage needs targets= in begin_step")
            tgt = jnp.asarray(self._targets[mb])

            def fn(p, x):
                return self.loss_fn(stage.apply({"params": p}, x), tgt)
        else:
            def fn(p, x):
                return stage.apply({"params": p}, x)
        return fn

    def _act_shape(self, mb: int):
        """Activation bucket geometry between virtual stages: (micro
        batch, seq, d_model) float32 — model-dtype outputs upcast for
        the wire (cross-host hops re-compress to bf16/fp8 under
        HVD_TPU_COMPRESSION, with error feedback)."""
        src = self._inputs[mb] if self._inputs is not None else None
        if src is None:
            raise ValueError("first pipeline stage needs inputs= in "
                             "begin_step")
        return (src.shape[0], src.shape[1], self.stages[0].d_model)

    def _execute(self, action: PipeAction) -> None:
        import jax
        import jax.numpy as jnp

        mb, chunk = action.microbatch, action.chunk
        v = self._virtual(chunk)
        first_v, last_v = v == 0, v == self._n_virtual - 1
        if action.kind == "fwd":
            if first_v:
                x = jnp.asarray(self._inputs[mb])
            else:
                shape = (*self._recv_shape_hint(), )
                buf = self._bucket(chunk, shape, np.float32)
                self.transport.recv(buf, self._fwd_peer(recv=True),
                                    self._fwd_name(v, mb))
                x = jnp.array(buf)
            fn = self._stage_fn(chunk, v, mb)
            if first_v:
                out, vjp = jax.vjp(lambda p: fn(p, x), self.params[chunk])
            else:
                out, vjp = jax.vjp(fn, self.params[chunk], x)
            self._stash[(mb, chunk)] = vjp
            if last_v:
                self._losses.append(float(out))
            else:
                self.transport.send(
                    np.asarray(out, np.float32), self._fwd_peer(recv=False),
                    self._fwd_name(v + 1, mb))
        else:
            vjp = self._stash.pop((mb, chunk))
            if last_v:
                # Seed 1/M: the step loss is the micro-batch mean, so the
                # accumulated grads equal the full-batch mean gradient.
                seed = jnp.float32(1.0 / self.n_micro)
            else:
                shape = (*self._recv_shape_hint(), )
                buf = self._bucket(chunk, shape, np.float32)
                self.transport.recv(buf, self._bwd_peer(recv=True),
                                    self._bwd_name(v, mb))
                seed = jnp.array(buf)
            cots = vjp(seed)
            dparams = cots[0]
            acc = self._grads[chunk]
            self._grads[chunk] = dparams if acc is None else jax.tree.map(
                lambda a, b: a + b, acc, dparams)
            if not first_v:
                self.transport.send(
                    np.asarray(cots[1], np.float32),
                    self._bwd_peer(recv=False), self._bwd_name(v - 1, mb))

    def _recv_shape_hint(self):
        """Geometry of incoming buckets.  Every virtual stage moves
        (micro_batch, seq, d_model); the first stage knows it from its
        inputs, others carry it via set_bucket_shape."""
        if self._bucket_shape is not None:
            return self._bucket_shape
        if self._inputs is not None:
            return self._act_shape(0)
        raise ValueError(
            "pipeline stage needs set_bucket_shape(micro_batch, seq, "
            "d_model) before stepping (fixed-shape bucket contract)")

    _bucket_shape = None

    def set_bucket_shape(self, micro_batch: int, seq: int,
                         d_model: Optional[int] = None) -> None:
        """Declare the fixed activation-bucket geometry.  Mandatory on
        stages that never see ``inputs=``; the shape is part of the p2p
        contract — the coordinator rejects a sender/receiver mismatch
        with a typed precondition error rather than corrupting a
        transfer."""
        d_model = d_model if d_model is not None else self.stages[0].d_model
        self._bucket_shape = (micro_batch, seq, d_model)

    def _fwd_peer(self, recv: bool) -> int:
        return self.grid.prev_rank if recv else self.grid.next_rank

    def _bwd_peer(self, recv: bool) -> int:
        return self.grid.next_rank if recv else self.grid.prev_rank


def run_local_pipeline(runners: Sequence[PipelineRunner], inputs,
                       targets) -> tuple:
    """Drive every stage of a pipeline in ONE process over a shared
    :class:`LocalTransport` — the unit-test harness: cooperative
    round-robin until every schedule drains, deadlock detected when no
    stage can move.  Returns ``(loss, [per-stage grads])``."""
    for i, r in enumerate(runners):
        r.begin_step(inputs if r.grid.stage == 0 else None,
                     targets if r.grid.stage == r.grid.n_stages - 1
                     else None)
        if r.grid.stage != 0:
            mb = inputs.shape[0] // r.n_micro
            r.set_bucket_shape(mb, inputs.shape[1])
    while not all(r.done for r in runners):
        progressed = False
        for r in runners:
            if r.try_next():
                progressed = True
        if not progressed:
            stuck = {r.grid.stage: r.schedule[r._cursor]
                     for r in runners if not r.done}
            raise AssertionError(
                f"pipeline deadlock; stages blocked on {stuck}")
    results = [r.finish_step() for r in runners]
    loss = next((lo for lo, _ in results if lo is not None), None)
    return loss, [g for _, g in results]
