"""Device-mesh and sharding utilities: the TPU-native communicator layer.

The reference's communicator model — `mpi_comm` (global), `local_comm`
(per-node), `cross_comm` (one rank per node)
(/root/reference/horovod/common/operations.cc:181-189,1364-1389) — maps on
TPU to a `jax.sharding.Mesh` whose axes separate ICI (chips within a slice)
from DCN (across slices/hosts).  Collectives laid out along the ICI axis ride
the high-bandwidth interconnect; the DCN axis carries the hierarchical
(cross-host) step, exactly the split the reference's hierarchical allreduce
exploits (/root/reference/horovod/common/operations.cc:1003-1048).
"""

from horovod_tpu.parallel.mesh import (  # noqa: F401
    data_parallel_mesh,
    hierarchical_mesh,
    replicate,
    shard_batch,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    EngineTransport,
    LocalTransport,
    PipeAction,
    PipelineGrid,
    PipelineRunner,
    TransformerStage,
    bubble_fraction,
    partition_params,
    partition_transformer,
    run_local_pipeline,
    schedule_1f1b,
    schedule_interleaved,
    simulate_schedule,
)
