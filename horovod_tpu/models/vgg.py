"""VGG (configurations A/D/E = VGG-11/16/19), TPU-tuned flax implementation.

VGG-16 is one of the reference's three published scaling benchmarks
(68% efficiency at 512 GPUs, /root/reference/README.md:50,
docs/benchmarks.md:6) — the hard case, being parameter-heavy: its ~138M
parameters stress gradient-exchange bandwidth, which is exactly what
tensor fusion / XLA collective overlap are for.

NHWC, bfloat16 compute, float32 params; classifier matches the original
(4096-4096-classes with dropout).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Per-stage conv counts; all convs are 3x3, channels 64,128,256,512,512.
_CFG = {
    "vgg11": (1, 1, 2, 2, 2),
    "vgg16": (2, 2, 3, 3, 3),
    "vgg19": (2, 2, 4, 4, 4),
}
_CHANNELS = (64, 128, 256, 512, 512)


class VGG(nn.Module):
    stage_convs: Sequence[int]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for stage, n_convs in enumerate(self.stage_convs):
            for i in range(n_convs):
                x = nn.Conv(_CHANNELS[stage], (3, 3), padding="SAME",
                            dtype=self.dtype,
                            name=f"conv{stage}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(4096, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(4096, dtype=self.dtype, name="fc2")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


VGG11 = functools.partial(VGG, stage_convs=_CFG["vgg11"])
VGG16 = functools.partial(VGG, stage_convs=_CFG["vgg16"])
VGG19 = functools.partial(VGG, stage_convs=_CFG["vgg19"])
