"""BatchStatsNorm: BatchNorm with the running-stats EMA hoisted out of the
model into ONE fused step-level op.

Why: a ResNet-101 has 104 BatchNorm layers; flax's ``nn.BatchNorm`` updates
each layer's running mean/var inside the module, which XLA compiles to
~208 tiny elementwise kernels plus memory-space copies — measured 1.4 ms
of pure per-op overhead per training step on v5e (docs/benchmarks.md,
round-3 tuning log).  ``BatchStatsNorm`` instead *writes the raw batch
statistics* into the ``batch_stats`` collection, and the training step
applies the EMA once over the whole flattened tree
(:func:`ema_batch_stats`) — numerically identical to per-layer flax BN
(same formula, same f32 stats), but 2 kernels instead of ~200.

Drop-in: parameter and variable names match ``nn.BatchNorm`` ("scale",
"bias" / "mean", "var"), so checkpoints interchange.  The contract is that
the TRAINING STEP calls ``ema_batch_stats(old, new, momentum)`` on the
returned mutable update; forgetting it stores raw batch stats (still
usable, just not smoothed).  Eval mode reads the running stats as usual.

No reference counterpart (the reference delegates BN to the frameworks);
this is TPU-first step-level fusion of framework bookkeeping.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree


class BatchStatsNorm(nn.Module):
    """``nn.BatchNorm``-compatible normalization with step-level EMA.

    In train mode (``use_running_average=False``) normalizes with the
    current batch statistics (exactly as flax BN does) and stores those
    RAW statistics in the ``batch_stats`` collection; apply
    :func:`ema_batch_stats` to the mutable update in the train step.
    """

    use_running_average: bool = False
    # NOT applied here: the step-level ema_batch_stats call must be passed
    # the same momentum (both default 0.9).  Kept as a field so module
    # configs stay interchangeable with nn.BatchNorm.
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    scale_init: Callable = nn.initializers.ones_init()

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(features, jnp.float32))
        scale = self.param("scale", self.scale_init, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (features,),
                          jnp.float32)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            reduce_axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = xf.mean(axis=reduce_axes)
            mean2 = (xf * xf).mean(axis=reduce_axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = mean2 - mean * mean
            if not self.is_initializing():
                ra_mean.value = mean
                ra_var.value = var
        # Fold the normalize into a per-channel affine y = x*a + b with the
        # COEFFICIENTS in float32 and the per-element arithmetic in the
        # compute dtype: normalizing in f32 materializes a full f32 copy of
        # every activation (measured ~40 convert_element_type kernels per
        # ResNet-101 step, tools/profile_step.py), while the bf16 affine
        # fuses into the producing conv's epilogue.  Stock flax BN computes
        # the whole normalize in the compute dtype, so this is strictly
        # more precise than the nn.BatchNorm path it interchanges with.
        a = lax.rsqrt(var + self.epsilon) * scale
        b = bias - mean * a
        x = x.astype(self.dtype)  # no-op for conv outputs already in dtype
        return x * a.astype(self.dtype) + b.astype(self.dtype)


class BatchNorm(BatchStatsNorm):
    """``BatchStatsNorm`` under the class name ``BatchNorm``: flax derives
    auto-generated module names from the class name (``BatchNorm_0`` …),
    so using this alias keeps fused-EMA param/stat trees *path-identical*
    to ``nn.BatchNorm`` ones — checkpoints interchange between the two
    paths."""


def ema_batch_stats(old_stats, batch_stats, momentum: float = 0.9):
    """One fused EMA over a whole ``batch_stats`` tree.

    ``new_running = momentum * old + (1 - momentum) * batch`` — the same
    update flax BN applies per layer, computed as a single elementwise op
    over the flattened tree.  Returns a tree with ``old_stats``'s
    structure.  The train step's stats carry becomes::

        logits, upd = model.apply({...}, x, train=True,
                                  mutable=["batch_stats"])
        new_stats = ema_batch_stats(stats, upd["batch_stats"])
    """
    flat_old, unravel = ravel_pytree(old_stats)
    flat_new, _ = ravel_pytree(batch_stats)
    return unravel(momentum * flat_old + (1.0 - momentum) * flat_new)
