"""ResNet family (v1.5), TPU-tuned flax implementation.

The workload of the reference's flagship examples and benchmarks
(`/root/reference/examples/keras_imagenet_resnet50.py`,
`/root/reference/examples/pytorch_imagenet_resnet50.py`,
`/root/reference/docs/benchmarks.md:22-38` — ResNet-101 images/sec).

TPU-first choices:
* NHWC layout and bfloat16 compute (`dtype=jnp.bfloat16`) — the MXU's native
  convolution layout and precision; parameters stay float32.
* BatchNorm with optional ``axis_name`` for cross-replica (sync) statistics
  inside `shard_map` — the role the reference delegates to per-worker BN plus
  gradient allreduce.
* Static shapes and `nn.Conv` everywhere: XLA tiles the convs onto the MXU
  and fuses the elementwise tail (BN + ReLU + residual add) into them.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut (ResNet v1.5:
    stride on the 3x3, matching the torchvision/keras models the reference
    examples use)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 → 3x3 block (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs.

    ``axis_name`` enables cross-replica BatchNorm inside mapped computations;
    leave None for per-worker statistics (the reference's behavior).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    small_inputs: bool = False  # CIFAR-style stem: 3x3/1, no maxpool

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.axis_name)

        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm, act=nn.relu)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="head")(x.astype(jnp.float32))
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
