"""ResNet family (v1.5), TPU-tuned flax implementation.

The workload of the reference's flagship examples and benchmarks
(`/root/reference/examples/keras_imagenet_resnet50.py`,
`/root/reference/examples/pytorch_imagenet_resnet50.py`,
`/root/reference/docs/benchmarks.md:22-38` — ResNet-101 images/sec).

TPU-first choices:
* NHWC layout and bfloat16 compute (`dtype=jnp.bfloat16`) — the MXU's native
  convolution layout and precision; parameters stay float32.
* BatchNorm with optional ``axis_name`` for cross-replica (sync) statistics
  inside `shard_map` — the role the reference delegates to per-worker BN plus
  gradient allreduce.
* Static shapes and `nn.Conv` everywhere: XLA tiles the convs onto the MXU
  and fuses the elementwise tail (BN + ReLU + residual add) into them.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut (ResNet v1.5:
    stride on the 3x3, matching the torchvision/keras models the reference
    examples use)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 → 3x3 block (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class SpaceToDepthStem(nn.Module):
    """The 7x7/stride-2 stem conv computed on space-to-depth-transformed
    input — mathematically *identical* to ``nn.Conv(64, (7,7), (2,2),
    SAME)`` (same (7,7,3,F) parameter, same function), but the MXU sees a
    4x4/stride-1 conv over 12 input channels instead of a 7x7/stride-2
    conv over 3, which tiles far better (3 channels fill 3 of 128 MXU
    lanes).  The MLPerf-era TPU ResNet trick, done as an in-graph weight
    reshape so checkpoints and initialization stay conv-compatible.
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # Same init/param shape as the plain conv stem.
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (7, 7, x.shape[-1], self.features), jnp.float32)
        b, h, wd, c = x.shape
        if h % 2 or wd % 2:  # odd sizes: plain conv (correctness path)
            return lax.conv_general_dilated(
                x.astype(self.dtype), w.astype(self.dtype), (2, 2), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # Input space-to-depth(2): (h, w, c) -> (h/2, w/2, 4c).
        x2 = x.reshape(b, h // 2, 2, wd // 2, 2, c)
        x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wd // 2,
                                                    4 * c)
        # Kernel: zero-pad 7x7 -> 8x8, regroup as 4x4 over (dy, dx, c).
        # Output pixel o covers input rows 2o-2..2o+4 (SAME, k=7, s=2) =
        # s2d rows o-1..o+2, so ki = 2*di + dy with di in 0..3.
        wp = jnp.pad(w.astype(self.dtype), ((0, 1), (0, 1), (0, 0), (0, 0)))
        w4 = wp.reshape(4, 2, 4, 2, c, self.features)
        w4 = w4.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                    self.features)
        return lax.conv_general_dilated(
            x2.astype(self.dtype), w4, (1, 1), ((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs.

    ``axis_name`` enables cross-replica BatchNorm inside mapped computations;
    leave None for per-worker statistics (the reference's behavior).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    small_inputs: bool = False  # CIFAR-style stem: 3x3/1, no maxpool
    # Step-level fused running-stats EMA (models/norm.py): the ~104 BN
    # layers' EMAs collapse into one op — the train step must then apply
    # models.ema_batch_stats to the mutable update.  Same math, ~1.4 ms
    # less per-op overhead per v5e step (docs/benchmarks.md).
    fused_ema: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        from horovod_tpu.models import norm as norm_mod

        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        # norm_mod.BatchNorm = BatchStatsNorm aliased so flax auto-names
        # (BatchNorm_0 ...) keep the two paths' trees path-identical.
        norm_cls = norm_mod.BatchNorm if self.fused_ema else nn.BatchNorm
        norm = functools.partial(
            norm_cls, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.axis_name)

        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = SpaceToDepthStem(self.num_filters, dtype=self.dtype,
                                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm, act=nn.relu)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="head")(x.astype(jnp.float32))
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
