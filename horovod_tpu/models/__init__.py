"""Model zoo backing the examples and benchmarks.

The reference ships no model library — its acceptance surface is the
`examples/` scripts (ResNet-50 via `keras.applications`, MNIST convnets,
word2vec; /root/reference/examples/).  Those architectures live here as
first-class flax modules so the examples, the benchmark harness, and the
driver's graft entry all share one TPU-tuned implementation.
"""

from horovod_tpu.models.mnist import MnistCNN  # noqa: F401
from horovod_tpu.models.norm import (  # noqa: F401
    BatchStatsNorm,
    ema_batch_stats,
)
from horovod_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.transformer import (  # noqa: F401
    DecodeContext,
    TransformerLM,
    next_token_loss,
)
from horovod_tpu.models.vgg import VGG11, VGG16, VGG19  # noqa: F401
from horovod_tpu.models.inception import InceptionV3  # noqa: F401
