"""MNIST convnet matching the reference example architectures
(/root/reference/examples/keras_mnist.py:31-41,
/root/reference/examples/pytorch_mnist.py:49-63): two convs, max-pool,
dropout, two dense layers."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
