"""Decoder-only Transformer LM, TPU-tuned, with optional sequence parallelism.

No reference counterpart (zhangzhao156/horovod predates LLM workloads); this
is the long-context flagship the task adds: bfloat16 compute on the MXU,
RoPE positions (no position table to shard), pre-norm blocks, and attention
that is either the fused Pallas :func:`~horovod_tpu.ops.flash_attention`
(single shard) or :func:`~horovod_tpu.ops.ring_attention` when the sequence
dimension is sharded over a mesh axis (``seq_axis=``) — context length then
scales linearly with the ring size.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.utils.jax_compat import axis_size as _axis_size
from horovod_tpu.utils.jax_compat import vma as _aval_vma

from horovod_tpu.ops import (blockwise_attention, flash_attention,
                             ring_attention)


@jax.custom_vjp
def _qkv_project(x, w):
    """Fused qkv projection returning the UNSTACKED (q, k, v) triple.

    Functionally identical to slicing ``einsum('bsd,djhe->jbhse')`` —
    but under plain autodiff those three slices transpose to pad+concat
    of the cotangents into a materialized j-stack (measured ~150 us/step
    of pure copy in the LM profile).  The custom VJP computes dx as the
    sum of three per-slot matmuls and dW by stacking only the (small)
    weight gradients, so no activation-sized stack is ever built."""
    q, k, v = jnp.einsum("bsd,djhe->jbhse", x, w)
    return q, k, v


def _qkv_project_fwd(x, w):
    return _qkv_project(x, w), (x, w)


def _vma(t):
    """Varying-manual-axes of a value under shard_map (empty outside)."""
    return frozenset(_aval_vma(t) or ())


def _qkv_project_bwd(res, cots):
    x, w = res
    dx = sum(jnp.einsum("bhse,dhe->bsd", c, w[:, j])
             for j, c in enumerate(cots))
    dw = jnp.stack([jnp.einsum("bsd,bhse->dhe", x, c) for c in cots],
                   axis=1)  # (d, 3, h, e): params-sized, cheap to stack
    # Under shard_map the cotangents vary over the mapped axes while the
    # primal inputs may be replicated (w always is; x can be, e.g. when
    # only the batch is mapped elsewhere).  A custom_vjp must return
    # cotangents whose varying axes MATCH the primal's — the psum plain
    # autodiff would insert is our job here.
    extra_w = _vma(dw) - _vma(w)
    if extra_w:  # sorted: stable axis order -> stable jaxpr/compile cache
        dw = lax.psum(dw, tuple(sorted(extra_w)))
    extra_x = _vma(dx) - _vma(x)
    if extra_x:
        dx = lax.psum(dx, tuple(sorted(extra_x)))
    return dx, dw


_qkv_project.defvjp(_qkv_project_fwd, _qkv_project_bwd)


def rope(x, positions, base: float = 10000.0, seq_dim: int = -2):
    """Rotary position embedding, ADJACENT-pair formulation: component
    pairs ``(x[2i], x[2i+1])`` rotate by the i-th frequency.  The pairs
    are reached by a free reshape view instead of the classic
    [even half | odd half] split's two big slices + concatenate — XLA
    then fuses the whole rotation into neighbouring ops (measured +6%
    LM step time; docs/benchmarks.md round-3 log).  The two pairings are
    the same function up to a fixed permutation of the q/k projections'
    output axis — :func:`migrate_rope_pairing` converts checkpoints
    trained under the old pairing exactly.

    ``positions``: (seq,) global token positions — global, so
    sequence-sharded shards stay consistent — or (batch, seq) when every
    batch row sits at a different offset (the serving plane's continuous
    decode batch, where slot b's next token lives at its own cache
    length).  ``seq_dim`` names the sequence axis of ``x`` (-2 for
    (b, h, s, d), 1 for (b, s, h, d))."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs
    shape = [1] * x.ndim
    if positions.ndim == 2:  # per-batch-row offsets (decode mode)
        shape[0] = positions.shape[0]
    shape[seq_dim] = x.shape[seq_dim]
    shape[-1] = half
    cos = jnp.cos(angles).reshape(shape)[..., None]
    sin = jnp.sin(angles).reshape(shape)[..., None]
    xp = x.reshape(x.shape[:-1] + (half, 2))
    a, b = xp[..., :1], xp[..., 1:]
    rotated = jnp.concatenate([a * cos - b * sin, a * sin + b * cos],
                              axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


def _rope_half_pairing(x, positions, base: float = 10000.0,
                       seq_dim: int = -2):
    """The pre-round-3 [even half | odd half] pairing — kept as the
    reference the rope-pairing migration test checks against."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]
    shape = [1] * x.ndim
    shape[seq_dim] = x.shape[seq_dim]
    shape[-1] = half
    cos = jnp.cos(angles).reshape(shape)
    sin = jnp.sin(angles).reshape(shape)
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


class DecodeContext(NamedTuple):
    """Per-step context for cached (KV) decode — the serving plane's
    iteration-level hook (docs/inference.md).

    ``k``/``v``: ``(n_layers, batch, heads, ctx_len, head_dim)`` — every
    layer's cached keys/values (post-rope, as the layers wrote them),
    gathered by the caller (the serving engine gathers its block-pool
    pages; a simple driver can pass a contiguous cache).  ``mask``:
    ``(batch, ctx_len)`` bool — which context positions are valid for
    each batch row (rows at different lengths share one padded buffer).
    ``positions``: ``(batch, new_len)`` int32 — the global positions of
    the new tokens per row (= the row's cache length + arange).
    """

    k: Any
    v: Any
    mask: Any
    positions: Any

    def layer(self, i: int):
        return self.k[i], self.v[i], self.mask, self.positions


def _decode_attention(q, k, v, mask, sm_scale):
    """Masked attention for the decode path: ``q`` (b, h, s, hd) against
    ``k``/``v`` (b, h, S, hd) under ``mask`` (b, s, S).  Plain einsum —
    decode steps are a handful of query rows, so a fused kernel would buy
    nothing — with float32 softmax internals regardless of storage dtype.
    Every query row attends at least to itself (the caller's mask always
    admits the within-chunk diagonal), so the softmax is never empty."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    logits = jnp.where(mask[:, None], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


class Attention(nn.Module):
    n_heads: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    use_flash: bool = True
    # Under sequence parallelism: how K/V shards travel the ring —
    # "ppermute" (XLA collective permute), "rdma", or "fused" (rotation
    # DMA inside the flash kernel; ops/ring_flash.py).
    ring_impl: str = "ppermute"
    # Sow each layer's (post-rope) K/V into the "intermediates"
    # collection: the sharded ring-prefill path reads them back to fill
    # the serving plane's KV cache (serving/prefill.py).
    capture_kv: bool = False

    @nn.compact
    def __call__(self, x, decode_ctx=None):
        b, s, d = x.shape
        head_dim = d // self.n_heads
        # One fused qkv projection whose einsum emits q/k/v *head-major*
        # ('jbhse'): XLA folds the seq<->head transpose into the matmul's
        # output layout, so no standalone copy passes appear around the
        # attention kernel (they measured ~7% of the LM step at batch 16
        # on v5e).  The inverse transpose folds into the output
        # projection's einsum the same way.  Per-matrix fan-in init
        # matches separate q/k/v Dense layers (fan_in = d).
        w_qkv = self.param(
            "qkv_kernel",
            nn.initializers.lecun_normal(in_axis=0, out_axis=(1, 2, 3)),
            (d, 3, self.n_heads, head_dim), jnp.float32)
        q, k, v = _qkv_project(x.astype(self.dtype),
                               w_qkv.astype(self.dtype))
        # (b, heads, seq, head_dim) each; custom VJP avoids the
        # activation-sized cotangent stack the sliced einsum would build.

        new_kv = None
        if decode_ctx is not None:
            k_ctx, v_ctx, ctx_mask, positions = decode_ctx
            q, k = rope(q, positions), rope(k, positions)
            ctx_len = k_ctx.shape[-2]
            # Context keys all precede the new chunk; within the chunk
            # positions are consecutive, so causality is a lower triangle.
            mask = jnp.concatenate([
                jnp.broadcast_to(ctx_mask[:, None, :], (b, s, ctx_len)),
                jnp.broadcast_to(jnp.tril(jnp.ones((s, s), bool))[None],
                                 (b, s, s)),
            ], axis=-1)
            keys = jnp.concatenate([k_ctx.astype(k.dtype), k], axis=-2)
            vals = jnp.concatenate([v_ctx.astype(v.dtype), v], axis=-2)
            out = _decode_attention(q, keys, vals, mask, head_dim ** -0.5)
            new_kv = (k, v)
        elif self.seq_axis is not None:
            offset = lax.axis_index(self.seq_axis) * s
            positions = offset + jnp.arange(s)
            q, k = rope(q, positions), rope(k, positions)
            if self.capture_kv:
                self.sow("intermediates", "kv", (k, v))
            out = ring_attention(q, k, v, axis_name=self.seq_axis,
                                 causal=True, rotate_impl=self.ring_impl)
        else:
            positions = jnp.arange(s)
            q, k = rope(q, positions), rope(k, positions)
            if self.capture_kv:
                self.sow("intermediates", "kv", (k, v))
            out = flash_attention(q, k, v, causal=True) if self.use_flash \
                else blockwise_attention(q, k, v, causal=True)
        w_o = self.param(
            "o_kernel",
            nn.initializers.lecun_normal(in_axis=(0, 1), out_axis=2),
            (self.n_heads, head_dim, d), jnp.float32)
        proj = jnp.einsum("bhse,hed->bsd", out, w_o.astype(self.dtype))
        return proj if new_kv is None else (proj, new_kv)


class Block(nn.Module):
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    use_flash: bool = True
    ring_impl: str = "ppermute"
    capture_kv: bool = False

    @nn.compact
    def __call__(self, x, decode_ctx=None):
        h = nn.RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        attn = Attention(self.n_heads, self.dtype, self.seq_axis,
                         self.use_flash, self.ring_impl, self.capture_kv,
                         name="attn")
        new_kv = None
        if decode_ctx is None:
            x = x + attn(h)
        else:
            a, new_kv = attn(h, decode_ctx)
            x = x + a
        h = nn.RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        h = nn.Dense(self.d_ff, use_bias=False, dtype=self.dtype,
                     name="up")(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], use_bias=False, dtype=self.dtype,
                     name="down")(h)
        x = x + h
        return x if new_kv is None else (x, new_kv)


class TransformerLM(nn.Module):
    """Causal LM over token ids ``(batch, seq[, sharded over seq_axis])``."""

    vocab_size: int
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: Optional[int] = None
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None  # mapped mesh axis of sequence shards
    use_flash: bool = True
    ring_impl: str = "ppermute"  # K/V rotation under sequence parallelism
    capture_kv: bool = False  # sow per-layer K/V (ring prefill capture)
    # Storage dtype of the returned logits.  The MXU accumulation is
    # always float32; bfloat16 STORAGE halves the dominant HBM stream of
    # the LM step (the (batch, seq, vocab) logits tensor and its
    # cotangent round-trip HBM several times between the head matmul,
    # the softmax-CE, and the two backward matmuls — and the backward
    # matmuls consume bf16 operands anyway).  next_token_loss upcasts to
    # f32 internally, so the only precision loss is one bf16 rounding of
    # each logit (~0.4% relative); measured +9% tokens/s on v5e
    # (docs/benchmarks.md round-4 log).
    logits_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, targets=None, decode_ctx=None):
        if targets is not None and self.seq_axis is not None:
            raise ValueError(
                "targets= (fused head+loss) is unsupported under sequence "
                "parallelism: it has no axis_name-aware normalization; "
                "compute logits and use next_token_loss(..., axis_name=...) "
                "instead.")
        if decode_ctx is not None and (targets is not None
                                       or self.seq_axis is not None):
            raise ValueError(
                "decode_ctx= (cached KV decode) composes with neither "
                "targets= nor sequence parallelism: decode is an "
                "inference-only, single-shard path (docs/inference.md).")
        d_ff = self.d_ff or 4 * self.d_model
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.dtype, name="embed")(tokens)
        new_ks, new_vs = [], []
        for i in range(self.n_layers):
            block = Block(self.n_heads, d_ff, self.dtype, self.seq_axis,
                          self.use_flash, self.ring_impl, self.capture_kv,
                          name=f"layer_{i}")
            if decode_ctx is None:
                x = block(x)
            else:
                x, (k_new, v_new) = block(x, decode_ctx.layer(i))
                new_ks.append(k_new)
                new_vs.append(v_new)
        x = nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)
        # Logits accumulate in float32 for a numerically stable softmax,
        # but the matmul runs in bfloat16 on the MXU: an f32xf32 matmul
        # costs multiple MXU passes, and the lm_head is ~1/3 of the model's
        # FLOPs at vocab 32k.
        w = self.param(
            "lm_head_kernel",
            nn.initializers.variance_scaling(1.0, "fan_in",
                                             "truncated_normal"),
            (self.d_model, self.vocab_size), jnp.float32)
        if targets is not None:
            # Fused head+loss: see fused_next_token_loss.
            return fused_next_token_loss(x, w, targets, dtype=self.dtype)
        logits = jnp.einsum("bsd,dv->bsv", x.astype(self.dtype),
                            w.astype(self.dtype),
                            preferred_element_type=jnp.float32).astype(
                                self.logits_dtype)
        if decode_ctx is not None:
            # (n_layers, batch, heads, new_len, head_dim) each: the new
            # chunk's K/V for the caller to persist into its cache.
            return logits, (jnp.stack(new_ks), jnp.stack(new_vs))
        return logits


# Param-layout version stamped into checkpoint wrappers by the migrators
# (and checked by check_layout): 2 = fused qkv/o/lm_head kernels with the
# legacy [even half | odd half] rope pairing, 3 = round-3 adjacent-pair
# rope.  An unversioned tree that skips migrate_rope_pairing still loads
# and runs — computing a silently different function — so loaders should
# gate on check_layout rather than on users reading docstrings.
LAYOUT_VERSION = 3


def stamp_layout(variables, version: int = LAYOUT_VERSION):
    """Return ``variables`` (a ``{"params": ...}``-style checkpoint
    wrapper) with a ``layout`` collection recording the param-layout
    version.  flax ``Module.apply`` ignores unused collections, so the
    stamp rides along transparently; serializers persist it."""
    if "params" not in variables:
        raise ValueError("stamp_layout expects a {'params': ...} wrapper "
                         "(the stamp must not live inside the param tree, "
                         "where optimizers would treat it as a weight)")
    return {**variables, "layout": {"version": version}}


def check_layout(variables, strict: bool = False):
    """Gate a loaded checkpoint wrapper on its layout stamp.

    Unversioned trees (no ``layout`` collection) predate round 3 and run
    under the adjacent-pair rope as a silently different function —
    warn (or raise with ``strict=True``) and point at the migrators.
    Returns ``variables`` unchanged so this can wrap a load expression.
    """
    version = variables.get("layout", {}).get("version")
    version = None if version is None else int(version)
    if version == LAYOUT_VERSION:
        return variables
    msg = (
        "TransformerLM checkpoint has no current layout stamp "
        f"(found version {version}, current {LAYOUT_VERSION}): trees "
        "saved before round 3 use the legacy rope pairing and will "
        "compute a DIFFERENT function if applied unmigrated.  Run "
        "models.transformer.migrate_params(...) (structure) and "
        "migrate_rope_pairing(...) (rope) once; both stamp the result."
    )
    if strict:
        raise ValueError(msg)
    import warnings

    warnings.warn(msg)
    return variables


def migrate_params(params, n_heads: int):
    """Convert a legacy TransformerLM param tree to the fused layout.

    The fused projections renamed/reshaped parameters relative to earlier
    revisions of this model (``qkv_kernel``/``o_kernel``/``lm_head_kernel``
    replaced per-matrix ``q``/``k``/``v``/``o``/``lm_head`` Dense kernels,
    and an interim revision's single ``qkv`` Dense).  This converter makes
    old checkpoints loadable — the analogue of how ``SpaceToDepthStem``
    kept the (7,7,C,F) conv param so ResNet checkpoints stayed loadable.

    Accepts either a bare param dict or a ``{"params": ...}`` wrapper; the
    layout is detected per-module, so already-migrated trees pass through
    unchanged.  ``n_heads`` must match the model's head count (the fused
    kernels are stored head-major).

    Round-1/2 checkpoints were also trained under the old rope pairing:
    after this structural conversion, apply
    :func:`migrate_rope_pairing` once to reproduce their function under
    the round-3 adjacent-pair rope exactly.
    """
    if "params" in params and isinstance(params["params"], dict):
        # Structure migrated but rope still legacy: version 2 (the rope
        # migrator upgrades the stamp to LAYOUT_VERSION).
        return stamp_layout(
            {**params, "params": migrate_params(params["params"], n_heads)},
            version=2)

    def fuse_attention(attn):
        if "qkv" in attn:  # interim fused (d, 3d) Dense
            w = attn["qkv"]["kernel"]
            d = w.shape[0]
            qkv = w.reshape(d, 3, n_heads, d // n_heads)
        elif all(k in attn for k in ("q", "k", "v")):  # per-matrix Dense
            ws = [attn[k]["kernel"] for k in ("q", "k", "v")]
            d = ws[0].shape[0]
            qkv = jnp.stack(ws, axis=1).reshape(d, 3, n_heads,
                                                d // n_heads)
        else:
            return attn  # already fused
        # Old o Dense consumed the (h, hd)-flattened attention output, so
        # its input dim unflattens head-major.
        wo = attn["o"]["kernel"]
        o = wo.reshape(n_heads, wo.shape[0] // n_heads, wo.shape[1])
        rest = {key: val for key, val in attn.items()
                if key not in ("q", "k", "v", "qkv", "o")}
        return {**rest, "qkv_kernel": qkv, "o_kernel": o}

    out = {}
    for key, val in params.items():
        if key == "lm_head" and isinstance(val, dict) and "kernel" in val:
            out["lm_head_kernel"] = val["kernel"]
        elif isinstance(val, dict) and ("qkv" in val or "q" in val):
            out[key] = fuse_attention(val)
        elif isinstance(val, dict):
            out[key] = migrate_params(val, n_heads)
        else:
            out[key] = val
    return out


def migrate_rope_pairing(params, n_heads: int):
    """Convert a checkpoint trained under the pre-round-3 rope pairing
    ([even half | odd half]) to the adjacent-pair formulation, EXACTLY:
    the pairings differ by a fixed permutation P of the q/k projections'
    head_dim axis (``new_rope(P x) = P old_rope(x)`` and attention scores
    are invariant under a shared q/k permutation), so permuting
    ``qkv_kernel``'s q and k slots reproduces the old model's function to
    the bit.  v and the output projection are untouched (no rope).
    Accepts a bare param dict or a ``{"params": ...}`` wrapper.  Apply
    ONCE per checkpoint (it is its own inverse only for head_dim == 2).
    """
    if "params" in params and isinstance(params["params"], dict):
        return stamp_layout(
            {**params,
             "params": migrate_rope_pairing(params["params"], n_heads)})

    converted = [0]

    def permute(tree):
        out = {}
        for key, val in tree.items():
            if isinstance(val, dict) and "qkv_kernel" in val:
                w = val["qkv_kernel"]  # (d, 3, heads, head_dim)
                if w.shape[-2] != n_heads:
                    raise ValueError(
                        f"qkv_kernel has {w.shape[-2]} heads, caller said "
                        f"n_heads={n_heads}")
                head_dim = w.shape[-1]
                half = head_dim // 2
                # new output 2i <- old i ; 2i+1 <- old i+half.
                idx = jnp.stack([jnp.arange(half),
                                 jnp.arange(half) + half],
                                axis=1).reshape(-1)
                qk = w[:, :2, :, :][..., idx]
                out[key] = {**val,
                            "qkv_kernel": jnp.concatenate(
                                [qk, w[:, 2:, :, :]], axis=1)}
                converted[0] += 1
            elif isinstance(val, dict):
                out[key] = permute(val)
            else:
                out[key] = val
        return out

    out = permute(params)
    if not converted[0]:
        raise ValueError(
            "no qkv_kernel found: this tree is still in a legacy layout "
            "— run migrate_params(...) first, then migrate_rope_pairing")
    return out


def fused_next_token_loss(hidden, w, targets, dtype=jnp.bfloat16,
                          n_chunks: int = 8):
    """Mean cross-entropy computed head-chunk by head-chunk.

    The full-logits path materializes a ``(batch, seq, vocab)`` float32
    tensor (1 GiB at batch 8 / seq 1024 / vocab 32k) that HBM round-trips
    several times (softmax, correct-class gather, d-logits).  Here the
    token dimension is split into chunks inside a rematerialized
    ``lax.scan``: each chunk's logits live only transiently, the forward
    keeps a scalar, and the backward recomputes one chunk's logits at a
    time — O(tokens/n_chunks * vocab) peak memory, same math.  (The model
    invokes this when ``targets`` is passed to ``__call__``.)

    This trades one extra head matmul (the remat recompute) for the logits
    round-trips: measured on v5e at vocab 32k / batch 8 it is ~8% *slower*
    than the full-logits path, so use it when the logits tensor does not
    fit comfortably (long sequences, big vocab, large batch), not as a
    throughput knob.
    """
    B, S, D = hidden.shape
    tokens = B * S
    if tokens % n_chunks:
        n_chunks = 1
    xc = hidden.reshape(n_chunks, tokens // n_chunks, D)
    tc = targets.reshape(n_chunks, tokens // n_chunks)
    wb = w.astype(dtype)

    def chunk(total, xt):
        x, t = xt
        logits = jnp.einsum("md,dv->mv", x.astype(dtype), wb,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        correct = jnp.take_along_axis(logits, t[:, None], axis=-1)[:, 0]
        return total + (lse - correct).sum(), None

    total, _ = lax.scan(jax.checkpoint(chunk),
                        jnp.zeros((), jnp.float32), (xc, tc))
    return total / tokens


def next_token_loss(logits, targets, mask=None, axis_name=None):
    """Mean cross-entropy of ``logits`` against aligned ``targets``.

    Shift once globally before sharding (``inputs = tokens[:, :-1]``,
    ``targets = tokens[:, 1:]``) so sequence-sharded shards stay aligned
    across shard boundaries.  Unmasked, per-shard means `pmean` exactly
    (equal shard sizes).  With a ``mask`` (padding weighted out), pass the
    mapped ``axis_name`` (or tuple) too: shards may hold different numbers
    of valid tokens, so the local sum is normalized by the *global mean*
    token count per shard — the subsequent `pmean` then reproduces the
    exact global weighted mean instead of over-weighting padded shards.
    """
    import optax

    # f32 internals regardless of logits storage dtype (bf16-stored
    # logits ride a convert that XLA fuses into the reductions).
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets)
    if mask is None:
        return loss.mean()
    mask = mask.astype(loss.dtype)
    count = mask.sum()
    if axis_name is not None:
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        n_shards = 1
        for a in axes:
            n_shards *= _axis_size(a)
        count = lax.psum(count, axes) / n_shards
    return (loss * mask).sum() / jnp.maximum(count, 1.0)
