"""Decoder-only Transformer LM, TPU-tuned, with optional sequence parallelism.

No reference counterpart (zhangzhao156/horovod predates LLM workloads); this
is the long-context flagship the task adds: bfloat16 compute on the MXU,
RoPE positions (no position table to shard), pre-norm blocks, and attention
that is either the fused Pallas :func:`~horovod_tpu.ops.flash_attention`
(single shard) or :func:`~horovod_tpu.ops.ring_attention` when the sequence
dimension is sharded over a mesh axis (``seq_axis=``) — context length then
scales linearly with the ring size.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops import (blockwise_attention, flash_attention,
                             ring_attention)


def rope(x, positions, base: float = 10000.0):
    """Rotary position embedding over the last dim (pairs interleaved as
    [even half | odd half]).  ``positions``: (seq,) global token positions —
    global, so sequence-sharded shards stay consistent."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]
    cos = jnp.cos(angles)[None, None]  # (1, 1, seq, half)
    sin = jnp.sin(angles)[None, None]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


class Attention(nn.Module):
    n_heads: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    use_flash: bool = True

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        head_dim = d // self.n_heads
        dense = lambda name: nn.Dense(  # noqa: E731
            d, use_bias=False, dtype=self.dtype, name=name)
        q, k, v = (dense(n)(x) for n in ("q", "k", "v"))
        # (b, heads, seq, head_dim)
        split = lambda t: t.reshape(  # noqa: E731
            b, s, self.n_heads, head_dim).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)

        if self.seq_axis is not None:
            offset = lax.axis_index(self.seq_axis) * s
            positions = offset + jnp.arange(s)
            q, k = rope(q, positions), rope(k, positions)
            out = ring_attention(q, k, v, axis_name=self.seq_axis,
                                 causal=True)
        else:
            positions = jnp.arange(s)
            q, k = rope(q, positions), rope(k, positions)
            out = flash_attention(q, k, v, causal=True) if self.use_flash \
                else blockwise_attention(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, d)
        return nn.Dense(d, use_bias=False, dtype=self.dtype, name="o")(out)


class Block(nn.Module):
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    use_flash: bool = True

    @nn.compact
    def __call__(self, x):
        h = nn.RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        x = x + Attention(self.n_heads, self.dtype, self.seq_axis,
                          self.use_flash, name="attn")(h)
        h = nn.RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        h = nn.Dense(self.d_ff, use_bias=False, dtype=self.dtype,
                     name="up")(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], use_bias=False, dtype=self.dtype,
                     name="down")(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM over token ids ``(batch, seq[, sharded over seq_axis])``."""

    vocab_size: int
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: Optional[int] = None
    dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None  # mapped mesh axis of sequence shards
    use_flash: bool = True

    @nn.compact
    def __call__(self, tokens):
        d_ff = self.d_ff or 4 * self.d_model
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.dtype, name="embed")(tokens)
        for i in range(self.n_layers):
            x = Block(self.n_heads, d_ff, self.dtype, self.seq_axis,
                      self.use_flash, name=f"layer_{i}")(x)
        x = nn.RMSNorm(dtype=self.dtype, name="final_norm")(x)
        # Logits in float32 for a numerically stable softmax/loss.
        return nn.Dense(self.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x.astype(jnp.float32))


def next_token_loss(logits, targets, mask=None, axis_name=None):
    """Mean cross-entropy of ``logits`` against aligned ``targets``.

    Shift once globally before sharding (``inputs = tokens[:, :-1]``,
    ``targets = tokens[:, 1:]``) so sequence-sharded shards stay aligned
    across shard boundaries.  Unmasked, per-shard means `pmean` exactly
    (equal shard sizes).  With a ``mask`` (padding weighted out), pass the
    mapped ``axis_name`` (or tuple) too: shards may hold different numbers
    of valid tokens, so the local sum is normalized by the *global mean*
    token count per shard — the subsequent `pmean` then reproduces the
    exact global weighted mean instead of over-weighting padded shards.
    """
    import optax

    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is None:
        return loss.mean()
    mask = mask.astype(loss.dtype)
    count = mask.sum()
    if axis_name is not None:
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        n_shards = 1
        for a in axes:
            n_shards *= lax.axis_size(a)
        count = lax.psum(count, axes) / n_shards
    return (loss * mask).sum() / jnp.maximum(count, 1.0)
