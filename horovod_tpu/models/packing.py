"""TreePacker: carry a pytree's many tiny leaves as ONE flat buffer.

Why: a ResNet-101 train state holds ~420 tiny 1-D float32 tensors (104
BatchNorm layers x scale/bias/mean/var, plus their optimizer-momentum
mirrors).  Each is a separate XLA buffer, and every one pays a fixed-cost
(~40 us on v5e) memory-space-assignment copy per executed step — measured
11% of the whole ResNet-101 step (docs/benchmarks.md, round-3 profile).
The reference's analogue of this problem class is its fusion buffer for
many tiny gradient tensors (/root/reference/horovod/common/operations.cc,
tensor-fusion); here the fix is at the train-state level: pack the tiny
leaves into one vector OUTSIDE the step, and unpack INSIDE the jitted step
with static `jnp.split` — whose transpose is a single `concatenate`, so
the gradient flows back into one packed cotangent buffer too.  2 buffers
(vector + its momentum) replace ~400.

Numerics are untouched: unpacking reproduces the exact leaf values (same
bytes, same dtypes); residual drift vs an unpacked step is only XLA
choosing different fusions for the two graphs, bounded float32-tight by
tests/test_models.py::test_packed_train_step_bit_identical.

Usage::

    packer = TreePacker(params)              # layout from an example tree
    packed = packer.pack(params)             # {"big": (...), "small": vec}
    tx_state = tx.init(packed)               # optax mirrors the packing

    @jax.jit
    def step(packed, ...):
        params = packer.unpack(packed)       # split + reshapes, fuses away
        ...
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _default_small(leaf) -> bool:
    """Tiny-leaf predicate: 1-D float32 tensors (BN scale/bias/mean/var,
    dense biases) are the many-tiny-buffers problem; kernels stay
    unpacked.  Restricted to float32 so packing is value-exact — a cast
    through the packed dtype would silently round int/uint leaves (PRNG
    keys, step counters) and float64 leaves."""
    return np.ndim(leaf) <= 1 and jnp.asarray(leaf).dtype == jnp.float32


class TreePacker:
    """Reversible (tree) <-> ({"big": tuple, "small": vector}) transform.

    The layout (treedef, which leaves are small, their shapes/dtypes and
    split offsets) is computed host-side once from an example tree; both
    :meth:`pack` and :meth:`unpack` are then pure jnp functions usable
    inside or outside jit.  Only leaves whose dtype already equals the
    packed dtype are packed (enforced on top of ``small``): a cast
    through the packed dtype would silently corrupt int/uint/float64
    leaves, so those always stay in the ``big`` partition.
    """

    def __init__(self, example_tree, small: Callable = _default_small,
                 dtype=jnp.float32):
        leaves, self._treedef = jax.tree_util.tree_flatten(example_tree)
        self._is_small = [
            bool(small(l)) and jnp.asarray(l).dtype == jnp.dtype(dtype)
            for l in leaves
        ]
        self._shapes = [np.shape(l) for l in leaves]
        self._dtypes = [jnp.asarray(l).dtype for l in leaves]
        self._dtype = dtype
        sizes = [int(np.prod(s)) if f else 0
                 for s, f in zip(self._shapes, self._is_small)]
        self._bounds = list(np.cumsum([s for s, f in
                                       zip(sizes, self._is_small) if f])[:-1])
        self.packed_size = sum(sizes)
        if not any(self._is_small):
            raise ValueError("no leaves matched the small() predicate; "
                             "packing would be an identity with overhead")

    def pack(self, tree):
        """tree -> {"big": tuple(big leaves), "small": 1-D vector}."""
        leaves = self._treedef.flatten_up_to(tree)
        small = [jnp.ravel(l) for l, f in zip(leaves, self._is_small) if f]
        big = tuple(l for l, f in zip(leaves, self._is_small) if not f)
        return {"big": big, "small": jnp.concatenate(small)}

    def unpack(self, packed):
        """Inverse of :meth:`pack`; inside jit the splits/reshapes fuse
        into the consumers and the VJP is one concatenate."""
        pieces = jnp.split(packed["small"], self._bounds)
        big_it = iter(packed["big"])
        small_it = iter(pieces)
        leaves = [
            (next(small_it).reshape(shape).astype(dt) if f
             else next(big_it))
            for f, shape, dt in zip(self._is_small, self._shapes,
                                    self._dtypes)
        ]
        return self._treedef.unflatten(leaves)
