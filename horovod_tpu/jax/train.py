"""Data-parallel training-step builder: the five-line Horovod recipe, compiled.

Also home of the checkpoint-resume glue for job-level restart
(docs/fault-tolerance.md): :func:`save_checkpoint` /
:func:`load_latest_checkpoint` give a ``hvdrun --max-restarts`` job a
durable step counter + pytree snapshot, so a mid-run rank crash costs the
steps since the last checkpoint instead of the whole run (the Elastic
Horovod / TorchElastic contract).  Under ``hvdrun --min-np`` even that
cost disappears for in-budget failures: wrap the loop in
``hvd.run_elastic`` with an ``hvd.ElasticState(params=..., opt_state=...,
step=...)`` — pytree leaves broadcast fine — and survivors shrink and
continue in place, with the checkpoint path as the below-``--min-np``
fallback (docs/fault-tolerance.md#elastic-membership).

The reference's usage recipe (/root/reference/README.md:80-105) — scale LR by
size, wrap the optimizer, broadcast initial state — becomes one call here:
``build_train_step`` returns a jitted SPMD step in which each mesh shard
computes gradients on its slice of the batch and `DistributedOptimizer`'s
per-leaf `psum` averages them over ICI, overlapped with the backward pass by
XLA (the compiled equivalent of the reference's hook-driven
allreduce-during-backprop, /root/reference/horovod/torch/__init__.py:64-89).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore  # noqa: E501


def shard_map(fn, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: the replication-check kwarg was
    renamed check_rep -> check_vma; translate for the min-supported jax
    (CI min-versions leg)."""
    import inspect

    kw = ("check_vma" if "check_vma"
          in inspect.signature(_shard_map).parameters else "check_rep")
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{kw: check_vma})

from horovod_tpu.common import metrics as _metrics
from horovod_tpu.jax import DistributedOptimizer

# ---------------------------------------------------------------------------
# Checkpoint-resume glue (job-level restart, docs/fault-tolerance.md).
# ---------------------------------------------------------------------------

_CKPT_PREFIX = "ckpt-"
_CKPT_SUFFIX = ".pkl"


def save_checkpoint(directory: str, step: int, tree) -> str:
    """Write ``tree`` (any picklable pytree — params, opt_state, rng, ...)
    as ``ckpt-<step>.pkl`` under ``directory``; returns the path.  Atomic
    (write + rename), so a rank crash mid-save can never leave a torn
    checkpoint for the restarted job to resume from.  Call on ONE rank
    (conventionally 0); the restart path re-replicates via broadcast."""
    import os
    import pickle
    import tempfile

    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{_CKPT_PREFIX}{step:08d}{_CKPT_SUFFIX}")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            # device_get: materialize device arrays as host numpy so the
            # pickle is portable across restarts (and device topologies).
            pickle.dump({"step": int(step),
                         "tree": jax.device_get(tree)}, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    """Path of the highest-step ``ckpt-*.pkl`` in ``directory``; None when
    there is none (first run, or checkpointing disabled)."""
    import os

    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for name in names:
        if name.startswith(_CKPT_PREFIX) and name.endswith(_CKPT_SUFFIX):
            try:
                steps.append(
                    (int(name[len(_CKPT_PREFIX):-len(_CKPT_SUFFIX)]), name))
            except ValueError:
                continue
    if not steps:
        return None
    return os.path.join(directory, max(steps)[1])


def load_latest_checkpoint(directory: str):
    """``(step, tree)`` from the newest checkpoint in ``directory``, or
    ``(0, None)`` when none exists — so resume code can be unconditional:
    ``step, state = load_latest_checkpoint(d); state = state or init()``."""
    import pickle

    path = latest_checkpoint(directory)
    if path is None:
        return 0, None
    with open(path, "rb") as f:
        payload = pickle.load(f)
    return int(payload["step"]), payload["tree"]


class _TimedStep:
    """Callable proxy over the jitted step that feeds the ``step_sec``
    histogram of the metrics registry (docs/metrics.md) — per-epoch step
    summaries for free wherever ``build_train_step`` is used — and, when a
    timeline is active (docs/timeline.md), wraps each call in a
    ``jax.train_step`` span on this rank's trace.  The measured interval
    is the on-host dispatch of one step call (jax dispatch is async);
    training loops that fetch the loss each step see true step time.
    Every jit attribute (``lower``, ``trace``, ...) delegates to the
    wrapped function."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, *args, **kwargs):
        from horovod_tpu import common as _common

        tl = _common.timeline_enabled()
        mx = _metrics.registry.enabled
        if not tl and not mx:
            return self._fn(*args, **kwargs)
        if tl:
            _common._trace_begin("jax.train_step", "TRAIN_STEP")
        t0 = time.perf_counter()
        try:
            out = self._fn(*args, **kwargs)
        finally:
            if tl:
                _common._trace_end("jax.train_step")
        if mx:
            _metrics.registry.observe("step_sec", time.perf_counter() - t0)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def build_train_step(loss_fn: Callable, optimizer, mesh: Mesh,
                     axis_name: Optional[str] = None,
                     has_aux: bool = False,
                     batch_spec=None,
                     donate: bool = True,
                     check_vma: bool = True):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss[, aux])``.

    ``loss_fn(params, batch)`` computes the *local shard's* mean loss (and
    optionally an aux pytree with ``has_aux=True`` — e.g. updated batch-norm
    statistics, which the step cross-replica-averages like the loss).
    ``optimizer`` is a plain `optax.GradientTransformation`; it is wrapped in
    `DistributedOptimizer` internally.  Batches enter sharded along
    ``axis_name`` (see `horovod_tpu.parallel.shard_batch`); params/opt_state
    are replicated.  ``batch_spec`` (default ``P(axis_name)`` over every
    leaf) may be a pytree prefix of PartitionSpecs for batches mixing sharded
    data with replicated state (e.g. batch-norm statistics: ``P()``).
    """
    axis_name = axis_name or mesh.axis_names[0]
    if batch_spec is None:
        batch_spec = P(axis_name)
    # `axis_name` may be one mesh axis or a tuple (e.g. ("dp", "sp")):
    # gradient averaging and loss reporting reduce over all of them.
    import optax

    dist_opt = DistributedOptimizer(optimizer, axis_name=axis_name)

    def shard_step(params, opt_state, batch):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = dist_opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, axis_name)
        if has_aux:
            aux = jax.tree.map(lambda a: lax.pmean(a, axis_name), aux)
            return params, opt_state, loss, aux
        return params, opt_state, loss

    n_out = 4 if has_aux else 3
    # check_vma=False is needed for interpret-mode Pallas collectives on
    # CPU test meshes (rdma / fused ring rotation): the interpreter does
    # not propagate the varying-manual-axes annotation through its
    # internals.  Compiled TPU kernels don't need it.
    mapped = shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P(), batch_spec),
        out_specs=(P(),) * n_out,
        check_vma=check_vma)
    return _TimedStep(jax.jit(mapped, donate_argnums=(0, 1)
                              if donate else ()))
