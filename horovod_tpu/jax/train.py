"""Data-parallel training-step builder: the five-line Horovod recipe, compiled.

Also home of the checkpoint-resume glue for job-level restart
(docs/fault-tolerance.md): :func:`save_checkpoint` /
:func:`load_latest_checkpoint` give a ``hvdrun --max-restarts`` job a
durable step counter + pytree snapshot, so a mid-run rank crash costs the
steps since the last checkpoint instead of the whole run (the Elastic
Horovod / TorchElastic contract).  Under ``hvdrun --min-np`` even that
cost disappears for in-budget failures: wrap the loop in
``hvd.run_elastic`` with an ``hvd.ElasticState(params=..., opt_state=...,
step=...)`` — pytree leaves broadcast fine — and survivors shrink and
continue in place, with the checkpoint path as the below-``--min-np``
fallback (docs/fault-tolerance.md#elastic-membership).

The reference's usage recipe (/root/reference/README.md:80-105) — scale LR by
size, wrap the optimizer, broadcast initial state — becomes one call here:
``build_train_step`` returns a jitted SPMD step in which each mesh shard
computes gradients on its slice of the batch and `DistributedOptimizer`'s
per-leaf `psum` averages them over ICI, overlapped with the backward pass by
XLA (the compiled equivalent of the reference's hook-driven
allreduce-during-backprop, /root/reference/horovod/torch/__init__.py:64-89).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore  # noqa: E501


def shard_map(fn, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: the replication-check kwarg was
    renamed check_rep -> check_vma; translate for the min-supported jax
    (CI min-versions leg)."""
    import inspect

    kw = ("check_vma" if "check_vma"
          in inspect.signature(_shard_map).parameters else "check_rep")
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{kw: check_vma})

from horovod_tpu.common import metrics as _metrics
from horovod_tpu.jax import DistributedOptimizer

# ---------------------------------------------------------------------------
# Checkpoint-resume glue (job-level restart, docs/fault-tolerance.md).
# ---------------------------------------------------------------------------

_CKPT_PREFIX = "ckpt-"
_CKPT_SUFFIX = ".pkl"


def _ckpt_barrier(name: str) -> None:
    """Named-collective barrier for the sharded commit protocol (an
    allreduce of one int — every rank must pass it before the manifest
    commits, and again before save returns)."""
    import numpy as np

    from horovod_tpu import common as _common

    _common.allreduce(np.ones(1, np.int32), average=False, name=name)


def save_checkpoint(directory: str, step: int, tree,
                    sharded: bool = False,
                    keep: Optional[int] = None) -> str:
    """Write ``tree`` (any picklable pytree — params, opt_state, rng, ...)
    as a checkpoint under ``directory``; returns the committed path.

    **Legacy mode** (``sharded=False``, the default): one atomic
    ``ckpt-<step>.pkl`` — call on ONE rank (conventionally 0); the
    restart path re-replicates via broadcast.

    **Sharded mode** (``sharded=True``;
    docs/fault-tolerance.md#state-plane): call on EVERY rank — each
    writes only the 1/size shard of leaves it owns
    (``ckpt-<step>/rank-N.pkl``), a named-collective barrier confirms all
    shards landed, and rank 0 commits ``manifest.json`` atomically —
    checkpoint wall time drops from O(model) on one rank's disk/NIC to
    O(model/size) per rank, and a directory without a committed manifest
    is torn by definition (invisible to :func:`latest_checkpoint`).
    Sharded is a deliberate API opt-in, NOT an env knob: the two modes
    have different call contracts (one rank vs every rank), and an
    environment flip of a rank-0-only call site would park rank 0 in a
    barrier nobody else enqueues.

    Retention (both modes): ``keep`` (default ``HVD_TPU_CKPT_KEEP``;
    unset = unbounded) prunes the oldest committed checkpoints AFTER the
    new one commits — never the one being written, never a torn
    directory some writer still owns.
    """
    import os

    from horovod_tpu import common as _common
    from horovod_tpu.common import metrics as _metrics
    from horovod_tpu.state import checkpoint as _ckpt

    if keep is None:
        keep = _ckpt.retention_keep()
    os.makedirs(directory, exist_ok=True)
    if sharded:
        if _common.is_initialized():
            rank, size = _common.rank(), _common.size()
            barrier = _ckpt_barrier if size > 1 else None
        else:
            rank, size, barrier = 0, 1, None
        path = _ckpt.save_sharded(directory, step, tree, rank, size,
                                  barrier=barrier)
        if rank == 0:
            _ckpt.prune_checkpoints(directory, keep, protect_step=step)
        return path
    import pickle

    path = os.path.join(directory, f"{_CKPT_PREFIX}{step:08d}{_CKPT_SUFFIX}")
    # device_get: materialize device arrays as host numpy so the pickle
    # is portable across restarts (and device topologies).
    _ckpt._atomic_write(path, lambda f: pickle.dump(
        {"step": int(step), "tree": jax.device_get(tree)}, f))
    _metrics.registry.record_state_ckpt("legacy_saves",
                                        nbytes=os.path.getsize(path))
    _ckpt.prune_checkpoints(directory, keep, protect_step=step)
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    """Path of the highest-step committed checkpoint in ``directory`` —
    a legacy ``ckpt-*.pkl`` file or a sharded ``ckpt-*/`` directory with
    a committed manifest (torn sharded directories are invisible); None
    when there is none (first run, or checkpointing disabled)."""
    from horovod_tpu.state import checkpoint as _ckpt

    entries = _ckpt.scan_checkpoints(directory)
    return entries[-1][1] if entries else None


def load_checkpoint(path: str, collective: bool = True):
    """``(step, tree)`` from one checkpoint ``path`` (legacy pickle file
    or sharded directory).  For sharded checkpoints ``collective=True``
    reads only this rank's shard and gathers the rest by broadcast when
    the engine is up at the saved world size (every rank must call);
    ``collective=False`` assembles every shard locally (root-only resume
    glue, tools, mismatched world sizes)."""
    import os
    import pickle

    from horovod_tpu.common import metrics as _metrics
    from horovod_tpu.state import checkpoint as _ckpt

    if os.path.isdir(path):
        return _ckpt.load_sharded(path, collective=collective)
    with open(path, "rb") as f:
        payload = pickle.load(f)
    _metrics.registry.record_state_ckpt("loads")
    return int(payload["step"]), payload["tree"]


def load_latest_checkpoint(directory: str, collective: bool = True):
    """``(step, tree)`` from the newest committed checkpoint in
    ``directory`` — legacy and sharded formats alike — or ``(0, None)``
    when none exists, so resume code can be unconditional:
    ``step, state = load_latest_checkpoint(d); state = state or init()``."""
    path = latest_checkpoint(directory)
    if path is None:
        return 0, None
    return load_checkpoint(path, collective=collective)


class _TimedStep:
    """Callable proxy over the jitted step that feeds the ``step_sec``
    histogram of the metrics registry (docs/metrics.md) — per-epoch step
    summaries for free wherever ``build_train_step`` is used — and, when a
    timeline is active (docs/timeline.md), wraps each call in a
    ``jax.train_step`` span on this rank's trace.  The measured interval
    is the on-host dispatch of one step call (jax dispatch is async);
    training loops that fetch the loss each step see true step time.
    Every jit attribute (``lower``, ``trace``, ...) delegates to the
    wrapped function."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, *args, **kwargs):
        from horovod_tpu import common as _common

        tl = _common.timeline_enabled()
        mx = _metrics.registry.enabled
        if not tl and not mx:
            return self._fn(*args, **kwargs)
        if tl:
            _common._trace_begin("jax.train_step", "TRAIN_STEP")
        t0 = time.perf_counter()
        try:
            out = self._fn(*args, **kwargs)
        finally:
            if tl:
                _common._trace_end("jax.train_step")
        if mx:
            _metrics.registry.observe("step_sec", time.perf_counter() - t0)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def build_train_step(loss_fn: Callable, optimizer, mesh: Mesh,
                     axis_name: Optional[str] = None,
                     has_aux: bool = False,
                     batch_spec=None,
                     donate: bool = True,
                     check_vma: bool = True):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss[, aux])``.

    ``loss_fn(params, batch)`` computes the *local shard's* mean loss (and
    optionally an aux pytree with ``has_aux=True`` — e.g. updated batch-norm
    statistics, which the step cross-replica-averages like the loss).
    ``optimizer`` is a plain `optax.GradientTransformation`; it is wrapped in
    `DistributedOptimizer` internally.  Batches enter sharded along
    ``axis_name`` (see `horovod_tpu.parallel.shard_batch`); params/opt_state
    are replicated.  ``batch_spec`` (default ``P(axis_name)`` over every
    leaf) may be a pytree prefix of PartitionSpecs for batches mixing sharded
    data with replicated state (e.g. batch-norm statistics: ``P()``).
    """
    axis_name = axis_name or mesh.axis_names[0]
    if batch_spec is None:
        batch_spec = P(axis_name)
    # `axis_name` may be one mesh axis or a tuple (e.g. ("dp", "sp")):
    # gradient averaging and loss reporting reduce over all of them.
    import optax

    dist_opt = DistributedOptimizer(optimizer, axis_name=axis_name)

    def shard_step(params, opt_state, batch):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = dist_opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, axis_name)
        if has_aux:
            aux = jax.tree.map(lambda a: lax.pmean(a, axis_name), aux)
            return params, opt_state, loss, aux
        return params, opt_state, loss

    n_out = 4 if has_aux else 3
    # check_vma=False is needed for interpret-mode Pallas collectives on
    # CPU test meshes (rdma / fused ring rotation): the interpreter does
    # not propagate the varying-manual-axes annotation through its
    # internals.  Compiled TPU kernels don't need it.
    mapped = shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P(), batch_spec),
        out_specs=(P(),) * n_out,
        check_vma=check_vma)
    return _TimedStep(jax.jit(mapped, donate_argnums=(0, 1)
                              if donate else ()))


# ---------------------------------------------------------------------------
# Pipeline parallelism glue (docs/pipeline.md).
# ---------------------------------------------------------------------------

def run_pipeline(stage_modules, stage_params, optimizer, batches,
                 n_stages: Optional[int] = None,
                 n_microbatches: Optional[int] = None,
                 loss_fn=None, prefix: str = "pipe",
                 tag: Optional[int] = None):
    """Pipelined training loop: 1F1B over the engine's p2p plane.

    The world is a ``stages x data-parallel`` grid (contiguous ranks per
    stage).  Each step runs the 1F1B (or interleaved, when
    ``stage_modules`` holds several chunks) schedule through
    :class:`~horovod_tpu.parallel.pipeline.PipelineRunner`, DP-averages
    the accumulated parameter gradients over this stage's
    ``hvd.stage_group`` — never the full world — and applies
    ``optimizer`` (an ``optax.GradientTransformation``) locally.

    ``stage_modules``/``stage_params`` are THIS rank's chunks (see
    ``partition_transformer`` / ``partition_params``).  ``batches``
    iterates ``(inputs, targets)`` per-DP-rank batches; every rank passes
    its DP shard (the first stage consumes inputs, the last targets, and
    every stage derives the fixed activation-bucket geometry from the
    input shape).  ``loss_fn(logits, targets)`` runs on the last stage
    (default ``models.next_token_loss``).

    Knobs (overridable by argument): ``HVD_TPU_PIPELINE_STAGES``,
    ``HVD_TPU_PIPELINE_MICROBATCHES`` (default 4),
    ``HVD_TPU_PIPELINE_TAG`` (p2p tag base, default 0 — bump to isolate
    concurrent pipelines' tensor namespaces).

    Returns ``(stage_params, opt_state, losses)`` — ``losses`` carries
    one mean micro-batch loss per step on last-stage ranks, Nones
    elsewhere.
    """
    import os

    import numpy as np
    import optax

    from horovod_tpu import common as hvd
    from horovod_tpu.models.transformer import next_token_loss
    from horovod_tpu.parallel.pipeline import (EngineTransport,
                                               PipelineGrid,
                                               PipelineRunner)

    if n_stages is None:
        n_stages = int(os.environ.get("HVD_TPU_PIPELINE_STAGES", "0"))
    if n_stages < 1:
        raise ValueError(
            "pass n_stages= or set HVD_TPU_PIPELINE_STAGES (>= 1)")
    if n_microbatches is None:
        n_microbatches = int(
            os.environ.get("HVD_TPU_PIPELINE_MICROBATCHES", "4"))
    if tag is None:
        tag = int(os.environ.get("HVD_TPU_PIPELINE_TAG", "0"))

    grid = PipelineGrid(n_stages, hvd.size(), hvd.rank())
    last = grid.stage == n_stages - 1
    if loss_fn is None and last:
        loss_fn = next_token_loss
    runner = PipelineRunner(stage_modules, stage_params, grid,
                            n_microbatches, EngineTransport(tag),
                            loss_fn=loss_fn, prefix=prefix)
    group = (hvd.stage_group(grid.stage_ranks()) if grid.dp > 1 else None)
    opt_state = [optimizer.init(p) for p in runner.params]
    losses = []
    try:
        for inputs, targets in batches:
            runner.set_bucket_shape(inputs.shape[0] // n_microbatches,
                                    inputs.shape[1])
            loss, grads = runner.step(inputs if grid.stage == 0 else None,
                                      targets if last else None)
            for chunk, gtree in enumerate(grads):
                if gtree is None:
                    continue
                if group is not None:
                    # DP-average within the stage: scoped collective,
                    # named per leaf so the cycle replays through the
                    # response cache like the p2p stream does.  The
                    # stage id is part of the name — stage groups are
                    # disjoint, so the same leaf index negotiates
                    # concurrently in every stage.
                    leaves, treedef = jax.tree.flatten(gtree)
                    reduced = [
                        hvd.allreduce(
                            np.asarray(leaf, np.float32),
                            name=(f"{prefix}.s{grid.stage}.grad"
                                  f".c{chunk}.l{i}"),
                            group=group)
                        for i, leaf in enumerate(leaves)]
                    gtree = jax.tree.unflatten(treedef, reduced)
                updates, opt_state[chunk] = optimizer.update(
                    jax.tree.map(jnp_asarray, gtree), opt_state[chunk],
                    runner.params[chunk])
                runner.params[chunk] = optax.apply_updates(
                    runner.params[chunk], updates)
            losses.append(loss)
        # Closing world barrier: stage groups are disjoint, so without
        # it a fast stage can finish its last DP reduction and tear the
        # job down (hvd.shutdown in the caller) while another stage's
        # group collective is still in flight — which aborts that
        # collective with a shutdown error instead of completing it.
        hvd.allreduce(np.zeros(1, np.float32), name=f"{prefix}.barrier")
    except hvd.RanksDownError as exc:
        # PipelineRunner.step wraps aborts it sees, but a stage death
        # can just as well surface in the DP grad reduction or the
        # closing barrier (the survivors race the failure detector);
        # every survivor must still read the dead STAGE, not just a
        # rank number (docs/pipeline.md#faults).
        if str(exc).startswith("pipeline aborted"):
            raise
        stages = sorted({grid.stage_of(r) for r in exc.ranks})
        named = ", ".join(f"stage {s} (ranks {grid.stage_ranks(s)})"
                          for s in stages) or "unknown stage"
        raise hvd.RanksDownError(
            f"pipeline aborted: {named} died: {exc}", exc.ranks) from exc
    return runner.params, opt_state, losses


def jnp_asarray(x):
    """numpy -> jnp leaf cast for post-allreduce gradient trees."""
    import jax.numpy as jnp

    return jnp.asarray(x)
