"""XLA data plane for eager collectives.

The north-star TPU mapping of the reference's NCCL data plane
(/root/reference/horovod/common/operations.cc:861-1100): eagerly issued
tensors execute as *compiled XLA collectives* over the accelerator fabric
(ICI on a pod; gloo/gRPC on CPU test meshes) instead of the engine's TCP
ring.  Enabled with ``HVD_TPU_XLA_DATA_PLANE=1``; the TCP engine remains
the control plane (negotiation, error paths) and the fallback for dtypes
XLA does not carry (f64 with x64 disabled, bool).

Dispatch-order agreement
------------------------
Every rank must issue the *same sequence* of compiled collectives or the
fabric deadlocks.  Each plane op therefore enqueues a tiny int64 allreduce
(``__xp.<name>``) through the TCP engine carrying a per-rank metadata slot:
``vec[rank] = hash(op, dtype, shape, root)`` and ``vec[size+rank] = dim0``.
The engine's coordinator negotiates these exactly like any other tensor
(the reference's MPIRequest counting, operations.cc:268-301) and — because
response lists are built by rank 0 and broadcast — completes them in an
order that is identical on every rank.  The engine stamps each completion
with a (tick, seq) pair (engine.cc CompleteEntry); the plane dispatches
XLA programs only for ops in *closed* ticks, sorted by seq, with fusion
buckets that never straddle a tick.  Any prefix a rank dispatches early is
therefore a prefix of what every other rank will dispatch: interleaved
poll-while-enqueue patterns (torch hooks firing in different orders,
polling one handle while another rank enqueues more) cannot diverge.

The metadata hash doubles as the cross-rank shape/dtype/root consistency
check (the reference's ConstructMPIResponse validation,
operations.cc:301-503): a mismatch surfaces as a typed ``ValueError`` on
every rank instead of an opaque XLA error or a hang.  The per-rank dim0
slots carry ragged allgather geometry, so eager allgather rides the plane
too (the reference's MPI_Allgatherv displs, operations.cc:778-838).
Because these ``__xp.*`` metadata ops negotiate through the same rank-0
coordinator as engine collectives, they feed the coordinator's
announce-order accounting for free: plane collectives show up in
``metrics_snapshot()["skew"]`` (last-to-announce counts, skew histogram)
and in rank 0's NEGOTIATE timeline rows exactly like engine ones.

Metadata cache (steady state)
-----------------------------
Training repeats the identical collective sequence every step, so after
the first step the metadata allreduce re-derives an agreement every rank
already holds.  A ``(name, my_hash)``-keyed cache (insert-only, filled in
dispatch order, which is prefix-consistent across ranks) lets repeat
ops replay the verified agreement through a negotiation-only engine noop
(``OP_NOOP``): zero ``__xp.*`` data movement, and — once the engine's own
response cache warms — a per-op cache *bit* on the wire instead of a
string request.  A rank whose metadata changed misses locally and submits
the real ``__xp.`` op; the coordinator converts that split into a typed
mismatched-metadata error on every rank.  Allgathers never cache: their
ragged per-rank dim0 must keep flowing through the metadata allreduce.
``HVD_TPU_RESPONSE_CACHE=0`` disables (docs/performance.md).

Tensor fusion
-------------
flush() concatenates consecutive same-dtype allreduces of one tick into a
single flat buffer — one compiled all-reduce per bucket, the analogue of
the fusion buffer (operations.cc:1607-1642, docs/tensor-fusion.md) — under
``HOROVOD_FUSION_THRESHOLD``.  Executables cache by (op, padded length,
dtype), the NCCL-communicator-cache analogue (operations.cc:212); buffer
lengths are padded to ~12.5%-granular pseudo-log buckets so steady-state
training reuses one executable per step.
"""

from __future__ import annotations

import collections
import ctypes
import hashlib
import os
import threading
import time
from typing import List, Optional

import numpy as np

from horovod_tpu.common import metrics as _metrics
from horovod_tpu.common import postmortem as _postmortem

_lock = threading.Lock()
_plane = None  # initialized XlaDataPlane, or False if init failed/disabled

# Compiled-executable cache bound (_jit_for): steady-state training reuses
# a handful of (op, padded length, dtype) keys, but a pathological shape
# stream (e.g. per-sample ragged allgathers) used to grow the dict — and
# jax's compilation cache behind it — without bound.  LRU past this.
_JIT_CACHE_CAPACITY = 128

# Wire compression (docs/performance.md#wire-compression): the plane
# mirrors the engine's negotiated scheme with jnp casts — f32 allreduce
# buckets past the min-bytes floor dispatch in the wire dtype and the
# compiled program widens back to f32 before summing (f32 accumulation,
# like the engine's per-hop f32 accumulate).  Mode codes are the engine's
# CompressionMode values, read per closed tick over the same lockstep
# seam the fusion threshold rides, so every rank compresses the same
# buckets the same way.  fp8 saturates at ±448 before the cast (ml_dtypes
# overflows to nan; one clipped outlier must not poison a fused bucket —
# the engine's encoder saturates identically).
_FP8_MAX = 448.0
_WIRE_DTYPES = {}
_WIRE_MODE_NAMES = {1: "bf16", 2: "fp8"}
try:
    import ml_dtypes as _ml_dtypes

    _WIRE_DTYPES = {1: np.dtype(_ml_dtypes.bfloat16),
                    2: np.dtype(_ml_dtypes.float8_e4m3fn)}
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass


def quantize_error_feedback(values: np.ndarray, mode: int):
    """Quantize f32 ``values`` to the wire dtype for ``mode`` (1=bf16,
    2=fp8-e4m3fn, saturating) and return ``(wire, residual)``.  The
    residual EXACTLY carries the rounding error in f32 arithmetic
    (``values == wire.astype(f32) + residual`` element-wise, saturation
    clipping excepted): the quantized value is within a fraction of the
    input's magnitude, so the subtraction is exact by Sterbenz's lemma.
    Feeding the residual into the next step's pre-compression add is the
    1-bit-SGD-style error feedback that keeps lossy wire formats
    converging like fp32."""
    wire_dtype = _WIRE_DTYPES[mode]
    v = np.clip(values, -_FP8_MAX, _FP8_MAX) if mode == 2 else values
    wire = v.astype(wire_dtype)
    residual = values - wire.astype(np.float32)
    return wire, residual


def _meta_hash(kind: str, dtype, shape, root: int) -> int:
    payload = repr((kind, np.dtype(dtype).str, tuple(shape), root)).encode()
    digest = hashlib.blake2b(payload, digest_size=8).digest()
    return int.from_bytes(digest, "little") & ((1 << 62) - 1)


def _bucket_len(n: int, minimum: int = 256) -> int:
    """Pad a flat buffer length to a pseudo-log bucket (8 steps per octave,
    <=12.5% waste) so the executable cache stays small without doubling
    fabric traffic the way pure power-of-two padding would."""
    if n <= minimum:
        return minimum
    p = 1 << (int(n) - 1).bit_length()  # next power of two >= n
    half = p >> 1
    step = max(half // 8, 1)
    return half + -(-(n - half) // step) * step


class _Batch:
    """One dispatched XLA program; its host copy is materialized once and
    shared by every handle whose segment lives in it.  Fused batches are
    shared by many handles, which may be waited from different threads —
    the lock keeps the lazy materialization single-shot."""

    def __init__(self, arr, t_disp: float = 0.0):
        self._arr = arr
        self._host = None
        self._mu = threading.Lock()
        self._t_disp = t_disp  # metrics: dispatch timestamp (0.0 = off)

    def ready(self) -> bool:
        with self._mu:
            return self._host is not None or self._arr.is_ready()

    def host(self) -> np.ndarray:
        with self._mu:
            if self._host is None:
                self._host = np.asarray(self._arr)
                self._arr = None
                if self._t_disp:
                    _metrics.registry.observe(
                        "dispatch_sec", time.perf_counter() - self._t_disp)
            return self._host


class _PlaneOp:
    __slots__ = ("name", "kind", "payload", "root", "handle", "neg_raw",
                 "neg_in", "neg_out", "my_hash", "seq", "tick", "dim0s",
                 "t_enq", "t_neg", "cached")

    def __init__(self, name, kind, payload, root, handle):
        self.name = name
        self.kind = kind  # "ar" | "bc" | "ag"
        self.payload = payload  # compute-dtype, C-contiguous
        self.root = root
        self.handle = handle
        self.cached = False  # metadata-cache hit: negotiation-only noop
        self.neg_raw = -1
        self.neg_in = None  # pinned until negotiation completes
        self.neg_out = None
        self.my_hash = 0
        self.seq = None  # engine completion stamps once negotiated
        self.tick = None
        self.dim0s = None  # per-rank dim0 (allgather geometry)
        # Metrics timestamps (0.0 = metrics disabled at enqueue): t_enq at
        # submission, t_neg when the negotiation stamp lands.
        self.t_enq = 0.0
        self.t_neg = 0.0


class XlaHandle:
    """Duck-type of horovod_tpu.common.Handle for XLA-plane collectives."""

    def __init__(self, plane, op_kind: str, name: str,
                 out: Optional[np.ndarray], average: bool, size: int,
                 dtype, shape):
        self._plane = plane
        self._kind = op_kind
        self._name = name
        self._out = out
        self._average = average
        self._size = size
        self._dtype = dtype  # caller-visible dtype (pre-widening)
        self._shape = tuple(shape)
        self._batch: Optional[_Batch] = None
        self._off = 0
        self._n = 0
        self._ag_pad = 0  # allgather: padded per-rank dim0
        self._ag_dim0s = None
        self._error: Optional[Exception] = None
        self._finished = False
        self._tl_started = False  # timeline op row opened at dispatch
        # Metrics: end-to-end wait latency from enqueue (0.0 = off).
        self._t0 = time.perf_counter() if _metrics.registry.enabled else 0.0
        # Negotiation (tick, seq) stamp, mirrored from the engine metadata
        # op at dispatch time (duck-type parity with common.Handle).
        self.completion_tick: Optional[int] = None
        self.completion_seq: Optional[int] = None

    # plane-side plumbing -------------------------------------------------
    def _fail(self, err: Exception) -> None:
        self._error = err

    def _set_result(self, batch: _Batch, off: int, n: int,
                    tick: Optional[int] = None,
                    seq: Optional[int] = None) -> None:
        self._batch = batch
        self._off = off
        self._n = n
        self.completion_tick = tick
        self.completion_seq = seq

    # public handle API ---------------------------------------------------
    def done(self) -> bool:
        if self._finished:
            return True
        self._plane.flush()
        if self._error is not None:
            return True
        return self._batch is not None and self._batch.ready()

    def wait(self) -> np.ndarray:
        if self._finished:
            raise ValueError(f"handle for '{self._name}' already waited on")
        self._finished = True
        self._plane._wait_dispatch(self)
        if self._error is not None:
            if self._tl_started:
                from horovod_tpu import common

                # Close the op row opened at dispatch so the trace does
                # not show the tensor as running forever.
                common._lib.hvd_tpu_timeline_op_end(self._name.encode(), 0)
            raise self._error
        tl_lib = None
        if self._tl_started:
            from horovod_tpu import common

            tl_lib = common._lib
            tl_lib.hvd_tpu_timeline_activity_start(self._name.encode(),
                                                   b"DEVICE_WAIT")
        host = self._batch.host()
        if tl_lib is not None or self._t0:
            # This op's own extent, not the shared fused buffer's size
            # (which would over-report by the fusion factor).
            # Caller-visible width: bf16/f16 allreduce widens the compute
            # buffer to f32, but the tensor the caller moved is half that.
            itemsize = np.dtype(self._dtype).itemsize
            if self._kind == "ag":
                my_bytes = int(np.prod(self._shape)) * itemsize
            else:
                my_bytes = self._n * itemsize
            if tl_lib is not None:
                tl_lib.hvd_tpu_timeline_activity_end(self._name.encode())
                tl_lib.hvd_tpu_timeline_op_end(self._name.encode(),
                                               int(my_bytes))
            if self._t0:
                _metrics.registry.record_bytes_out("xla", int(my_bytes))
                _metrics.registry.observe(
                    "wait_sec", time.perf_counter() - self._t0)
        if self._kind == "ag":
            pad = self._ag_pad
            blocks = [host[r * pad:r * pad + int(d)]
                      for r, d in enumerate(self._ag_dim0s)]
            return np.concatenate(blocks).reshape(self._shape)
        seg = host[self._off:self._off + self._n].reshape(self._shape)
        if self._average:
            if np.issubdtype(self._dtype, np.integer):
                seg = (seg / self._size).astype(self._dtype)
            else:
                seg = (seg / np.asarray(self._size, seg.dtype)).astype(
                    self._dtype)
        else:
            seg = seg.astype(self._dtype, copy=False)
        if self._out is not None:
            np.copyto(self._out, seg)
            return self._out
        return np.ascontiguousarray(seg) if seg.ndim else seg.copy()


class XlaDataPlane:
    def __init__(self, mesh, spec_sharded, spec_replicated, rank, size,
                 fusion_threshold, spec_proc_only=None, local_chips=1):
        self._mesh = mesh
        # ar/bc inputs shard the flat payload across this process's local
        # chips too ("hvd_local"), engaging every chip's ICI bandwidth;
        # allgather keeps the ragged payload replicated per process.
        self._in_sharding = spec_sharded
        self._in_sharding_proc = spec_proc_only or spec_sharded
        self._out_sharding = spec_replicated
        self._local_chips = int(local_chips)
        self._rank = rank
        self._size = size
        self._fusion_threshold = int(fusion_threshold)
        from horovod_tpu.common.config import Config

        # Snapshot once: _wait_dispatch is per-handle hot path; <=0
        # disables the stall warning (the conventional "off" value).
        cfg = Config.from_env()
        self._stall_sec = cfg.stall_warning_sec
        # Hard deadline for the dispatch wait (XLA-plane parity with the
        # engine's coordinated abort): past it the handle FAILS with
        # CollectiveTimeoutError instead of polling forever.  <= 0 = off.
        self._timeout_sec = cfg.collective_timeout_sec
        self._fns = collections.OrderedDict()  # LRU-bounded compile cache
        # Metadata cache (docs/performance.md): name -> verified my_hash.
        # A repeat op whose hash matches replays the cached cross-rank
        # agreement through a negotiation-only engine noop and skips the
        # "__xp.*" metadata allreduce entirely.  Entries are inserted in
        # DISPATCH order — the one sequence that is prefix-consistent
        # across ranks (module docstring) — and are insert-only/immutable
        # (see _meta_update), so every rank's cache holds the same
        # entries and a hit on one rank is a hit on all (a divergence
        # would surface as the engine's typed cached-vs-changed-metadata
        # error, never a hang).  Allgathers are excluded: their per-rank
        # dim0 may legitimately change step to step, and that geometry
        # must keep flowing through the metadata allreduce.
        cfg_cap = cfg.effective_cache_capacity
        self._meta_cache = {} if cfg_cap > 0 else None
        self._meta_capacity = cfg_cap
        # Online autotuning (docs/performance.md#autotuning): the engine's
        # fusion threshold can change at tick boundaries, and the plane's
        # bucket boundaries must follow it IDENTICALLY on every rank (a
        # fused bucket is one compiled collective — a split into
        # old-threshold and new-threshold camps would dispatch mismatched
        # programs).  Memoized per tick: the engine's applied-parameter
        # history is append-only, so a closed tick's threshold is stable.
        self._tick_thresholds: dict = {}
        # Single-process ops carry tick -1 (no negotiation): their
        # threshold is the live engine value, read ONCE per flush — not
        # per op, the bucketing loop is the dispatch hot path.
        self._live_threshold: Optional[int] = None
        # Wire compression (docs/performance.md#wire-compression): the
        # mode is the engine's lockstep-broadcast state, looked up per
        # closed tick exactly like the fusion threshold so autotuned mode
        # changes move every rank's dispatch format at the same tick
        # boundary.  Residuals are the per-tensor f32 error-feedback
        # buffers; comp_stats mirrors the engine's wire/payload byte and
        # per-mode bucket accounting for metrics_snapshot()["compression"].
        self._comp_min_bytes = int(cfg.compression_min_bytes)
        self._tick_comp: dict = {}
        self._live_comp: Optional[int] = None
        self._residuals: dict = {}
        self.comp_stats = {"wire_bytes": 0, "payload_bytes": 0,
                           "ops": {"none": 0, "bf16": 0, "fp8": 0}}
        self._mu = threading.RLock()  # guards _fns, _pending, _local_seq
        self._pending: List[_PlaneOp] = []
        # Ops withdrawn by a timed-out wait, pinned so the engine's raw
        # pointers into their negotiation buffers stay valid (see
        # _fail_timed_out).  Timeouts are terminal for the job; bounded in
        # practice by the handful of ops outstanding at abort time.
        self._abandoned: List[_PlaneOp] = []
        # One stall = one abort event in the metrics, no matter how many
        # outstanding handles time out on it (the engine's latched abort
        # is synced separately and counts as its own detection event).
        self._abort_recorded = False
        self._local_seq = 0  # single-process ordering (no negotiation)
        # Observability: dispatches counts compiled-program launches;
        # fused_tensors counts ops carried by them (tests assert N small
        # allreduces ride 1 dispatch).
        self.stats = {"dispatches": 0, "fused_tensors": 0}

    # -- negotiation over the TCP control plane ---------------------------

    def _negotiate(self, op: _PlaneOp) -> None:
        """Enqueue the metadata allreduce for `op` through the engine."""
        from horovod_tpu import common
        from horovod_tpu.common import dtypes as _dt

        if self._size == 1:
            op.seq = self._local_seq
            self._local_seq += 1
            op.tick = -1  # always closed
            op.dim0s = np.asarray(
                [op.payload.shape[0] if op.payload.ndim else 0], np.int64)
            if op.t_enq:
                op.t_neg = time.perf_counter()
                _metrics.registry.observe("negotiation_sec",
                                          op.t_neg - op.t_enq)
            return
        dim0 = op.payload.shape[0] if op.payload.ndim else 0
        shape = (op.payload.shape[1:] if op.kind == "ag"
                 else op.payload.shape)
        op.my_hash = _meta_hash(op.kind, op.handle._dtype, shape, op.root)
        if self._meta_cache is not None and op.kind != "ag":
            if self._meta_cache.get(op.name) == op.my_hash:
                # Metadata-cache hit: every rank holding this verified
                # agreement replays it through a negotiation-only engine
                # noop — global dispatch order still comes from the
                # engine's completion stamps, but no metadata allreduce
                # runs and, once the engine's own response cache warms, no
                # string negotiation either.  A rank whose metadata
                # changed misses here and submits the real "__xp." op; the
                # engine's coordinator then converts the split into the
                # typed mismatched-metadata error (engine.cc).
                dims = (ctypes.c_longlong * 1)(2 * self._size)
                raw = common._lib.hvd_tpu_enqueue(
                    common.OP_NOOP, ("__xp." + op.name).encode(),
                    None, None, dims, 1, _dt.numpy_to_code(np.dtype(np.int64)),
                    -1, 0)
                if raw < 0:
                    raise common.HorovodInternalError("engine is shut down")
                op.cached = True
                op.neg_raw = raw
                _metrics.registry.record_cache("xla", "hits")
                if _postmortem.plane_ring.enabled:
                    _postmortem.plane_ring.record("cache_hit", op.name)
                return
            _metrics.registry.record_cache("xla", "misses")
        vec = np.zeros(2 * self._size, np.int64)
        vec[self._rank] = op.my_hash
        vec[self._size + self._rank] = dim0
        out = np.zeros_like(vec)
        dims = (ctypes.c_longlong * 1)(2 * self._size)
        raw = common._lib.hvd_tpu_enqueue(
            common.OP_ALLREDUCE, ("__xp." + op.name).encode(),
            vec.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            dims, 1, _dt.numpy_to_code(vec.dtype), -1, 0)
        if raw < 0:
            raise common.HorovodInternalError("engine is shut down")
        op.neg_raw = raw
        op.neg_in = vec
        op.neg_out = out

    def _poll_negotiations(self) -> None:
        """Collect completion stamps for negotiated ops (non-blocking)."""
        from horovod_tpu import common

        lib = common._lib
        for op in self._pending:
            if op.seq is not None or self._size == 1:
                continue
            if lib.hvd_tpu_poll(op.neg_raw) != 1:
                continue
            code = lib.hvd_tpu_status(op.neg_raw)
            if code != common.ST_OK:
                msg = lib.hvd_tpu_error(op.neg_raw).decode()
                op.handle._fail(common._status_error(code, msg, op.name))
                if _postmortem.plane_ring.enabled:
                    _postmortem.plane_ring.record("error", op.name, code)
                op.seq = -1  # consumed; never dispatched
                # A name that negotiated to an error (e.g. the cached-vs-
                # changed-metadata mismatch) must renegotiate from
                # scratch: drop the stale agreement.  The error reaches
                # every rank, so every cache evicts together.
                if self._meta_cache is not None:
                    self._meta_cache.pop(op.name, None)
            elif op.cached:
                # Negotiation-only replay: the cross-rank agreement was
                # verified when the entry was stored; only the ordering
                # stamps matter here.
                op.seq = int(lib.hvd_tpu_completion_seq(op.neg_raw))
                op.tick = int(lib.hvd_tpu_completion_tick(op.neg_raw))
                if op.t_enq:
                    op.t_neg = time.perf_counter()
                    _metrics.registry.observe("negotiation_sec",
                                              op.t_neg - op.t_enq)
            else:
                op.seq = int(lib.hvd_tpu_completion_seq(op.neg_raw))
                op.tick = int(lib.hvd_tpu_completion_tick(op.neg_raw))
                hashes = op.neg_out[:self._size]
                op.dim0s = op.neg_out[self._size:].copy()
                if not (hashes == op.my_hash).all():
                    bad = [r for r in range(self._size)
                           if hashes[r] != op.my_hash]
                    op.handle._fail(ValueError(
                        f"collective '{op.name}' failed: mismatched "
                        f"op/shape/dtype/root across ranks (ranks {bad} "
                        f"disagree with rank {self._rank}); every rank must "
                        f"submit the same collective with the same dtype "
                        f"and shape."))
                    op.seq = -1
                    if self._meta_cache is not None:
                        self._meta_cache.pop(op.name, None)
                if op.seq != -1 and op.t_enq:
                    op.t_neg = time.perf_counter()
                    _metrics.registry.observe("negotiation_sec",
                                              op.t_neg - op.t_enq)
            lib.hvd_tpu_release(op.neg_raw)
            op.neg_raw = -1
            op.neg_in = op.neg_out = None

    # -- dispatch ---------------------------------------------------------

    def flush(self) -> None:
        """Dispatch every op whose negotiation tick has closed, in the
        engine's completion order.  Ticks close simultaneously (in program
        order) on every rank, so the dispatched sequence — including fusion
        bucket boundaries, which never straddle a tick — is prefix-consistent
        across ranks no matter when each rank happens to flush."""
        from horovod_tpu import common

        with self._mu:
            # Snapshot the closed-tick horizon BEFORE polling: completions
            # of tick t are stored before ticks_done advances past t
            # (engine.cc RunLoopOnce), so every op in a tick this snapshot
            # closes is observable by the poll below — reading the counter
            # after polling could admit a later-seq op from a tick whose
            # earlier-seq op was polled too early, breaking the cross-rank
            # prefix property.
            if self._size == 1:
                ticks_done = 0  # local ticks are -1: always closed
            else:
                ticks_done = int(common._lib.hvd_tpu_ticks_done())
            self._live_threshold = None  # re-read at most once per flush
            self._live_comp = None
            self._poll_negotiations()
            ready = [op for op in self._pending
                     if op.seq is not None and op.seq >= 0
                     and op.tick < ticks_done]
            failed = [op for op in self._pending if op.seq == -1]
            dispatched = set()
            ready.sort(key=lambda o: o.seq)
            # Metadata-cache maintenance rides dispatch order — the one
            # sequence that is prefix-consistent across ranks — so every
            # rank stores, touches, and evicts the same entries in the
            # same order (see _meta_update).
            for op in ready:
                self._meta_update(op)
            bucket: List[_PlaneOp] = []
            bucket_key = None
            bucket_bytes = 0
            for op in ready:
                nbytes = op.payload.nbytes
                if op.kind == "ag":
                    key = ("ag", id(op))  # never fused
                else:
                    key = (op.kind, op.tick, op.payload.dtype.str, op.root)
                if (key != bucket_key
                        or bucket_bytes + nbytes
                        > self._threshold_for(op.tick)):
                    if bucket:
                        self._dispatch(bucket)
                    bucket = []
                    bucket_key = key
                    bucket_bytes = 0
                bucket.append(op)
                bucket_bytes += nbytes
                dispatched.add(id(op))
            if bucket:
                self._dispatch(bucket)
            consumed = dispatched | {id(op) for op in failed}
            self._pending = [op for op in self._pending
                             if id(op) not in consumed]

    def _threshold_for(self, tick: int) -> int:
        """Fusion threshold in force at engine tick `tick`.  The autotuner
        mutates the threshold in lockstep at tick boundaries (every rank
        applies the same broadcast at the same tick index), so keying the
        bucket limit off the op's completion tick keeps plane bucket
        boundaries cross-rank deterministic even while the knob moves.
        Without autotuning the engine history holds only the initial
        value, so this degrades to the static threshold.  `tick` < 0
        (single-process: no negotiation) reads the live value."""
        from horovod_tpu import common

        if common._lib is None:  # engine never loaded: static fallback
            return self._fusion_threshold
        if tick < 0:
            if self._live_threshold is None:
                self._live_threshold = int(
                    common._lib.hvd_tpu_autotune_fusion_threshold())
            return self._live_threshold
        thr = self._tick_thresholds.get(tick)
        if thr is None:
            thr = int(common._lib.hvd_tpu_fusion_threshold_at(tick))
            if len(self._tick_thresholds) > 4096:
                self._tick_thresholds.clear()
            self._tick_thresholds[tick] = thr
        return thr

    def _compression_for(self, tick: int) -> int:
        """Wire-compression mode in force at engine tick `tick`, memoized
        like :meth:`_threshold_for`: the mode mutates only in lockstep at
        tick boundaries, so keying the dispatch format off the op's
        completion tick keeps every rank compiling and launching the same
        program for the same bucket even while the autotuner moves the
        knob.  Size-1 jobs move no wire bytes — always uncompressed."""
        from horovod_tpu import common

        if common._lib is None or self._size == 1:
            return 0
        if tick < 0:
            if self._live_comp is None:
                self._live_comp = int(common._lib.hvd_tpu_compression_mode())
            return self._live_comp
        mode = self._tick_comp.get(tick)
        if mode is None:
            mode = int(common._lib.hvd_tpu_compression_mode_at(tick))
            if len(self._tick_comp) > 4096:
                self._tick_comp.clear()
            self._tick_comp[tick] = mode
        return mode

    def _wait_dispatch(self, handle: XlaHandle) -> None:
        """Block until `handle`'s op is dispatched (or failed).  Bounded by
        the engine cycle time; the reference's synchronize is the same poll
        loop (/root/reference/horovod/torch/mpi_ops.cc:393-399).  Like the
        engine's coordinator sweep (engine.cc CheckForStalledTensors), a
        wait that exceeds ``stall_warning_sec`` logs which negotiations are
        still outstanding — a peer that never submits the matching
        collective would otherwise spin here silently forever."""
        stall_sec = self._stall_sec
        timeout_sec = self._timeout_sec
        start = last_warn = time.monotonic()
        while True:
            self.flush()
            if handle._error is not None or handle._batch is not None:
                return
            now = time.monotonic()
            if timeout_sec > 0 and now - start >= timeout_sec:
                self._fail_timed_out(handle, now - start)
                return
            if stall_sec > 0 and now - last_warn >= stall_sec:
                last_warn = now
                with self._mu:
                    waiting = [op.name for op in self._pending
                               if op.seq is None]
                # Ungated (like the engine's sweep records): tests and
                # operators read metrics_snapshot()["stalls"] without
                # opting into full metrics collection.
                _metrics.registry.record_stall(handle._name, now - start)
                if _postmortem.plane_ring.enabled:
                    _postmortem.plane_ring.record(
                        "stall", handle._name, int(now - start))
                import sys

                print(
                    f"WARNING: XLA-plane wait for '{handle._name}' has "
                    f"stalled for {now - start:.0f}s; negotiations still "
                    f"pending: {waiting or '[none — tick not closed]'}. "
                    f"One or more ranks may not have submitted this "
                    f"collective.", file=sys.stderr, flush=True)
            time.sleep(0.001)

    def _fail_timed_out(self, handle: XlaHandle, waited_sec: float) -> None:
        """Dispatch-wait deadline (HVD_TPU_COLLECTIVE_TIMEOUT_SEC) hit:
        fail the handle with a typed error naming the negotiations still
        outstanding, and withdraw its op from the pending queue so a later
        flush cannot dispatch a collective its waiter already abandoned
        (the peers that DID time out would never dispatch the match, and a
        half-dispatched bucket wedges the fabric)."""
        from horovod_tpu import common

        with self._mu:
            waiting = [op.name for op in self._pending if op.seq is None]
            mine = [op for op in self._pending if op.handle is handle]
            self._pending = [op for op in self._pending
                             if op.handle is not handle]
            # The withdrawn op's negotiation may still be pending inside
            # the engine, which holds raw pointers into neg_in/neg_out —
            # pin the op (buffers and all) until shutdown rather than
            # freeing memory the engine thread could still write.
            self._abandoned.extend(mine)
            record_abort = not self._abort_recorded
            self._abort_recorded = True
        _metrics.registry.record_stall(handle._name, waited_sec)
        if record_abort:
            _metrics.registry.record_abort("timeout")
        if _postmortem.plane_ring.enabled:
            _postmortem.plane_ring.record("abort", handle._name,
                                          int(waited_sec))
        # The plane-side deadline is a typed abort too: leave the dump
        # (write-once; the engine path may already have claimed it).
        _postmortem.write_postmortem("timeout")
        handle._fail(common.CollectiveTimeoutError(
            f"collective '{handle._name}' failed: XLA-plane dispatch wait "
            f"exceeded HVD_TPU_COLLECTIVE_TIMEOUT_SEC "
            f"({waited_sec:.1f}s > {self._timeout_sec:.1f}s); negotiations "
            f"still pending: {waiting or '[none — tick not closed]'}. One "
            f"or more ranks never submitted the matching collective; the "
            f"wait was aborted instead of hanging."))

    def _meta_update(self, op: _PlaneOp) -> None:
        """Store `op`'s verified cross-rank agreement.  INSERT-ONLY and
        IMMUTABLE: entries are added in dispatch order (prefix-consistent
        across ranks) until the capacity is reached, never churn-evicted
        and never re-hashed in place.  An LRU eviction or in-place
        refresh would be applied at rank-local moments — two ranks
        mid-flush could disagree about it, and a fully consistent program
        would then split into cached/uncached camps and die with the
        typed mismatched-metadata error.  A stable entry set keeps the
        hit/miss decision identical on every rank; entries leave only
        through per-name error eviction (the typed error reaches every
        rank's op together).  Names beyond the capacity, and names whose
        metadata changed after caching, simply keep paying the metadata
        allreduce (the engine's response cache still makes its
        negotiation cheap).  Allgathers never cache: their ragged
        per-rank dim0 must keep flowing through the metadata exchange."""
        # Size 1 never negotiates (no hash is computed): nothing to cache.
        if self._meta_cache is None or op.kind == "ag" or self._size == 1:
            return
        if (op.name not in self._meta_cache
                and len(self._meta_cache) < self._meta_capacity):
            self._meta_cache[op.name] = op.my_hash

    def _jit_for(self, kind: str, length_or_shape, dtype, root: int = 0):
        import jax

        key = (kind, length_or_shape, np.dtype(dtype).str, root)
        fn = self._fns.get(key)
        if fn is None:
            if kind == "ar":
                fn = jax.jit(lambda a: a.sum(axis=0),
                             out_shardings=self._out_sharding)
            elif kind == "arc":
                # Compressed allreduce: the buffer arrives in the wire
                # dtype (bf16/fp8) and widens back to f32 BEFORE the sum
                # — f32 accumulation, mirroring the engine's per-hop f32
                # accumulate (docs/performance.md#wire-compression).
                import jax.numpy as jnp

                fn = jax.jit(lambda a: a.astype(jnp.float32).sum(axis=0),
                             out_shardings=self._out_sharding)
            elif kind == "bc":
                fn = jax.jit(lambda a: a[root],
                             out_shardings=self._out_sharding)
            else:  # "ag": resharding identity compiles to an all-gather
                fn = jax.jit(lambda a: a.reshape((-1,) + a.shape[2:]),
                             out_shardings=self._out_sharding)
            self._fns[key] = fn
            # LRU bound: a pathological shape stream (per-sample ragged
            # allgathers) used to grow this — and jax's compile cache
            # behind it — without limit.
            while len(self._fns) > _JIT_CACHE_CAPACITY:
                self._fns.popitem(last=False)
        else:
            self._fns.move_to_end(key)
        return fn

    def _global_array(self, local: np.ndarray, replicated: bool = False):
        import jax

        sharding = self._in_sharding_proc if replicated else self._in_sharding
        return jax.make_array_from_process_local_data(
            sharding, local[np.newaxis],
            (self._size,) + local.shape)

    _TL_OP_NAMES = {"ar": "XLA_ALLREDUCE", "bc": "XLA_BROADCAST",
                    "ag": "XLA_ALLGATHER"}

    def _dispatch(self, bucket: List[_PlaneOp]) -> None:
        # Timeline: plane execution phases land in the same Chrome-tracing
        # file as the engine's NEGOTIATE events (the `__xp.*` rows), per
        # REAL tensor name: BUCKET_BUILD -> XLA_DISPATCH here, DEVICE_WAIT
        # + op end in XlaHandle.wait().  Mirrors the reference's
        # ACTIVITY_START_ALL around every execution phase
        # (operations.cc:680-692).
        from horovod_tpu import common

        lib = common._lib
        tl = bool(lib and lib.hvd_tpu_timeline_enabled())
        if tl:
            op_name = self._TL_OP_NAMES[bucket[0].kind].encode()
            for op in bucket:
                lib.hvd_tpu_timeline_op_start(op.name.encode(), op_name)
                lib.hvd_tpu_timeline_activity_start(op.name.encode(),
                                                    b"BUCKET_BUILD")
                op.handle._tl_started = True
        self._dispatch_inner(bucket, lib if tl else None)

    def _dispatch_inner(self, bucket: List[_PlaneOp], tl_lib) -> None:
        kind = bucket[0].kind
        mx = _metrics.registry.enabled
        if mx:
            # Queue/bucket residency: negotiation stamp -> dispatch.  Ops
            # enqueued while metrics were off carry t_neg == 0.0 and skip.
            now = time.perf_counter()
            for op in bucket:
                if op.t_neg:
                    _metrics.registry.observe("residency_sec",
                                              now - op.t_neg)
            _metrics.registry.record_batch(len(bucket))
        if kind == "ag":
            op = bucket[0]
            pad = _bucket_len(int(op.dim0s.max()), minimum=1)
            rest = op.payload.shape[1:]
            block = np.zeros((pad,) + rest, op.payload.dtype)
            block[:op.payload.shape[0]] = op.payload
            fn = self._jit_for("ag", (pad,) + rest, op.payload.dtype)
            self._tl_phase(tl_lib, bucket, b"XLA_DISPATCH")
            batch = _Batch(self._traced_dispatch(fn, block, "ag", 1),
                           t_disp=time.perf_counter() if mx else 0.0)
            self._tl_phase(tl_lib, bucket, None)
            h = op.handle
            h._ag_pad = pad
            h._ag_dim0s = op.dim0s
            h._shape = (int(op.dim0s.sum()),) + rest
            h._set_result(batch, 0, 0, op.tick, op.seq)
        else:
            dtype = bucket[0].payload.dtype
            lens = [op.payload.size for op in bucket]
            total = int(sum(lens))
            length = _bucket_len(total)
            # The flat buffer also shards across this process's local
            # chips; keep it divisible so every chip holds an equal slice.
            chips = self._local_chips
            length = -(-length // chips) * chips
            flat = np.zeros(length, dtype)
            off = 0
            offs = []
            for op, n in zip(bucket, lens):
                flat[off:off + n] = op.payload.reshape(-1)
                offs.append(off)
                off += n
            # Wire compression: negotiated mode at this bucket's tick, on
            # f32 allreduce buckets past the min-bytes floor (the same
            # per-bucket-size-class decision the engine's coordinator
            # makes, from the same lockstep state — so the decision is
            # identical on every rank even though it is computed locally).
            bucket_bytes = sum(op.payload.nbytes for op in bucket)
            comp = 0
            if kind == "ar" and dtype == np.float32:
                comp = self._compression_for(bucket[0].tick)
                if (comp not in _WIRE_DTYPES
                        or bucket_bytes < self._comp_min_bytes):
                    comp = 0
            if comp:
                # Residual-map bound, checked ONCE before this bucket
                # touches the map (a mid-bucket clear would discard
                # residuals just stored for the bucket's earlier
                # tensors): never-repeating auto-named tensors gain
                # nothing from error feedback and must not grow this
                # forever.
                fresh = sum(1 for op in bucket
                            if op.name not in self._residuals)
                if fresh and len(self._residuals) + fresh > 4096:
                    self._residuals.clear()
                # Error feedback: fold each tensor's residual into its
                # segment, quantize the whole flat buffer once, and save
                # each segment's new rounding error for the next step.
                for op, o, n in zip(bucket, offs, lens):
                    r = self._residuals.get(op.name)
                    if r is not None and r.size == n:
                        flat[o:o + n] += r
                wire_flat, residual = quantize_error_feedback(flat, comp)
                for op, o, n in zip(bucket, offs, lens):
                    self._residuals[op.name] = residual[o:o + n].copy()
                flat = wire_flat
                fn = self._jit_for("arc", length, flat.dtype)
                mode_name = _WIRE_MODE_NAMES[comp]
            else:
                fn = self._jit_for(kind, length, dtype, bucket[0].root)
                mode_name = "none"
            if kind == "ar":
                # Ungated (like stalls): the wire-vs-payload ratio is the
                # compression acceptance number, assertable without full
                # metrics.  Payload counts at the CALLER-visible width,
                # wire at the dispatched buffer's dtype width (padding
                # excluded) — same semantics as the engine's counters.
                caller_bytes = sum(
                    int(np.prod(op.handle._shape))
                    * np.dtype(op.handle._dtype).itemsize for op in bucket)
                self.comp_stats["payload_bytes"] += caller_bytes
                self.comp_stats["wire_bytes"] += total * flat.dtype.itemsize
                self.comp_stats["ops"][mode_name] += 1
            if mx:
                _metrics.registry.observe(
                    "bucket_fill",
                    min(1.0, bucket_bytes
                        / max(self._threshold_for(bucket[0].tick), 1)))
            self._tl_phase(tl_lib, bucket, b"XLA_DISPATCH")
            batch = _Batch(self._traced_dispatch(fn, flat, kind,
                                                 len(bucket)),
                           t_disp=time.perf_counter() if mx else 0.0)
            self._tl_phase(tl_lib, bucket, None)
            for op, o, n in zip(bucket, offs, lens):
                op.handle._set_result(batch, o, n, op.tick, op.seq)
        self.stats["dispatches"] += 1
        self.stats["fused_tensors"] += len(bucket)
        if _postmortem.plane_ring.enabled:
            _postmortem.plane_ring.record("execute", bucket[0].name,
                                          len(bucket))

    def _tl_phase(self, tl_lib, bucket: List[_PlaneOp],
                  start: Optional[bytes]) -> None:
        """End the current timeline activity for every op in the bucket
        and (optionally) start the next one."""
        if tl_lib is None:
            return
        for op in bucket:
            tl_lib.hvd_tpu_timeline_activity_end(op.name.encode())
            if start is not None:
                tl_lib.hvd_tpu_timeline_activity_start(op.name.encode(),
                                                       start)

    def _traced_dispatch(self, fn, local: np.ndarray, kind: str, n_ops: int):
        """Launch the compiled collective, annotated for jax.profiler so
        plane dispatches are attributable inside an XProf/jax trace too
        (SURVEY §5.1's 'hooks into jax.profiler')."""
        import jax

        with jax.profiler.TraceAnnotation(
                f"hvd_plane_dispatch:{kind}:x{n_ops}"):
            return fn(self._global_array(local, replicated=(kind == "ag")))

    # -- public enqueue API ----------------------------------------------

    _OP_NAMES = {"ar": "allreduce", "bc": "broadcast", "ag": "allgather"}

    def _enqueue(self, kind: str, payload: np.ndarray, root: int,
                 handle: XlaHandle, name: str) -> XlaHandle:
        op = _PlaneOp(name, kind, payload, root, handle)
        # Flight recorder (postmortem plane): the XLA plane mirrors the
        # engine's ring so both data planes record their final seconds.
        if _postmortem.plane_ring.enabled:
            _postmortem.plane_ring.record("enqueue", name)
        if _metrics.registry.enabled:
            op.t_enq = time.perf_counter()
            # bytes.in/out are PAYLOAD bytes on both planes: the
            # caller-visible tensor at its own dtype's width (bf16/f16
            # pre-widening, f32 pre-compression).  On-wire bytes are
            # reported separately, in metrics_snapshot()["compression"]
            # (wire_bytes vs payload_bytes), so the two never mix.
            _metrics.registry.record_enqueue(
                "xla", self._OP_NAMES[kind],
                int(np.prod(handle._shape))
                * np.dtype(handle._dtype).itemsize)
        with self._mu:
            self._negotiate(op)
            self._pending.append(op)
        return handle

    def allreduce_async(self, array: np.ndarray, average: bool,
                        out: Optional[np.ndarray], name: str) -> XlaHandle:
        dtype = array.dtype
        # bf16/f16 sum in f32, like the engine's staging (engine.cc
        # HalfBufToFloat); bf16 from ml_dtypes reports kind "V".
        compute = array.astype(np.float32) if dtype.itemsize == 2 \
            and dtype.kind in ("f", "V") else array
        handle = XlaHandle(self, "ar", name, out, average, self._size,
                           dtype, array.shape)
        return self._enqueue("ar", compute, 0, handle, name)

    def broadcast_async(self, array: np.ndarray, root_rank: int,
                        out: Optional[np.ndarray], name: str) -> XlaHandle:
        handle = XlaHandle(self, "bc", name, out, False, self._size,
                           array.dtype, array.shape)
        return self._enqueue("bc", array, root_rank, handle, name)

    def allgather_async(self, array: np.ndarray, name: str) -> XlaHandle:
        # Final shape is known only after negotiation (ragged dim 0); the
        # handle's shape is patched at wait() from the negotiated dim0s.
        handle = XlaHandle(self, "ag", name, None, False, self._size,
                           array.dtype, array.shape)
        return self._enqueue("ag", array, 0, handle, name)


def _xla_coordinator(ps) -> Optional[str]:
    ep = os.environ.get("HVD_TPU_XLA_COORD")
    if ep:
        return ep
    if ps.coord_endpoint:
        # Default offset must clear the engine data ports, which occupy
        # port_base+1 .. port_base+local_size (runner/hosts.py); 500 matches
        # the launcher's own xla_coord allocation (hosts.py plan()).
        host, port = ps.coord_endpoint.rsplit(":", 1)
        offset = int(os.environ.get("HVD_TPU_XLA_COORD_PORT_OFFSET", "500"))
        return f"{host}:{int(port) + offset}"
    return None


def initialize(ps) -> Optional[XlaDataPlane]:
    """Connect jax.distributed across the job and build the process mesh.
    Returns None (with a warning) when the fabric cannot be initialized —
    callers fall back to the TCP engine."""
    global _plane
    with _lock:
        if _plane is not None:
            if _plane:
                # Re-init in the same process: the engine's tick counter
                # and applied-parameter history restarted, so tick-keyed
                # fusion thresholds / compression modes memoized in the
                # previous lifetime are stale (and, being per-rank
                # wall-time artifacts, would split ranks into different
                # bucket plans).  Residuals reset with the engine's.
                _plane._tick_thresholds.clear()
                _plane._tick_comp.clear()
                _plane._residuals.clear()
            return _plane or None
        try:
            import jax
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)

            from horovod_tpu.common.config import Config

            if ps.size > 1:
                coord = _xla_coordinator(ps)
                if coord is None:
                    raise RuntimeError(
                        "no XLA coordinator endpoint (HVD_TPU_XLA_COORD)")
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=ps.size, process_id=ps.rank)
            devices = jax.devices()
            # A (process, local-chip) 2-D mesh: each process may own
            # several local devices (the reference ran several GPUs from
            # one process, test_tensorflow.py:189); with one device per
            # process this reduces to the 1-D per-process mesh.
            by_proc = {}
            for d in devices:
                by_proc.setdefault(d.process_index, []).append(d)
            if len(by_proc) != ps.size:
                raise RuntimeError(
                    f"{len(by_proc)} processes visible to JAX, expected "
                    f"{ps.size}")
            counts = {len(v) for v in by_proc.values()}
            if len(counts) != 1:
                raise RuntimeError(
                    f"uneven device counts per process: "
                    f"{ {k: len(v) for k, v in by_proc.items()} }")
            chips = counts.pop()
            mesh_devices = np.array(
                [sorted(by_proc[i], key=lambda d: d.id)
                 for i in sorted(by_proc)])
            mesh = Mesh(mesh_devices, ("hvd_proc", "hvd_local"))
            plane = XlaDataPlane(
                mesh,
                NamedSharding(mesh, P("hvd_proc", "hvd_local")),
                NamedSharding(mesh, P()),
                ps.rank, ps.size,
                Config.from_env().fusion_threshold,
                spec_proc_only=NamedSharding(mesh, P("hvd_proc")),
                local_chips=chips)
            _plane = plane
            return plane
        except Exception as exc:  # fall back to the TCP engine
            import warnings

            warnings.warn(
                f"XLA data plane unavailable ({exc}); eager collectives "
                "will use the TCP engine.")
            _plane = False
            return None


def reset() -> None:
    """Testing hook: forget the cached plane (jax.distributed state is
    process-wide and cannot be re-initialized; use fresh processes)."""
    global _plane
    with _lock:
        _plane = None
