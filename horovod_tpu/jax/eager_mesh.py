"""XLA data plane for eager collectives.

The north-star TPU mapping of the reference's NCCL data plane
(/root/reference/horovod/common/operations.cc:861-1100): eagerly issued
tensors execute as *compiled XLA collectives* over the accelerator fabric
(ICI on a pod; gloo/gRPC on CPU test meshes) instead of the engine's TCP
ring.  Enabled with ``HVD_TPU_XLA_DATA_PLANE=1``; the TCP engine remains
the control plane (negotiation, allgather, error paths) and the fallback.

Design: `jax.distributed` connects all processes (its coordinator endpoint
comes from the launcher, `HVD_TPU_XLA_COORD`); one device per process forms
a process-spanning mesh.  An eager allreduce turns the per-process value
into a global array sharded over the process axis and runs a jitted
``sum(axis=0)`` replicated out — XLA compiles that to an all-reduce over
the fabric.  Executables cache by (op, shape, dtype), the analogue of the
reference's NCCL-communicator cache (operations.cc:212).  Dispatch is
async (JAX returns futures); `XlaHandle.wait()` materializes.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_plane = None  # initialized XlaDataPlane, or False if init failed/disabled


class XlaHandle:
    """Duck-type of horovod_tpu.common.Handle for XLA-plane collectives.

    Dispatch is deferred: the op sits in the plane's pending list until any
    handle is polled/waited, at which point everything pending flushes in
    **name order** — so ranks whose enqueue order differs (e.g. torch
    backward hooks firing in different orders) still execute the same
    collective sequence, the property the engine gets from name-based
    negotiation."""

    def __init__(self, plane, name: str, out: Optional[np.ndarray],
                 average: bool, size: int, dtype):
        self._plane = plane
        self._name = name
        self._result = None  # jax.Array once flushed
        self._out = out
        self._average = average
        self._size = size
        self._dtype = dtype
        self._finished = False

    def done(self) -> bool:
        if self._finished:
            return True
        self._plane.flush()
        return self._result.is_ready()

    def wait(self) -> np.ndarray:
        if self._finished:
            raise ValueError(f"handle for '{self._name}' already waited on")
        self._finished = True
        self._plane.flush()
        host = np.asarray(self._result)
        if self._average:
            if np.issubdtype(self._dtype, np.integer):
                host = (host / self._size).astype(self._dtype)
            else:
                host = (host / np.asarray(self._size, host.dtype)).astype(
                    self._dtype)
        else:
            host = host.astype(self._dtype, copy=False)
        if self._out is not None:
            np.copyto(self._out, host.reshape(self._out.shape))
            return self._out
        return host


class XlaDataPlane:
    def __init__(self, mesh, spec_sharded, spec_replicated, rank, size):
        self._mesh = mesh
        self._in_sharding = spec_sharded
        self._out_sharding = spec_replicated
        self._rank = rank
        self._size = size
        self._fns = {}
        self._mu = threading.Lock()  # guards _fns and _pending
        self._pending = []  # (name, op, payload, root, handle)

    def _jit_for(self, op: str, shape, dtype, root: int = 0):
        import jax

        key = (op, shape, np.dtype(dtype).str, root)
        fn = self._fns.get(key)
        if fn is None:
            if op == "allreduce":
                fn = jax.jit(lambda a: a.sum(axis=0),
                             out_shardings=self._out_sharding)
            else:  # broadcast: every process receives root's block
                fn = jax.jit(lambda a: a[root],
                             out_shardings=self._out_sharding)
            self._fns[key] = fn
        return fn

    def _global_array(self, array: np.ndarray):
        import jax

        local = array[np.newaxis]  # (1, ...) — this process's block
        return jax.make_array_from_process_local_data(
            self._in_sharding, local, (self._size,) + array.shape)

    def flush(self) -> None:
        """Dispatch every pending op, sorted by collective name (the
        cross-rank matching key).  Dispatches go out back-to-back, so XLA
        pipelines the transfers."""
        with self._mu:
            pending, self._pending = self._pending, []
            pending.sort(key=lambda item: item[0])
            for name, op, payload, root, handle in pending:
                arr = self._global_array(payload)
                fn = self._jit_for(op, payload.shape, payload.dtype, root)
                handle._result = fn(arr)

    def allreduce_async(self, array: np.ndarray, average: bool,
                        out: Optional[np.ndarray], name: str) -> XlaHandle:
        dtype = array.dtype
        # bf16/f16 sum in f32, like the engine's staging (engine.cc); bf16
        # from ml_dtypes reports kind "V".
        compute = array.astype(np.float32) if dtype.itemsize == 2 \
            and dtype.kind in ("f", "V") else array
        handle = XlaHandle(self, name, out, average, self._size, dtype)
        with self._mu:
            self._pending.append((name, "allreduce", compute, 0, handle))
        return handle

    def broadcast_async(self, array: np.ndarray, root_rank: int,
                        out: Optional[np.ndarray], name: str) -> XlaHandle:
        handle = XlaHandle(self, name, out, False, self._size, array.dtype)
        with self._mu:
            self._pending.append(
                (name, "broadcast", array, root_rank, handle))
        return handle


def _xla_coordinator(ps) -> Optional[str]:
    ep = os.environ.get("HVD_TPU_XLA_COORD")
    if ep:
        return ep
    if ps.coord_endpoint:
        # Derive a port clear of both defaults: engine coordinator 58930
        # and data 58931 (basics.py pod-metadata resolution).
        host, port = ps.coord_endpoint.rsplit(":", 1)
        offset = int(os.environ.get("HVD_TPU_XLA_COORD_PORT_OFFSET", "3"))
        return f"{host}:{int(port) + offset}"
    return None


def initialize(ps) -> Optional[XlaDataPlane]:
    """Connect jax.distributed across the job and build the process mesh.
    Returns None (with a warning) when the fabric cannot be initialized —
    callers fall back to the TCP engine."""
    global _plane
    with _lock:
        if _plane is not None:
            return _plane or None
        try:
            import jax
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)

            if ps.size > 1:
                coord = _xla_coordinator(ps)
                if coord is None:
                    raise RuntimeError(
                        "no XLA coordinator endpoint (HVD_TPU_XLA_COORD)")
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=ps.size, process_id=ps.rank)
            devices = jax.devices()
            # One device per process, ordered by rank.
            by_proc = {}
            for d in devices:
                by_proc.setdefault(d.process_index, d)
            if len(by_proc) != ps.size:
                raise RuntimeError(
                    f"{len(by_proc)} processes visible to JAX, expected "
                    f"{ps.size}")
            mesh_devices = [by_proc[i] for i in sorted(by_proc)]
            mesh = Mesh(np.array(mesh_devices), ("hvd_proc",))
            plane = XlaDataPlane(
                mesh,
                NamedSharding(mesh, P("hvd_proc")),
                NamedSharding(mesh, P()),
                ps.rank, ps.size)
            _plane = plane
            return plane
        except Exception as exc:  # fall back to the TCP engine
            import warnings

            warnings.warn(
                f"XLA data plane unavailable ({exc}); eager collectives "
                "will use the TCP engine.")
            _plane = False
            return None


def reset() -> None:
    """Testing hook: forget the cached plane (jax.distributed state is
    process-wide and cannot be re-initialized; use fresh processes)."""
    global _plane
    with _lock:
        _plane = None
