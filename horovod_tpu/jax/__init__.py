"""JAX binding: the compiled TPU data path.

The role of the framework bindings in the reference (e.g.
/root/reference/horovod/tensorflow/__init__.py — `allreduce`,
`DistributedOptimizer`, variable broadcast) re-designed TPU-first:

* **Inside `jit` / `shard_map`** (pass ``axis_name=``): `allreduce` lowers to
  `lax.psum`/`lax.pmean`, `allgather` to `lax.all_gather(tiled)`, and
  `broadcast` to a masked `psum` — all compiled by XLA into async collectives
  over ICI.  Fusion, scheduling, and compute/comm overlap are XLA's job here;
  this path replaces the reference's background-engine hot loop
  (/root/reference/horovod/common/operations.cc:696-1229) for compiled
  programs.
* **Outside `jit`** (no ``axis_name``): values round-trip through the C++
  collective engine (negotiation, fusion, ring transport over DCN), the same
  substrate the numpy/torch APIs use.  This serves eager setup work —
  parameter broadcast, metric averaging — exactly the role the engine plays
  for eagerly-issued tensors in the reference.

`DistributedOptimizer` wraps any `optax.GradientTransformation` and averages
gradients across workers before the update, the direct analogue of the
reference's optimizer wrappers
(/root/reference/horovod/tensorflow/__init__.py:134-208).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import horovod_tpu.common as _common
from horovod_tpu.utils.jax_compat import axis_size as _axis_size
from horovod_tpu.common import (  # noqa: F401  (re-exported process API)
    HorovodInternalError,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "mpi_threads_supported", "HorovodInternalError",
    "allreduce", "allgather", "broadcast", "allreduce_pytree",
    "broadcast_parameters", "broadcast_optimizer_state",
    "DistributedOptimizer",
]


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def _eager_to_host(tensor) -> np.ndarray:
    # jax bfloat16 arrays convert to ml_dtypes.bfloat16 numpy arrays, which
    # the engine's dtype table understands (common/dtypes.py).  _as_contig
    # preserves 0-d shapes (np.ascontiguousarray would promote to (1,)).
    return _common._as_contig(np.asarray(tensor))


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              axis_name: Optional[str] = None):
    """Sum (or mean) of per-worker contributions of ``tensor``.

    With ``axis_name`` inside a mapped computation this is a compiled XLA
    collective; otherwise an eager engine collective (requires `hvd.init()`).

    The compiled path is *varying-aware* (and therefore requires shard_map's
    default ``check_vma=True``): JAX's grad transpose already inserts the
    cross-shard `psum` when differentiating w.r.t. replicated parameters, so
    gradients reach the caller as the cross-worker **sum** with the mapped
    axis no longer in their varying set.  For such already-reduced values
    allreduce is sum→identity / mean→divide-by-N; for still-varying values it
    is a real `psum`/`pmean`.  Either way the result is the reduction of the
    per-shard contributions — allreduce is idempotent, like the engine path.
    """
    if axis_name is not None:
        # One mesh axis or several (e.g. ("dp", "sp") for a 2-D mesh).
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        vma = getattr(getattr(tensor, "aval", None), "vma", None)
        # Axes absent from the varying set are already reduced (e.g. by the
        # grad transpose's automatic psum): the value is the cross-worker
        # sum over them, so only psum the still-varying axes and divide by
        # the full participant count when averaging.
        present = axes if vma is None else tuple(a for a in axes if a in vma)
        out = lax.psum(tensor, present) if present else tensor
        if average:
            denom = 1
            for a in axes:
                denom *= _axis_size(a)
            out = out / denom
        return out
    if _is_tracer(tensor):
        raise ValueError(
            "allreduce of a traced value requires axis_name= (the mapped "
            "mesh axis); the eager engine path cannot run under jit.")
    out = _common.allreduce(_eager_to_host(tensor), average=average, name=name)
    return jnp.asarray(out)


def allgather(tensor, name: Optional[str] = None,
              axis_name: Optional[str] = None):
    """Concatenate ``tensor`` from all workers along dimension 0.

    Workers may differ in dimension 0 only on the eager path (the engine
    negotiates per-rank sizes as the reference does,
    /root/reference/horovod/common/operations.cc:778-838); inside a mapped
    computation XLA requires equal shapes per shard.
    """
    if axis_name is not None:
        return lax.all_gather(tensor, axis_name, axis=0, tiled=True)
    if _is_tracer(tensor):
        raise ValueError(
            "allgather of a traced value requires axis_name= (the mapped "
            "mesh axis); the eager engine path cannot run under jit.")
    return jnp.asarray(_common.allgather(_eager_to_host(tensor), name=name))


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              axis_name: Optional[str] = None):
    """Every worker receives ``root_rank``'s value of ``tensor``."""
    if axis_name is not None:
        idx = lax.axis_index(axis_name)
        cast = tensor.dtype == jnp.bool_ if hasattr(tensor, "dtype") else False
        x = jnp.asarray(tensor)
        if cast:
            x = x.astype(jnp.uint8)
        picked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
        out = lax.psum(picked, axis_name)
        return out.astype(jnp.bool_) if cast else out
    if _is_tracer(tensor):
        raise ValueError(
            "broadcast of a traced value requires axis_name= (the mapped "
            "mesh axis); the eager engine path cannot run under jit.")
    out = _common.broadcast(_eager_to_host(tensor), root_rank=root_rank,
                            name=name)
    return jnp.asarray(out)


def _leaf_paths(tree):
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return leaves_with_paths


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                    for p in path)


def allreduce_pytree(tree, average: bool = True,
                     name_prefix: str = "allreduce",
                     axis_name: Optional[str] = None):
    """Allreduce every array leaf of a pytree (names derived from tree paths
    so all ranks agree on collective identity, as the reference derives op
    names from tensor names, /root/reference/horovod/tensorflow/mpi_ops.py:65)."""
    def one(path, leaf):
        return allreduce(leaf, average=average,
                         name=f"{name_prefix}.{_path_str(path)}",
                         axis_name=axis_name)
    return jax.tree_util.tree_map_with_path(one, tree)


def _bcast_leaf(path, leaf, root_rank: int, name_prefix: str):
    name = f"{name_prefix}.{_path_str(path)}"
    if isinstance(leaf, (jax.Array, np.ndarray)):
        out = _common.broadcast(_eager_to_host(leaf), root_rank, name=name)
        if isinstance(leaf, np.ndarray):
            return out
        return jnp.asarray(out)
    if isinstance(leaf, (bool, int, float)):
        # Scalars round-trip through tensors, as the reference's
        # broadcast_optimizer_state does for hyperparameters
        # (/root/reference/horovod/torch/__init__.py:161-228).
        out = _common.broadcast(np.asarray(leaf), root_rank, name=name)
        return type(leaf)(out.item())
    return leaf


def broadcast_parameters(params, root_rank: int = 0,
                         name_prefix: str = "broadcast_parameters"):
    """Replicate rank ``root_rank``'s parameter pytree on every worker.

    The rank-0 state-replication step of the reference
    (/root/reference/horovod/torch/__init__.py:127-158,
    horovod/tensorflow/__init__.py:89-131), for arbitrary JAX pytrees.
    Eager: call once after `hvd.init()` and before training.
    """
    _common._check_initialized(_common._load_lib())
    return jax.tree_util.tree_map_with_path(
        lambda p, l: _bcast_leaf(p, l, root_rank, name_prefix), params)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Replicate rank ``root_rank``'s optax optimizer state (a pytree that
    may include scalar hyperparameters) on every worker."""
    return broadcast_parameters(opt_state, root_rank,
                                name_prefix="broadcast_optimizer_state")


def DistributedOptimizer(optimizer, axis_name: Optional[str] = None,
                         average: bool = True,
                         name_prefix: str = "DistributedOptimizer"):
    """Wrap an `optax.GradientTransformation` so updates see the cross-worker
    (mean) gradient.

    Counterpart of the reference's optimizer wrappers
    (/root/reference/horovod/tensorflow/__init__.py:134-208,
    horovod/torch/__init__.py:64-124).  Inside `shard_map` pass the mesh
    ``axis_name``: the gradient average compiles to one XLA `psum` per leaf
    which XLA fuses and overlaps with the backward pass — the compiled
    equivalent of the reference's tensor fusion + backprop overlap.  Without
    ``axis_name`` gradients are averaged eagerly through the engine.
    """
    import optax

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(updates, state, params=None, **extra):
        reduced = allreduce_pytree(updates, average=average,
                                   name_prefix=name_prefix,
                                   axis_name=axis_name)
        return optimizer.update(reduced, state, params, **extra)

    return optax.GradientTransformation(init_fn, update_fn)
