"""Build the native collective engine (libhvdtpu.so).

Counterpart of the reference's setup.py extension build
(/root/reference/setup.py:31-34,210-425), radically simplified: no MPI/CUDA/
NCCL feature probing is needed because the engine's only system dependencies
are POSIX sockets and pthreads.  The library is compiled on first import and
cached next to the sources; rebuilt when any source is newer than the binary.
"""

from __future__ import annotations

import os
import subprocess
import tempfile

_CC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cc")
_SOURCES = ["net.cc", "wire.cc", "timeline.cc", "engine.cc", "c_api.cc"]
_LIB_NAME = "libhvdtpu.so"


def lib_path() -> str:
    return os.path.join(_CC_DIR, _LIB_NAME)


def needs_build() -> bool:
    lib = lib_path()
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    for fname in os.listdir(_CC_DIR):
        if fname.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(_CC_DIR, fname)) > lib_mtime:
                return True
    return False


def build(verbose: bool = False) -> str:
    """Compile the engine; returns the .so path.  Raises on failure."""
    lib = lib_path()
    if not needs_build():
        return lib
    cxx = os.environ.get("CXX", "g++")
    srcs = [os.path.join(_CC_DIR, s) for s in _SOURCES]
    # Build into a temp file then atomically rename, so concurrent test
    # processes racing to build don't load a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_CC_DIR)
    os.close(fd)
    cmd = [cxx, "-std=c++17", "-O2", "-g", "-fPIC", "-shared", "-pthread",
           "-Wall", "-Wextra", "-Wno-unused-parameter",
           "-o", tmp] + srcs
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"failed to build {_LIB_NAME}:\n{proc.stderr}")
        os.replace(tmp, lib)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    if verbose:
        print(f"[horovod_tpu] built {lib}")
    return lib


if __name__ == "__main__":
    build(verbose=True)
