"""Build the native collective engine (libhvdtpu.so).

Counterpart of the reference's setup.py extension build
(/root/reference/setup.py:31-34,210-425), radically simplified: no MPI/CUDA/
NCCL feature probing is needed because the engine's only system dependencies
are POSIX sockets and pthreads.  The library is compiled on first import and
cached next to the sources; rebuilt when any source is newer than the binary.

Sanitized builds (docs/contributing.md#sanitized-engine-builds):
``HVD_TPU_SANITIZE=thread|address|undefined`` compiles the engine with the
matching ``-fsanitize=`` runtime into its own ``libhvdtpu.<mode>.so`` next
to the normal binary, so switching modes never invalidates the regular
cached build.  Loading a sanitized engine into an uninstrumented python
needs the sanitizer runtime preloaded — ``sanitizer_preload()`` returns
the ``LD_PRELOAD`` path (the slow-tier TSan test in tests/test_sanitize.py
wires this for its rank subprocesses).
"""

from __future__ import annotations

import functools
import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import List, Optional

_CC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cc")
_SOURCES = ["net.cc", "transport.cc", "wire.cc", "timeline.cc", "autotune.cc", "flight.cc",
            "engine.cc", "simscale.cc", "c_api.cc"]
_LIB_NAME = "libhvdtpu.so"

# -O3 + native SIMD for the AccumulateSum / half-conversion hot loops.
# -march=native is safe *only* because the build stamp below keys the
# cached .so on the host's CPU feature set: a package directory shared
# over NFS or baked into an image rebuilds on a host whose ISA differs
# instead of SIGILL-ing on unsupported instructions.
_FLAGS = ["-std=c++17", "-O3", "-march=native", "-g", "-fPIC", "-shared",
          "-pthread", "-Wall", "-Wextra", "-Wno-unused-parameter"]

# Sanitizer modes -> (compile flags, runtime to preload into
# uninstrumented hosts).  ONE table so a future mode cannot be accepted
# by the build but unknown to the preload resolver (or vice versa).
# Flags swap in for the -O3/-march pair (-O1 + frame pointers keep
# reports readable and the instrumented hot loops tolerable; correctness
# tools don't want vectorized shuffles anyway).
_SANITIZERS = {
    "thread": (["-fsanitize=thread"], "libtsan.so"),
    "address": (["-fsanitize=address"], "libasan.so"),
    "undefined": (["-fsanitize=undefined", "-fno-sanitize-recover=all"],
                  "libubsan.so"),
}


def sanitize_mode() -> str:
    """The validated ``HVD_TPU_SANITIZE`` mode ('' = normal build)."""
    mode = (os.environ.get("HVD_TPU_SANITIZE") or "").strip().lower()
    _check_mode(mode)
    return mode


def _check_mode(mode: str) -> None:
    if mode and mode not in _SANITIZERS:
        raise ValueError(
            f"HVD_TPU_SANITIZE: unknown sanitizer {mode!r} "
            f"(want {', '.join(sorted(_SANITIZERS))})")


def _flags(mode: str) -> List[str]:
    if not mode:
        return list(_FLAGS)
    base = [f for f in _FLAGS if f not in ("-O3", "-march=native")]
    return base + ["-O1", "-fno-omit-frame-pointer"] + _SANITIZERS[mode][0]


def lib_path(mode: Optional[str] = None) -> str:
    if mode is None:
        mode = sanitize_mode()
    name = _LIB_NAME if not mode else f"libhvdtpu.{mode}.so"
    return os.path.join(_CC_DIR, name)


def sanitizer_preload(mode: Optional[str] = None) -> str:
    """Path of the sanitizer runtime to LD_PRELOAD when dlopen-ing a
    sanitized engine from an uninstrumented python ('' for normal
    builds, or when the compiler can't name it).  Raises ``ValueError``
    on an unknown mode, like :func:`sanitize_mode`."""
    if mode is None:
        mode = sanitize_mode()
    if not mode:
        return ""
    _check_mode(mode)
    return _resolve_preload(mode)


@functools.lru_cache(maxsize=None)
def _resolve_preload(mode: str) -> str:
    """One compiler subprocess per mode per process: the launcher calls
    sanitizer_preload once per rank, and the answer never changes."""
    cxx = os.environ.get("CXX", "g++")
    try:
        out = subprocess.run(
            [cxx, f"-print-file-name={_SANITIZERS[mode][1]}"],
            capture_output=True, text=True, timeout=30).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        return ""
    # An unresolved -print-file-name echoes the bare name back.
    if not out or os.sep not in out:
        return ""
    real = os.path.realpath(out)
    return real if os.path.exists(real) else ""


def _stamp_path(mode: str = "") -> str:
    suffix = f".{mode}" if mode else ""
    return os.path.join(_CC_DIR, f".buildstamp{suffix}")


def _build_stamp(mode: str = "") -> str:
    """Fingerprint of everything that must invalidate the cached binary
    besides source mtimes: the compile flags and the host CPU's ISA."""
    cpu = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    cpu = line
                    break
    except OSError:
        pass
    payload = " ".join(_flags(mode)) + " -lrt" + "|" + cpu
    return hashlib.sha256(payload.encode()).hexdigest()


def needs_build(mode: Optional[str] = None) -> bool:
    if mode is None:
        mode = sanitize_mode()
    lib = lib_path(mode)
    if not os.path.exists(lib):
        return True
    try:
        with open(_stamp_path(mode)) as f:
            if f.read().strip() != _build_stamp(mode):
                return True
    except OSError:
        return True
    lib_mtime = os.path.getmtime(lib)
    for fname in os.listdir(_CC_DIR):
        if fname.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(_CC_DIR, fname)) > lib_mtime:
                return True
    return False


def _sweep_stale_tmp() -> None:
    """Remove build droppings an earlier interrupted build left next to
    the sources: tmp*.so from the pre-temp-dir scheme (SIGKILL — e.g. the
    launcher's kill cascade — mid-compile leaked the mkstemp file), and
    stage_*.so.part from a kill during the staging copy.  Staging files
    are age-gated: a young one may belong to a CONCURRENT builder
    mid-copy and must not be unlinked from under it."""
    import time

    try:
        for fname in os.listdir(_CC_DIR):
            path = os.path.join(_CC_DIR, fname)
            stale = fname.startswith("tmp") and (
                fname.endswith(".so") or fname.endswith(".so.part"))
            if fname.startswith("stage_") and fname.endswith(".so.part"):
                try:
                    stale = time.time() - os.path.getmtime(path) > 300
                except OSError:
                    stale = False
            if stale:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    except OSError:
        pass


def build(verbose: bool = False) -> str:
    """Compile the engine; returns the .so path.  Raises on failure.
    ``HVD_TPU_SANITIZE`` selects a sanitized variant (own lib name, own
    stamp — the normal cached build is never invalidated by it)."""
    mode = sanitize_mode()
    lib = lib_path(mode)
    if not needs_build(mode):
        return lib
    _sweep_stale_tmp()
    cxx = os.environ.get("CXX", "g++")
    srcs = [os.path.join(_CC_DIR, s) for s in _SOURCES]
    # Compile in a throwaway temp DIRECTORY (system tmp, not the package
    # tree): a process killed mid-compile — the common leak source was the
    # launcher's kill cascade landing during a ~10 s rebuild — can no
    # longer strand tmp*.so files next to the sources.  The finished
    # binary is then staged next to the target and atomically renamed, so
    # concurrent test processes racing to build don't load a half-written
    # .so; the staging window is a few ms of copy, not the whole compile.
    tmpdir = tempfile.mkdtemp(prefix="hvdtpu_build_")
    stage = None
    try:
        out = os.path.join(tmpdir, os.path.basename(lib))
        # -lrt after the sources: shm_open/shm_unlink live in librt on
        # glibc < 2.34 (newer glibc keeps them in libc and the flag is a
        # harmless no-op).
        cmd = [cxx] + _flags(mode) + ["-o", out] + srcs + ["-lrt"]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"failed to build {os.path.basename(lib)}:\n{proc.stderr}")
        # prefix "stage_", NOT the mkstemp default "tmp": _sweep_stale_tmp
        # matches tmp* and must never unlink a CONCURRENT builder's live
        # staging file mid-copy.
        fd, stage = tempfile.mkstemp(prefix="stage_", suffix=".so.part",
                                     dir=_CC_DIR)
        os.close(fd)
        shutil.copy(out, stage)  # tmpdir may be another filesystem
        os.replace(stage, lib)
        stage = None
        with open(_stamp_path(mode), "w") as f:
            f.write(_build_stamp(mode))
    finally:
        if stage is not None and os.path.exists(stage):
            os.unlink(stage)
        shutil.rmtree(tmpdir, ignore_errors=True)
    if verbose:
        print(f"[horovod_tpu] built {lib}")
    return lib


if __name__ == "__main__":
    build(verbose=True)
