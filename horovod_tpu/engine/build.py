"""Build the native collective engine (libhvdtpu.so).

Counterpart of the reference's setup.py extension build
(/root/reference/setup.py:31-34,210-425), radically simplified: no MPI/CUDA/
NCCL feature probing is needed because the engine's only system dependencies
are POSIX sockets and pthreads.  The library is compiled on first import and
cached next to the sources; rebuilt when any source is newer than the binary.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tempfile

_CC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cc")
_SOURCES = ["net.cc", "wire.cc", "timeline.cc", "autotune.cc", "flight.cc",
            "engine.cc", "c_api.cc"]
_LIB_NAME = "libhvdtpu.so"

# -O3 + native SIMD for the AccumulateSum / half-conversion hot loops.
# -march=native is safe *only* because the build stamp below keys the
# cached .so on the host's CPU feature set: a package directory shared
# over NFS or baked into an image rebuilds on a host whose ISA differs
# instead of SIGILL-ing on unsupported instructions.
_FLAGS = ["-std=c++17", "-O3", "-march=native", "-g", "-fPIC", "-shared",
          "-pthread", "-Wall", "-Wextra", "-Wno-unused-parameter"]


def lib_path() -> str:
    return os.path.join(_CC_DIR, _LIB_NAME)


def _stamp_path() -> str:
    return os.path.join(_CC_DIR, ".buildstamp")


def _build_stamp() -> str:
    """Fingerprint of everything that must invalidate the cached binary
    besides source mtimes: the compile flags and the host CPU's ISA."""
    cpu = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    cpu = line
                    break
    except OSError:
        pass
    payload = " ".join(_FLAGS) + "|" + cpu
    return hashlib.sha256(payload.encode()).hexdigest()


def needs_build() -> bool:
    lib = lib_path()
    if not os.path.exists(lib):
        return True
    try:
        with open(_stamp_path()) as f:
            if f.read().strip() != _build_stamp():
                return True
    except OSError:
        return True
    lib_mtime = os.path.getmtime(lib)
    for fname in os.listdir(_CC_DIR):
        if fname.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(_CC_DIR, fname)) > lib_mtime:
                return True
    return False


def _sweep_stale_tmp() -> None:
    """Remove build droppings an earlier interrupted build left next to
    the sources: tmp*.so from the pre-temp-dir scheme (SIGKILL — e.g. the
    launcher's kill cascade — mid-compile leaked the mkstemp file), and
    stage_*.so.part from a kill during the staging copy.  Staging files
    are age-gated: a young one may belong to a CONCURRENT builder
    mid-copy and must not be unlinked from under it."""
    import time

    try:
        for fname in os.listdir(_CC_DIR):
            path = os.path.join(_CC_DIR, fname)
            stale = fname.startswith("tmp") and (
                fname.endswith(".so") or fname.endswith(".so.part"))
            if fname.startswith("stage_") and fname.endswith(".so.part"):
                try:
                    stale = time.time() - os.path.getmtime(path) > 300
                except OSError:
                    stale = False
            if stale:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    except OSError:
        pass


def build(verbose: bool = False) -> str:
    """Compile the engine; returns the .so path.  Raises on failure."""
    lib = lib_path()
    if not needs_build():
        return lib
    _sweep_stale_tmp()
    cxx = os.environ.get("CXX", "g++")
    srcs = [os.path.join(_CC_DIR, s) for s in _SOURCES]
    # Compile in a throwaway temp DIRECTORY (system tmp, not the package
    # tree): a process killed mid-compile — the common leak source was the
    # launcher's kill cascade landing during a ~10 s rebuild — can no
    # longer strand tmp*.so files next to the sources.  The finished
    # binary is then staged next to the target and atomically renamed, so
    # concurrent test processes racing to build don't load a half-written
    # .so; the staging window is a few ms of copy, not the whole compile.
    tmpdir = tempfile.mkdtemp(prefix="hvdtpu_build_")
    stage = None
    try:
        out = os.path.join(tmpdir, _LIB_NAME)
        cmd = [cxx] + _FLAGS + ["-o", out] + srcs
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"failed to build {_LIB_NAME}:\n{proc.stderr}")
        # prefix "stage_", NOT the mkstemp default "tmp": _sweep_stale_tmp
        # matches tmp* and must never unlink a CONCURRENT builder's live
        # staging file mid-copy.
        fd, stage = tempfile.mkstemp(prefix="stage_", suffix=".so.part",
                                     dir=_CC_DIR)
        os.close(fd)
        shutil.copy(out, stage)  # tmpdir may be another filesystem
        os.replace(stage, lib)
        stage = None
        with open(_stamp_path(), "w") as f:
            f.write(_build_stamp())
    finally:
        if stage is not None and os.path.exists(stage):
            os.unlink(stage)
        shutil.rmtree(tmpdir, ignore_errors=True)
    if verbose:
        print(f"[horovod_tpu] built {lib}")
    return lib


if __name__ == "__main__":
    build(verbose=True)
