// Flight recorder (postmortem plane, docs/troubleshooting.md#reading-a-
// postmortem): a fixed-size, always-on ring of recent control-plane events
// per rank, in the spirit of the NCCL/PyTorch flight recorder.  The engine
// records enqueue / announce / cache-hit / execute / tick / stall / abort /
// reshape / tune transitions with epoch-anchored timestamps and interned
// tensor names; on every typed abort the Python side drains the ring into
// HVD_TPU_POSTMORTEM_DIR/rank-N.json, so a crashed or hung job leaves a
// self-explaining record of what each rank was doing in its final seconds.
//
// Cost discipline: recording is one short mutex hold plus an intern-map
// lookup — a handful of control-plane events per collective, against the
// microseconds a negotiation tick costs (the <2% steady-state overhead
// budget the acceptance bench pins).  HVD_TPU_FLIGHT_EVENTS sizes the ring
// (default 512); 0 disables recording entirely.
#pragma once

#include <chrono>
#include <cstdint>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

// Event codes.  Keep in sync with horovod_tpu/common/postmortem.py (the
// Python side parses the *names* from Dump(), so new codes only need a
// name here).
enum FlightEventType : uint8_t {
  FL_ENQUEUE = 0,    // collective submitted to the engine (arg: handle)
  FL_ANNOUNCE = 1,   // full string request drained to the coordinator
  FL_CACHE_HIT = 2,  // repeat announced as a cache bit (arg: slot)
  FL_EXECUTE = 3,    // response executed (arg: fused tensor count)
  FL_ERROR = 4,      // response carried a typed error
  FL_TICK = 5,       // a tick that moved work closed (arg: tick index)
  FL_STALL = 6,      // rank-0 stall sweep warned (arg: stalled seconds)
  FL_ABORT = 7,      // coordinated abort latched (arg: status code)
  FL_RESHAPE = 8,    // elastic membership adopted (arg: new epoch)
  FL_TUNE = 9,       // lockstep parameter broadcast applied (arg: fusion)
  FL_COMPRESS = 10,  // wire-compression mode armed / changed (arg: mode)
  FL_TOPOLOGY = 11,  // two-level cross-node algorithm switched
                     // (arg: 1 = tree, 0 = ring; name = first bucket name)
  FL_STEADY = 12,    // decentralized steady state entered/exited
                     // (name: "enter" with arg = pattern length, or the
                     // exit reason with arg = the epoch it happened at) —
                     // the record that explains why a postmortem shows
                     // zero coordinator traffic before a hang
  FL_HEARTBEAT_MISS = 13,  // data-plane heartbeat detector flagged a
                           // silent peer (arg: the peer rank; name:
                           // "flag" when first flagged, "report" when
                           // the report frame went up, "local-abort"
                           // when the grace deadline escalated locally)
  FL_ANOMALY = 14,  // online anomaly detector emitted a typed verdict
                    // (name: "slow_link(A-B)" / "straggler(rank)" /
                    // "cache_degraded" / "slow_phase(phase)"; arg: the
                    // verdict-kind index) — the postmortem record that
                    // says WHERE the job was slow before it died
  FL_TRANSPORT = 15,  // shared-memory transport armed for the node-local
                      // ring (name: "shm"; arg: per-direction ring bytes)
  FL_P2P = 16,  // point-to-point transfer executed (docs/pipeline.md;
                // name: the tensor; arg: payload bytes, negative for a
                // receive so one ring entry distinguishes direction)
};

const char* FlightEventName(uint8_t event);

class FlightRecorder {
 public:
  // (Re-)arms the recorder for one engine lifetime: the ring and the
  // intern table restart (old entries carry a dead epoch's timestamps),
  // the cumulative event counter survives — the metrics contract every
  // engine counter follows (engine.h StallEvents).
  void Initialize(int64_t capacity,
                  std::chrono::steady_clock::time_point epoch);
  bool Enabled() const { return enabled_; }
  void Record(uint8_t event, const std::string& name, int64_t arg);
  // Process-cumulative count of recorded events (survives re-init).
  int64_t Events() const;
  // Ring snapshot, oldest first: "seq|ts_us|event|name|arg;..." with the
  // separators sanitized out of tensor names.  Non-destructive — the ring
  // keeps recording; postmortem writers and tests both read it.
  std::string Dump();

 private:
  struct Entry {
    int64_t seq = -1;  // -1 = never written
    int64_t ts_us = 0;
    uint8_t event = 0;
    int32_t name_id = 0;
    int64_t arg = 0;
  };
  int32_t InternLocked(const std::string& name);

  mutable std::mutex mu_;
  bool enabled_ = false;
  int64_t total_ = 0;    // cumulative across engine lifetimes
  int64_t next_seq_ = 0; // per-lifetime ring sequence
  size_t head_ = 0;      // next write slot
  std::vector<Entry> ring_;
  std::vector<std::string> names_;
  std::unordered_map<std::string, int32_t> name_ids_;
  std::chrono::steady_clock::time_point epoch_{};
};

}  // namespace hvdtpu
