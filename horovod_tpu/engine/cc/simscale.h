// Simulated-scale negotiation harness (docs/performance.md
// #control-plane-scaling): run N engine-plane ranks IN ONE PROCESS over
// loopback TCP — each rank a full Engine instance with its own
// background thread and control/data sockets — and drive OP_NOOP
// negotiation cycles from per-rank driver threads.  NOOPs move no data,
// so the measured per-cycle latency is pure control-plane cost: the
// star-vs-tree fan-in and the decentralized steady state's zero-frame
// replay become measurable in CI at hundreds of ranks without hundreds
// of processes.
#pragma once

#include <string>

namespace hvdtpu {

// Runs the harness and returns a one-line JSON report:
//   {"ok":1,"size":N,"tree":0|1,"steady_entered":0|1,
//    "warm_p50_us":..,"warm_p90_us":..,
//    "steady_p50_us":..,"steady_p90_us":..,
//    "steady_frames_delta":..,"steady_cycles":..,
//    "coord_children":..,"negotiated_cycles":..}
// or {"ok":0,"error":"..."} on setup failure.  `ops_per_cycle` OP_NOOP
// collectives are enqueued-then-waited per cycle on every rank; cycle
// latency is measured on rank 0's driver.  `steady_threshold` 0 keeps
// the star/tree negotiating every cycle (the baseline curve);
// `coord_tree` toggles the sub-coordinator tree.  `base_port` seeds the
// loopback endpoints (size + 1 consecutive ports).
std::string SimScaleRun(int size, int local_size, int ops_per_cycle,
                        int warm_cycles, int steady_cycles,
                        long long steady_threshold, int coord_tree,
                        int base_port, double timeout_sec);

}  // namespace hvdtpu
