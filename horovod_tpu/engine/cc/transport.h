// Pluggable data-plane transport seam (docs/performance.md#transport).
//
// Every ring hop in the engine moves bytes through a Channel: a TCP
// socket plus, when the shared-memory transport is armed, a pair of
// SPSC byte rings inside one mmap'd per-node segment.  The TCP fd is
// ALWAYS dialed and kept — it carries the rendezvous token relay, the
// heartbeat wake registry, and PeerClosed probes, and it is the
// fallback when shm cannot arm — so the socket path is simply the
// Channel with no rings attached.  ChannelExchange/ChannelExchangeBi/
// ChannelSendAll/ChannelRecvAll delegate to net.h when no ring is
// present; with rings they hand off fused-bucket bytes by offset with
// no serialization and no syscall per segment, polling with a
// spin-then-yield loop paced off the engine tick (no futex: the reader
// and writer are pinned engine threads that poll every few µs anyway).
//
// Segment lifecycle (crash-proof /dev/shm hygiene): local-rank 0
// unlinks any stale name, creates the segment O_CREAT|O_EXCL, then
// relays an attach token around the node-local ring over the already-
// connected TCP sockets; when the token returns, every local rank has
// the segment mapped and the creator unlinks it IMMEDIATELY, so no
// later abort, typed death, or SIGKILL can leak a /dev/shm entry — the
// kernel reclaims the memory on the last munmap/exit.  Names embed the
// job tag and membership epoch so elastic reshapes and rejoining
// standbys can never attach a stale generation's segment.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <string>

namespace hvdtpu {

// ---------------------------------------------------------------------------
// HVD_TPU_SHM policy knob: off pins every hop to TCP (kill switch,
// bit-identical data path), auto arms shm for the node-local ring when
// the job shape allows it and demotes to TCP otherwise, force fails
// init with a typed error when shm cannot arm.
// ---------------------------------------------------------------------------

enum class ShmMode { kOff = 0, kAuto = 1, kForce = 2 };

// nullptr/""/"auto" -> kAuto; "0"/"off" -> kOff; "1"/"force" -> kForce.
// Unrecognized values -> kAuto (the safe default; lint keeps the doc row
// canonical).
ShmMode ParseShmMode(const char* value);
const char* ShmModeName(ShmMode m);

// ---------------------------------------------------------------------------
// SPSC byte ring living inside the shared segment.  One writer (the
// source local rank) and one reader; head/tail are monotonically
// increasing byte cursors so empty == (head == tail) with no wasted
// slot.  `closed` is the abort wake: either side (or the heartbeat
// monitor) sets it and every blocked drive loop returns false within
// one poll iteration — the shm analogue of ShutdownFd.
// ---------------------------------------------------------------------------

struct ShmRing {
  alignas(64) std::atomic<uint64_t> head;    // bytes produced (writer-owned)
  alignas(64) std::atomic<uint64_t> tail;    // bytes consumed (reader-owned)
  alignas(64) std::atomic<uint32_t> closed;  // abort flag (either side)
  uint32_t capacity;                         // payload bytes (power of two)

  char* Data() { return reinterpret_cast<char*>(this) + sizeof(ShmRing); }
  // Copy up to len bytes in/out without blocking; returns bytes moved
  // (0 when the ring is full/empty).  Release/acquire pairing on the
  // cursors orders the payload copies across processes.
  size_t WriteSome(const void* buf, size_t len);
  size_t ReadSome(void* buf, size_t len);
};

static_assert(sizeof(ShmRing) == 192, "ring header layout is part of the ABI");

// ---------------------------------------------------------------------------
// Per-node segment: header + 2*local_size rings.  Ring (r, dir) is
// written by local rank r: dir 0 flows rightward (read by (r+1) % L as
// its leftward-receive), dir 1 flows leftward (read by (r-1+L) % L).
// ---------------------------------------------------------------------------

// "/hvdtpu_<fnv32(job_tag)>_n<node>_e<epoch>" — job_tag folds in the
// coordinator endpoint (unique per job on a host) and the launcher's
// restart epoch; the membership epoch suffix keeps elastic generations
// apart even if a segment were ever observable across them.
std::string ShmSegmentName(const std::string& job_tag, int node_id,
                           long long epoch);

class ShmSegment {
 public:
  ShmSegment() = default;
  ~ShmSegment() { Unmap(); }
  ShmSegment(const ShmSegment&) = delete;
  ShmSegment& operator=(const ShmSegment&) = delete;

  // Creator side (local rank 0): unlink any stale name, then
  // O_CREAT|O_EXCL + ftruncate + mmap + initialize every ring header.
  bool Create(const std::string& name, int local_size, size_t ring_bytes,
              std::string* err);
  // Worker side: shm_open an existing name and validate its header
  // against this job's shape (magic/version/local_size/ring_bytes).
  bool Attach(const std::string& name, int local_size, size_t ring_bytes,
              std::string* err);
  // Remove the name from /dev/shm (creator calls this the moment the
  // attach token round-trips; teardown calls it again defensively for
  // the create-to-attach window).  Idempotent; safe on non-creators.
  void Unlink();
  // Abort wake: set closed on every ring so any drive loop blocked on a
  // full/empty ring returns within one poll iteration.
  void CloseRings();
  void Unmap();

  bool mapped() const { return base_ != nullptr; }
  bool creator() const { return creator_; }
  const std::string& name() const { return name_; }
  size_t ring_bytes() const { return ring_bytes_; }
  ShmRing* Ring(int src_local_rank, int dir);

 private:
  void* base_ = nullptr;
  size_t bytes_ = 0;
  std::string name_;
  bool creator_ = false;
  bool unlinked_ = false;
  int local_size_ = 0;
  size_t ring_bytes_ = 0;
};

// ---------------------------------------------------------------------------
// Channel: the seam.  fd is always valid once the topology is wired;
// tx/rx point into the node segment only when the shm transport armed
// for this hop.  peer is the global rank at the far end (telemetry and
// chaos-clause key).
// ---------------------------------------------------------------------------

struct Channel {
  int fd = -1;
  ShmRing* tx = nullptr;  // ring this rank writes toward peer
  ShmRing* rx = nullptr;  // ring peer writes toward this rank
  int peer = -1;
  bool shm() const { return tx != nullptr && rx != nullptr; }
};

// Blocking full-buffer ops over a channel; TCP channels delegate to
// SendAll/RecvAll/Exchange/ExchangeBi, shm channels drive the rings
// (and mixed legs drive both nonblockingly in one loop).  All return
// false on peer death, a closed ring, or 30s of zero progress — the
// same contract as the net.h calls they stand in for.  Chaos delay/
// jitter clauses naming the link apply per handoff on the shm path
// (NetFaultDelayPeer); drop/flaky clauses never reach here — init
// refuses to arm shm under them (see Engine::SetupShmTransport).
bool ChannelSendAll(const Channel& ch, const void* buf, size_t len);
bool ChannelRecvAll(const Channel& ch, void* buf, size_t len);
bool ChannelExchange(const Channel& send_ch, const void* sbuf, size_t slen,
                     const Channel& recv_ch, void* rbuf, size_t rlen);
bool ChannelExchangeBi(const Channel& right, const void* send_r,
                       size_t send_r_len, void* recv_r, size_t recv_r_len,
                       const Channel& left, const void* send_l,
                       size_t send_l_len, void* recv_l, size_t recv_l_len);

}  // namespace hvdtpu
