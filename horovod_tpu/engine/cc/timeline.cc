#include "timeline.h"

#include <cstdio>

namespace hvdtpu {

namespace {
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}
}  // namespace

void Timeline::Initialize(const std::string& path, int rank,
                          std::chrono::steady_clock::time_point epoch) {
  if (path.empty()) return;
  std::lock_guard<std::mutex> lk(mu_);
  file_.open(path, std::ios::out | std::ios::trunc);
  if (!file_.is_open()) {
    fprintf(stderr, "[horovod_tpu] WARNING: cannot open timeline file %s\n",
            path.c_str());
    return;
  }
  // Re-init after a shutdown starts a fresh file: forget the previous
  // run's pid rows so every tensor re-emits its process_name metadata.
  tensor_pids_.clear();
  open_labels_.clear();
  last_ts_by_pid_.clear();
  start_ = epoch;
  last_flush_ = std::chrono::steady_clock::now();
  file_ << "[\n";
  // File identity for tools/timeline_merge.py: which rank wrote this
  // trace.  pid 0 is reserved for metadata (tensor pids start at 1).
  file_ << "{\"name\":\"hvd_rank\",\"ph\":\"M\",\"ts\":0,\"pid\":0,"
        << "\"args\":{\"rank\":" << rank << "}},\n";
  file_.flush();
  enabled_ = true;
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

int64_t Timeline::TensorPid(const std::string& name) {
  auto it = tensor_pids_.find(name);
  if (it != tensor_pids_.end()) return it->second;
  int64_t pid = static_cast<int64_t>(tensor_pids_.size()) + 1;
  tensor_pids_[name] = pid;
  // Metadata event labels the pid row with the tensor name.
  int64_t now = NowUs();
  last_ts_by_pid_[pid] = now;
  file_ << "{\"name\":\"process_name\",\"ph\":\"M\",\"ts\":" << now
        << ",\"pid\":" << pid << ",\"args\":{\"name\":\"" << JsonEscape(name)
        << "\"}},\n";
  return pid;
}

void Timeline::WriteEvent(const std::string& name, char phase,
                          const std::string& args,
                          const std::string& category, int64_t ts_us) {
  int64_t pid = TensorPid(name);
  // 'E' events repeat their opener's label (popped from the per-row
  // stack) so every event carries a name.
  std::string label = category;
  if (phase == 'B') {
    open_labels_[name].push_back(category);
  } else if (phase == 'E') {
    auto& stack = open_labels_[name];
    if (!stack.empty()) {
      label = stack.back();
      stack.pop_back();
    }
  }
  int64_t ts = ts_us >= 0 ? ts_us : NowUs();
  int64_t& row_ts = last_ts_by_pid_[pid];
  if (ts < row_ts) ts = row_ts;  // per-row monotonicity clamp
  row_ts = ts;
  file_ << "{\"ph\":\"" << phase << "\",\"ts\":" << ts
        << ",\"pid\":" << pid << ",\"tid\":0"
        << ",\"name\":\"" << JsonEscape(label) << "\"";
  if (!args.empty()) file_ << ",\"args\":{" << args << "}";
  file_ << "},\n";
  auto now = std::chrono::steady_clock::now();
  if (now - last_flush_ > std::chrono::seconds(1)) {
    file_.flush();
    last_flush_ = now;
  }
}

void Timeline::NegotiateStart(const std::string& name, uint8_t op) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'B', "", "NEGOTIATE");
}

void Timeline::NegotiateRankReady(const std::string& name, int rank,
                                  int64_t ts_us) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'i',
             "\"rank\":" + std::to_string(rank), "RANK_READY", ts_us);
}

void Timeline::NegotiateEnd(const std::string& name) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'E', "", "");
}

void Timeline::Start(const std::string& name, const std::string& op_name) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'B', "", op_name);
}

void Timeline::ActivityStart(const std::string& name,
                             const std::string& activity) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'B', "", activity);
}

void Timeline::ActivityEnd(const std::string& name) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'E', "", "");
}

void Timeline::End(const std::string& name, int64_t bytes) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'E', "\"bytes\":" + std::to_string(bytes), "");
}

void Timeline::Instant(const std::string& name, const std::string& label) {
  if (!enabled_) return;
  std::lock_guard<std::mutex> lk(mu_);
  WriteEvent(name, 'i', "", label);
}

void Timeline::WriteClockSync(int64_t offset_us, int64_t rtt_us) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!enabled_) return;
  file_ << "{\"name\":\"hvd_clock_sync\",\"ph\":\"M\",\"ts\":" << NowUs()
        << ",\"pid\":0,\"args\":{\"offset_us\":" << offset_us
        << ",\"rtt_us\":" << rtt_us << "}},\n";
  file_.flush();
}

void Timeline::Flush() {
  std::lock_guard<std::mutex> lk(mu_);
  if (!enabled_) return;
  file_.flush();
}

void Timeline::Shutdown() {
  std::lock_guard<std::mutex> lk(mu_);
  if (!enabled_) return;
  // Chrome's trace parser tolerates the trailing comma / missing "]".
  file_.flush();
  file_.close();
  enabled_ = false;
}

}  // namespace hvdtpu
