// Chrome-tracing timeline writer.  Same event model as the reference's
// Horovod Timeline (/root/reference/horovod/common/timeline.{h,cc}): one
// trace "pid" per tensor name, NEGOTIATE -> op -> activity nesting, JSON
// written incrementally and flushed periodically; load the output in
// chrome://tracing or Perfetto.
#pragma once

#include <chrono>
#include <cstdint>
#include <fstream>
#include <mutex>
#include <string>
#include <unordered_map>

namespace hvdtpu {

class Timeline {
 public:
  void Initialize(const std::string& path);
  bool Enabled() const { return enabled_; }

  void NegotiateStart(const std::string& name, uint8_t op);
  void NegotiateRankReady(const std::string& name, int rank);
  void NegotiateEnd(const std::string& name);
  void Start(const std::string& name, const std::string& op_name);
  void ActivityStart(const std::string& name, const std::string& activity);
  void ActivityEnd(const std::string& name);
  void End(const std::string& name, int64_t bytes);
  void Shutdown();

 private:
  void WriteEvent(const std::string& name, char phase, const std::string& args,
                  const std::string& category);
  int64_t TensorPid(const std::string& name);
  int64_t NowUs() const;

  bool enabled_ = false;
  std::ofstream file_;
  std::mutex mu_;
  std::unordered_map<std::string, int64_t> tensor_pids_;
  std::chrono::steady_clock::time_point start_{};
  std::chrono::steady_clock::time_point last_flush_{};
};

}  // namespace hvdtpu
