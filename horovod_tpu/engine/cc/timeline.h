// Chrome-tracing timeline writer.  Same event model as the reference's
// Horovod Timeline (/root/reference/horovod/common/timeline.{h,cc}): one
// trace "pid" per tensor name, NEGOTIATE -> op -> activity nesting, JSON
// written incrementally and flushed periodically; load the output in
// chrome://tracing or Perfetto.
//
// Cross-rank tracing (docs/timeline.md): EVERY rank writes its own file
// (the Python side resolves HOROVOD_TIMELINE's directory / %d forms to a
// per-rank path).  Timestamps are anchored to the engine's Init-time
// epoch, the coordinator measures each worker epoch's offset against its
// own (engine.cc ClockSync), and each file records its rank plus that
// offset as metadata — tools/timeline_merge.py uses them to fuse the
// per-rank files onto rank 0's clock.
#pragma once

#include <chrono>
#include <cstdint>
#include <fstream>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

class Timeline {
 public:
  // `epoch` anchors every ts (µs since it); `rank` is recorded as an
  // "hvd_rank" metadata event so merged traces know who wrote what.
  void Initialize(const std::string& path, int rank,
                  std::chrono::steady_clock::time_point epoch);
  bool Enabled() const { return enabled_; }

  void NegotiateStart(const std::string& name, uint8_t op);
  // `ts_us` >= 0 stamps the instant at that epoch-time instead of "now":
  // under the coordinator tree, rank 0 receives announce timestamps
  // forwarded (clock-mapped) by the sub-coordinators, and the RANK_READY
  // instants must carry the TRUE announce times or the straggler report
  // (tools/timeline_merge.py) would attribute every skew to the
  // aggregate frame's arrival.
  void NegotiateRankReady(const std::string& name, int rank,
                          int64_t ts_us = -1);
  void NegotiateEnd(const std::string& name);
  void Start(const std::string& name, const std::string& op_name);
  void ActivityStart(const std::string& name, const std::string& activity);
  void ActivityEnd(const std::string& name);
  void End(const std::string& name, int64_t bytes);
  // Instant event ('i') on `name`'s row — the span API's trace_marker.
  void Instant(const std::string& name, const std::string& label);
  // "hvd_clock_sync" metadata: this rank's estimated clock offset against
  // rank 0 (µs; subtract from ts to land on rank 0's clock) and the RTT of
  // the winning probe (the error bound).  Flushed immediately so the merge
  // tool can align even a trace whose writer later crashed.
  void WriteClockSync(int64_t offset_us, int64_t rtt_us);
  // Flush buffered events to disk without closing (abort/crash paths:
  // post-mortem traces must parse, docs/timeline.md).
  void Flush();
  void Shutdown();

 private:
  void WriteEvent(const std::string& name, char phase, const std::string& args,
                  const std::string& category, int64_t ts_us = -1);
  int64_t TensorPid(const std::string& name);
  int64_t NowUs() const;

  bool enabled_ = false;
  std::ofstream file_;
  std::mutex mu_;
  std::unordered_map<std::string, int64_t> tensor_pids_;
  // Per-row monotonicity clamp: explicit timestamps (forwarded announce
  // times under the coordinator tree) may precede a row's last written
  // event by microseconds; Chrome-trace consumers (and the structural
  // validator) want non-decreasing ts per row.
  std::unordered_map<int64_t, int64_t> last_ts_by_pid_;
  // Per-row stack of open 'B' labels so every 'E' event can repeat its
  // opener's name — the structural-validation contract (tests require
  // ph/ts/pid/name on every row) without breaking Chrome's B/E pairing.
  std::unordered_map<std::string, std::vector<std::string>> open_labels_;
  std::chrono::steady_clock::time_point start_{};
  std::chrono::steady_clock::time_point last_flush_{};
};

}  // namespace hvdtpu
