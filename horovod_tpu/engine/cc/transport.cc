#include "transport.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <thread>

#include "net.h"

namespace hvdtpu {

namespace {

double NowSec() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

long long NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Segment geometry.  The header block and every ring block are page-
// aligned so cursor cache lines never share a page-straddling ring
// payload tail with a neighbouring ring's header.
constexpr uint64_t kShmMagic = 0x68766474707573ULL;  // "hvdtpus"
constexpr uint32_t kShmVersion = 1;
constexpr size_t kShmHeaderBytes = 4096;
constexpr size_t kShmPage = 4096;

struct SegHeader {
  uint64_t magic;
  uint32_t version;
  uint32_t local_size;
  uint64_t ring_bytes;
};

size_t RoundUpPow2(size_t v) {
  size_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

size_t RingBlockBytes(size_t ring_bytes) {
  size_t raw = sizeof(ShmRing) + ring_bytes;
  return (raw + kShmPage - 1) / kShmPage * kShmPage;
}

size_t SegTotalBytes(int local_size, size_t ring_bytes) {
  return kShmHeaderBytes +
         static_cast<size_t>(local_size) * 2 * RingBlockBytes(ring_bytes);
}

// Spin-then-yield pacing for the ring drive loops: a burst of on-core
// pauses (the common case — the peer engine thread polls every few µs),
// then yields, then a 50µs sleep so an idle wait costs no meaningful
// CPU.  No futex anywhere: abort wake is the `closed` flag, observed
// within one pass.
void PollPause(int idle) {
  if (idle < 256) {
#if defined(__x86_64__)
    __builtin_ia32_pause();
#else
    std::this_thread::yield();
#endif
  } else if (idle < 4096) {
    std::this_thread::yield();
  } else {
    std::this_thread::sleep_for(std::chrono::microseconds(50));
  }
}

bool RingClosed(const ShmRing* r) {
  return r->closed.load(std::memory_order_acquire) != 0;
}

}  // namespace

ShmMode ParseShmMode(const char* value) {
  if (value == nullptr) return ShmMode::kAuto;
  std::string v(value);
  if (v.empty() || v == "auto") return ShmMode::kAuto;
  if (v == "0" || v == "off") return ShmMode::kOff;
  if (v == "1" || v == "force") return ShmMode::kForce;
  return ShmMode::kAuto;
}

const char* ShmModeName(ShmMode m) {
  switch (m) {
    case ShmMode::kOff: return "off";
    case ShmMode::kForce: return "force";
    default: return "auto";
  }
}

size_t ShmRing::WriteSome(const void* buf, size_t len) {
  const uint64_t h = head.load(std::memory_order_relaxed);
  const uint64_t t = tail.load(std::memory_order_acquire);
  const size_t space = capacity - static_cast<size_t>(h - t);
  size_t n = std::min(len, space);
  if (n == 0) return 0;
  const size_t off = static_cast<size_t>(h) & (capacity - 1);
  const size_t first = std::min(n, static_cast<size_t>(capacity) - off);
  memcpy(Data() + off, buf, first);
  if (n > first)
    memcpy(Data(), static_cast<const char*>(buf) + first, n - first);
  head.store(h + n, std::memory_order_release);
  return n;
}

size_t ShmRing::ReadSome(void* buf, size_t len) {
  const uint64_t t = tail.load(std::memory_order_relaxed);
  const uint64_t h = head.load(std::memory_order_acquire);
  const size_t avail = static_cast<size_t>(h - t);
  size_t n = std::min(len, avail);
  if (n == 0) return 0;
  const size_t off = static_cast<size_t>(t) & (capacity - 1);
  const size_t first = std::min(n, static_cast<size_t>(capacity) - off);
  memcpy(buf, Data() + off, first);
  if (n > first) memcpy(static_cast<char*>(buf) + first, Data(), n - first);
  tail.store(t + n, std::memory_order_release);
  return n;
}

std::string ShmSegmentName(const std::string& job_tag, int node_id,
                           long long epoch) {
  uint32_t h = 2166136261u;
  for (char c : job_tag) h = (h ^ static_cast<uint8_t>(c)) * 16777619u;
  char buf[64];
  snprintf(buf, sizeof(buf), "/hvdtpu_%08x_n%d_e%lld", h, node_id, epoch);
  return std::string(buf);
}

bool ShmSegment::Create(const std::string& name, int local_size,
                        size_t ring_bytes, std::string* err) {
  ring_bytes = RoundUpPow2(
      std::max<size_t>(64 * 1024, std::min<size_t>(ring_bytes, 256u << 20)));
  // Stale sweep: a previous generation that died between its create and
  // its attach round-trip may have left the name behind (the only
  // window in which a name exists at all).
  shm_unlink(name.c_str());
  int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    *err = "shm_open(" + name + "): " + strerror(errno);
    return false;
  }
  const size_t total = SegTotalBytes(local_size, ring_bytes);
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    *err = "ftruncate(" + name + ", " + std::to_string(total) +
           "): " + strerror(errno);
    close(fd);
    shm_unlink(name.c_str());
    return false;
  }
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    *err = "mmap(" + name + "): " + strerror(errno);
    shm_unlink(name.c_str());
    return false;
  }
  base_ = base;
  bytes_ = total;
  name_ = name;
  creator_ = true;
  unlinked_ = false;
  local_size_ = local_size;
  ring_bytes_ = ring_bytes;
  // ftruncate pages arrive zeroed, which is a valid initial state for
  // the cursor atomics; only capacity and the header need stores.
  for (int r = 0; r < local_size; ++r)
    for (int dir = 0; dir < 2; ++dir) Ring(r, dir)->capacity =
        static_cast<uint32_t>(ring_bytes);
  SegHeader* hdr = static_cast<SegHeader*>(base_);
  hdr->version = kShmVersion;
  hdr->local_size = static_cast<uint32_t>(local_size);
  hdr->ring_bytes = ring_bytes;
  hdr->magic = kShmMagic;
  return true;
}

bool ShmSegment::Attach(const std::string& name, int local_size,
                        size_t ring_bytes, std::string* err) {
  ring_bytes = RoundUpPow2(
      std::max<size_t>(64 * 1024, std::min<size_t>(ring_bytes, 256u << 20)));
  int fd = shm_open(name.c_str(), O_RDWR, 0600);
  if (fd < 0) {
    *err = "shm_open(" + name + "): " + strerror(errno);
    return false;
  }
  const size_t total = SegTotalBytes(local_size, ring_bytes);
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) != total) {
    *err = "segment " + name + " has size " + std::to_string(st.st_size) +
           ", want " + std::to_string(total) +
           " (stale generation or shape mismatch)";
    close(fd);
    return false;
  }
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    *err = "mmap(" + name + "): " + strerror(errno);
    return false;
  }
  const SegHeader* hdr = static_cast<const SegHeader*>(base);
  if (hdr->magic != kShmMagic || hdr->version != kShmVersion ||
      hdr->local_size != static_cast<uint32_t>(local_size) ||
      hdr->ring_bytes != ring_bytes) {
    *err = "segment " + name + " header mismatch (magic/version/shape)";
    munmap(base, total);
    return false;
  }
  base_ = base;
  bytes_ = total;
  name_ = name;
  creator_ = false;
  unlinked_ = false;
  local_size_ = local_size;
  ring_bytes_ = ring_bytes;
  return true;
}

void ShmSegment::Unlink() {
  if (name_.empty() || unlinked_) return;
  shm_unlink(name_.c_str());  // ENOENT after the init-time unlink: fine
  unlinked_ = true;
}

void ShmSegment::CloseRings() {
  if (!mapped()) return;
  for (int r = 0; r < local_size_; ++r)
    for (int dir = 0; dir < 2; ++dir)
      Ring(r, dir)->closed.store(1, std::memory_order_release);
}

void ShmSegment::Unmap() {
  if (base_ != nullptr) munmap(base_, bytes_);
  base_ = nullptr;
  bytes_ = 0;
  local_size_ = 0;
}

ShmRing* ShmSegment::Ring(int src_local_rank, int dir) {
  char* p = static_cast<char*>(base_) + kShmHeaderBytes +
            (static_cast<size_t>(src_local_rank) * 2 + dir) *
                RingBlockBytes(ring_bytes_);
  return reinterpret_cast<ShmRing*>(p);
}

// ---------------------------------------------------------------------------
// Channel drive loops.  One generic 4-leg progress engine covers
// SendAll/RecvAll/Exchange/ExchangeBi over any mix of ring and fd legs;
// the pure-TCP fast paths delegate to net.cc so the socket
// implementation (poll multiplexing, fault hooks, telemetry) stays the
// single source of truth for that transport.
// ---------------------------------------------------------------------------

namespace {

struct DriveLeg {
  const Channel* ch = nullptr;
  bool is_send = false;
  const char* sp = nullptr;
  char* rp = nullptr;
  size_t len = 0, done = 0;
  long long handoff_us = -1;  // send legs: time to fully enter the ring
};

bool DriveLegs(DriveLeg* legs, int n) {
  const bool track = NetLinkEnabled();
  const long long t0 = NowUs();
  // Chaos delay/jitter clauses apply once per handoff, before any bytes
  // move — the shm seam analogue of SendAll's pre-send NetFaultDelay.
  if (NetFaultActive())
    for (int i = 0; i < n; ++i)
      if (legs[i].is_send && legs[i].len > 0 && legs[i].ch->shm())
        NetFaultDelayPeer(legs[i].ch->peer);
  int idle = 0;
  double deadline = 0.0;  // armed lazily on the first stall
  auto pending = [&](const DriveLeg& l) { return l.done < l.len; };
  for (;;) {
    bool all_done = true, progress = false;
    for (int i = 0; i < n; ++i) {
      DriveLeg& l = legs[i];
      if (!pending(l)) continue;
      all_done = false;
      size_t moved = 0;
      if (l.is_send) {
        if (l.ch->shm()) {
          if (RingClosed(l.ch->tx)) return false;
          moved = l.ch->tx->WriteSome(l.sp + l.done, l.len - l.done);
        } else {
          ssize_t w = send(l.ch->fd, l.sp + l.done, l.len - l.done,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
          if (w < 0 && errno != EINTR && errno != EAGAIN &&
              errno != EWOULDBLOCK)
            return false;
          if (w > 0) moved = static_cast<size_t>(w);
        }
      } else {
        if (l.ch->shm()) {
          moved = l.ch->rx->ReadSome(l.rp + l.done, l.len - l.done);
          if (moved == 0 && RingClosed(l.ch->rx)) return false;
        } else {
          ssize_t g = recv(l.ch->fd, l.rp + l.done, l.len - l.done,
                           MSG_DONTWAIT);
          if (g == 0) return false;
          if (g < 0 && errno != EINTR && errno != EAGAIN &&
              errno != EWOULDBLOCK)
            return false;
          if (g > 0) moved = static_cast<size_t>(g);
        }
      }
      if (moved > 0) {
        l.done += moved;
        progress = true;
        if (l.is_send && l.done == l.len && l.ch->shm())
          l.handoff_us = NowUs() - t0;
      }
    }
    if (all_done) break;
    if (progress) {
      idle = 0;
      deadline = 0.0;
      continue;
    }
    ++idle;
    if (deadline == 0.0) {
      deadline = NowSec() + 30.0;  // same silence budget as the TCP path
    } else if ((idle & 1023) == 0 && NowSec() >= deadline) {
      return false;
    }
    PollPause(idle);
  }
  if (track) {
    for (int i = 0; i < n; ++i) {
      const DriveLeg& l = legs[i];
      if (!l.ch->shm() || l.len == 0) continue;
      if (l.is_send)
        NetLinkRecordShm(l.ch->peer, static_cast<long long>(l.len), 0,
                         l.handoff_us);
      else
        NetLinkRecordShm(l.ch->peer, 0, static_cast<long long>(l.len), -1);
    }
  }
  return true;
}

}  // namespace

bool ChannelSendAll(const Channel& ch, const void* buf, size_t len) {
  if (!ch.shm()) return SendAll(ch.fd, buf, len);
  DriveLeg leg;
  leg.ch = &ch;
  leg.is_send = true;
  leg.sp = static_cast<const char*>(buf);
  leg.len = len;
  return DriveLegs(&leg, 1);
}

bool ChannelRecvAll(const Channel& ch, void* buf, size_t len) {
  if (!ch.shm()) return RecvAll(ch.fd, buf, len);
  DriveLeg leg;
  leg.ch = &ch;
  leg.rp = static_cast<char*>(buf);
  leg.len = len;
  return DriveLegs(&leg, 1);
}

bool ChannelExchange(const Channel& send_ch, const void* sbuf, size_t slen,
                     const Channel& recv_ch, void* rbuf, size_t rlen) {
  if (!send_ch.shm() && !recv_ch.shm())
    return Exchange(send_ch.fd, sbuf, slen, recv_ch.fd, rbuf, rlen);
  DriveLeg legs[2];
  legs[0].ch = &send_ch;
  legs[0].is_send = true;
  legs[0].sp = static_cast<const char*>(sbuf);
  legs[0].len = slen;
  legs[1].ch = &recv_ch;
  legs[1].rp = static_cast<char*>(rbuf);
  legs[1].len = rlen;
  return DriveLegs(legs, 2);
}

bool ChannelExchangeBi(const Channel& right, const void* send_r,
                       size_t send_r_len, void* recv_r, size_t recv_r_len,
                       const Channel& left, const void* send_l,
                       size_t send_l_len, void* recv_l, size_t recv_l_len) {
  if (!right.shm() && !left.shm())
    return ExchangeBi(right.fd, send_r, send_r_len, recv_r, recv_r_len,
                      left.fd, send_l, send_l_len, recv_l, recv_l_len);
  DriveLeg legs[4];
  legs[0].ch = &right;
  legs[0].is_send = true;
  legs[0].sp = static_cast<const char*>(send_r);
  legs[0].len = send_r_len;
  legs[1].ch = &right;
  legs[1].rp = static_cast<char*>(recv_r);
  legs[1].len = recv_r_len;
  legs[2].ch = &left;
  legs[2].is_send = true;
  legs[2].sp = static_cast<const char*>(send_l);
  legs[2].len = send_l_len;
  legs[3].ch = &left;
  legs[3].rp = static_cast<char*>(recv_l);
  legs[3].len = recv_l_len;
  return DriveLegs(legs, 4);
}

}  // namespace hvdtpu
