// TCP transport for the collective engine: control plane (rank-0 coordinator
// star) and data plane (ring).  TPU-native replacement for the reference's
// use of MPI as both planes (/root/reference/horovod/common/operations.cc:
// 1541-1678 control, :1144/:828/:1211 data) -- on TPU pods the cross-host
// fabric is plain IP (DCN), so the engine speaks TCP directly and needs no
// MPI launcher.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

// Parse "host:port".  Returns false on malformed input.
bool ParseEndpoint(const std::string& ep, std::string* host, int* port);

// Create a listening socket bound to host:port.  Returns fd or -1.
int Listen(const std::string& host, int port, std::string* err);

// Accept one connection (blocking, with timeout_sec).  Returns fd or -1.
int AcceptOne(int listen_fd, double timeout_sec, std::string* err);

// Connect to host:port, retrying until timeout_sec elapses (peers may not be
// up yet -- the analogue of MPI_Init's implicit rendezvous).  fd or -1.
int ConnectRetry(const std::string& host, int port, double timeout_sec,
                 std::string* err);

// Blocking full-buffer send/recv.  Return false on error/EOF.
bool SendAll(int fd, const void* buf, size_t len);
bool RecvAll(int fd, void* buf, size_t len);

// Wait until fd is readable (or in error/EOF, which a subsequent recv will
// surface).  False on timeout — the liveness probe for the coordinator's
// per-rank tick recv: a healthy engine thread sends a frame every cycle
// (~5ms), so a deadline's worth of silence means the peer PROCESS is
// frozen or the network is partitioned, which socket EOF never reports.
bool WaitReadable(int fd, double timeout_sec);

// Non-blocking liveness probe: true when the peer has closed (EOF) or the
// socket is in error — i.e. sending to it can no longer succeed.  Pending
// unread data does NOT count as closed.
bool PeerClosed(int fd);

// Length-prefixed message framing ([u32 little-endian length][payload]).
bool SendFrame(int fd, const std::vector<uint8_t>& payload);
bool RecvFrame(int fd, std::vector<uint8_t>* payload);

// Append whatever bytes fd has ready RIGHT NOW to *buf without ever
// blocking (MSG_DONTWAIT), so a caller can assemble a message across
// ticks from a peer that trickles it.  False on error or EOF; true
// otherwise, including when zero new bytes were available.
bool RecvAvailable(int fd, std::vector<uint8_t>* buf);

// Full-duplex exchange: send `slen` bytes on send_fd while receiving `rlen`
// bytes from recv_fd, multiplexed with poll(2) so neighbouring ranks can
// stream large ring segments to each other without deadlocking on full
// kernel socket buffers.
bool Exchange(int send_fd, const void* sbuf, size_t slen,
              int recv_fd, void* rbuf, size_t rlen);

// Bidirectional neighbour exchange: stream A is sent rightward on
// right_fd while stream A' arrives on left_fd (recv_l); stream B is sent
// leftward on left_fd while B' arrives on right_fd (recv_r).  All four
// legs run in one poll loop, saturating both directions of both links.
bool ExchangeBi(int right_fd, const void* send_r, size_t send_r_len,
                void* recv_r, size_t recv_r_len, int left_fd,
                const void* send_l, size_t send_l_len, void* recv_l,
                size_t recv_l_len);

void CloseFd(int fd);

// shutdown(2) both directions WITHOUT closing: any thread blocked in
// poll/send/recv on the fd wakes with an error immediately, and the fd
// number stays allocated — no close-vs-concurrent-use reuse race.  The
// owner still calls CloseFd afterwards (after joining helpers).
void ShutdownFd(int fd);

}  // namespace hvdtpu
