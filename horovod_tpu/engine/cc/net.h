// TCP transport for the collective engine: control plane (rank-0 coordinator
// star) and data plane (ring).  TPU-native replacement for the reference's
// use of MPI as both planes (/root/reference/horovod/common/operations.cc:
// 1541-1678 control, :1144/:828/:1211 data) -- on TPU pods the cross-host
// fabric is plain IP (DCN), so the engine speaks TCP directly and needs no
// MPI launcher.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

struct iovec;  // <sys/uio.h>; kept out of this header on purpose

namespace hvdtpu {

// Parse "host:port".  Returns false on malformed input.
bool ParseEndpoint(const std::string& ep, std::string* host, int* port);

// Create a listening socket bound to host:port.  Returns fd or -1.
int Listen(const std::string& host, int port, std::string* err);

// Accept one connection (blocking, with timeout_sec).  Returns fd or -1.
int AcceptOne(int listen_fd, double timeout_sec, std::string* err);

// Connect to host:port, retrying until timeout_sec elapses (peers may not be
// up yet -- the analogue of MPI_Init's implicit rendezvous).  fd or -1.
int ConnectRetry(const std::string& host, int port, double timeout_sec,
                 std::string* err);

// Blocking full-buffer send/recv.  Return false on error/EOF.
bool SendAll(int fd, const void* buf, size_t len);
bool RecvAll(int fd, void* buf, size_t len);

// Scatter-gather blocking send: the iovec array goes out in one
// sendmsg(2) per kernel acceptance (header + payload straight from
// their source buffers, no stage copy), with the same fault hooks and
// timed-send telemetry as SendAll.  The iovecs are copied internally;
// the caller's array is never mutated.
bool SendVec(int fd, const struct iovec* iov, int iovcnt);

// Wait until fd is readable (or in error/EOF, which a subsequent recv will
// surface).  False on timeout — the liveness probe for the coordinator's
// per-rank tick recv: a healthy engine thread sends a frame every cycle
// (~5ms), so a deadline's worth of silence means the peer PROCESS is
// frozen or the network is partitioned, which socket EOF never reports.
bool WaitReadable(int fd, double timeout_sec);

// Non-blocking liveness probe: true when the peer has closed (EOF) or the
// socket is in error — i.e. sending to it can no longer succeed.  Pending
// unread data does NOT count as closed.
bool PeerClosed(int fd);

// Length-prefixed message framing ([u32 little-endian length][payload]).
bool SendFrame(int fd, const std::vector<uint8_t>& payload);
bool RecvFrame(int fd, std::vector<uint8_t>* payload);

// Append whatever bytes fd has ready RIGHT NOW to *buf without ever
// blocking (MSG_DONTWAIT), so a caller can assemble a message across
// ticks from a peer that trickles it.  False on error or EOF; true
// otherwise, including when zero new bytes were available.
bool RecvAvailable(int fd, std::vector<uint8_t>* buf);

// Full-duplex exchange: send `slen` bytes on send_fd while receiving `rlen`
// bytes from recv_fd, multiplexed with poll(2) so neighbouring ranks can
// stream large ring segments to each other without deadlocking on full
// kernel socket buffers.
bool Exchange(int send_fd, const void* sbuf, size_t slen,
              int recv_fd, void* rbuf, size_t rlen);

// Bidirectional neighbour exchange: stream A is sent rightward on
// right_fd while stream A' arrives on left_fd (recv_l); stream B is sent
// leftward on left_fd while B' arrives on right_fd (recv_r).  All four
// legs run in one poll loop, saturating both directions of both links.
bool ExchangeBi(int right_fd, const void* send_r, size_t send_r_len,
                void* recv_r, size_t recv_r_len, int left_fd,
                const void* send_l, size_t send_l_len, void* recv_l,
                size_t recv_l_len);

void CloseFd(int fd);

// ---------------------------------------------------------------------------
// Deterministic link-fault injection (HVD_TPU_NET_FAULT_SPEC, the chaos
// harness of docs/fault-tolerance.md#failure-detection).  Semicolon-
// separated clauses, each optionally suffixed `@after=S` (seconds after
// NetFaultInit before the clause activates — stage faults past init
// rendezvous):
//   link=A-B:drop            blackhole the A<->B link (both endpoints
//                            swallow their outbound bytes; receivers see
//                            silence, never EOF — the partition shape
//                            only the heartbeat detector can see)
//   link=A-B:delay=MS        synchronous per-send delay on the link
//   link=A-B:delay=MS|jitter=MS   + deterministic per-send jitter
//   link=A-B:flaky=P         probability P per send of a chopped,
//                            throttled partial write (absorbed by the
//                            retry loops: degradation, not failure)
//   partition=0,1/2,3        drop on EVERY link crossing the two groups
// Every rank parses the same spec and applies the clauses whose link
// touches it, so a dropped link is dark in BOTH directions without any
// cross-rank coordination.  Faults key off the fd -> peer-rank registry
// below; unregistered fds always pass through untouched.
//
// Parse + arm the table (idempotent per Init; empty spec disarms).
// Returns false with *err set on a malformed spec.
bool NetFaultInit(const std::string& spec, int my_rank, std::string* err);
// Whether any clause is armed (cheap; callers may skip lookups).
bool NetFaultActive();
// Associate fd with the CURRENT-membership rank at the far end.
void NetFaultRegister(int fd, int peer_rank);
void NetFaultForget(int fd);
// True when outbound bytes on fd must be swallowed right now (drop /
// partition clause active for its link).
bool NetFaultDrops(int fd);
// Apply pre-send latency (delay/jitter clause) for fd; no-op otherwise.
void NetFaultDelay(int fd);
// Flaky-link verdict for one send on fd: returns a byte cap (> 0) for a
// deliberately chopped write plus a tiny stall, or 0 for an untouched
// send.  Deterministic per (spec, rank, link, send index).
size_t NetFaultChop(int fd);

// Shm-seam interrogation (Engine::SetupShmTransport): the strongest
// clause naming the rank_a<->rank_b link, independent of @after arming
// (a clause that would arm later still decides transport choice at
// init).  Returns 0 = no clause, 1 = delay/jitter only (appliable at
// the shm seam), 2 = drop/flaky/partition (shm cannot express it — the
// caller must fall back to TCP or fail init with a typed error).  *text
// gets the deciding clause's source text for those messages.
int NetFaultQueryLink(int rank_a, int rank_b, std::string* text);

// Apply a delay/jitter clause keyed by PEER RANK rather than fd — the
// shm transport's per-handoff hook (rings have no fd).  Deterministic
// jitter stream per (spec, link), independent of the fd-keyed stream.
void NetFaultDelayPeer(int peer_rank);

// shutdown(2) both directions WITHOUT closing: any thread blocked in
// poll/send/recv on the fd wakes with an error immediately, and the fd
// number stays allocated — no close-vs-concurrent-use reuse race.  The
// owner still calls CloseFd afterwards (after joining helpers).
void ShutdownFd(int fd);

// ---------------------------------------------------------------------------
// Per-peer link telemetry (docs/metrics.md#links): byte / send / stall
// counters and a fixed-bucket send-latency histogram per PEER RANK,
// aggregated across every registered fd to that peer (control star, ring,
// beat lane).  Accounting rides the same fd -> peer registry the fault
// layer keys off — NetFaultRegister is the single registration point —
// and costs one mutex hold per SendAll/RecvAll/Exchange call (never per
// byte), so the chaos layer's injected delays land INSIDE the measured
// send latency: a `link=A-B:delay=MS` clause is directly observable as a
// latency excursion on exactly that link.  Counters are process-
// cumulative (the engine.h StallEvents contract: they survive re-init).
// HVD_TPU_LINK_STATS=0 disarms everything but the relaxed-atomic gate.

// Arm/disarm the accounting (called from Engine::Init with the parsed
// HVD_TPU_LINK_STATS gate; stats persist across re-inits).
void NetLinkInit(bool enabled);
bool NetLinkEnabled();

// Serialized per-peer snapshot for the c_api:
//   "enabled|peer:bytes_out:bytes_in:sends:recvs:stalls:short_writes:
//    send_us_sum:send_us_count:b0,b1,...,b9:rtt_last_us:rtt_ewma_us:
//    rtt_samples:shm_bytes_out:shm_bytes_in:shm_handoffs:shm_us_sum:
//    shm_us_count:s0,...,s9:transport;peer:..." (peers sorted; empty
// list when nothing flowed).  `transport` labels what carries this
// peer's data-plane bytes: "shm" once any ring handoff flowed (the
// remaining TCP bytes are rendezvous/heartbeat control), else "tcp".
std::string NetLinkInfo();

// Fold one shm-ring handoff into peer's stats: bytes in each direction
// plus — when handoff_us >= 0 — one segment-handoff latency histogram
// sample (time for a send leg to fully enter the peer's ring,
// including injected chaos delay, mirroring the SendAll clock).
void NetLinkRecordShm(int peer_rank, long long bytes_out, long long bytes_in,
                      long long handoff_us);

// Histogram bucket upper bounds (µs); the last bucket is +inf.  Exposed
// so the Python registry renders `le` labels that match the C++ counts.
extern const long long kNetLinkBucketUs[];
extern const int kNetLinkBuckets;

// Fold one heartbeat-echo round-trip sample into peer's RTT estimate
// (last + EWMA).  Called from the heartbeat monitor thread.
void NetLinkRecordRtt(int peer_rank, long long rtt_us);

// Cumulative timed-send count across all peers (simscale report surface:
// proves which regime an overhead-bench cell actually ran in).
long long NetLinkSendsTotal();

// Detector-side accessor (anomaly monitor): per-peer cumulative send-
// latency totals, cheap enough to poll every sweep.
struct NetLinkLatencyTotal {
  int peer;
  long long sum_us;
  long long count;
  long long rtt_last_us;
};
std::vector<NetLinkLatencyTotal> NetLinkLatencyTotals();

}  // namespace hvdtpu
