#include "flight.h"

namespace hvdtpu {

namespace {

// Interned-name bound: a pathological auto-named tensor stream must not
// grow the table without limit; names past the cap share one bucket (the
// ring entry still carries its event type, timestamp and arg).
constexpr size_t kMaxInternedNames = 4096;

}  // namespace

const char* FlightEventName(uint8_t event) {
  switch (event) {
    case FL_ENQUEUE:   return "enqueue";
    case FL_ANNOUNCE:  return "announce";
    case FL_CACHE_HIT: return "cache_hit";
    case FL_EXECUTE:   return "execute";
    case FL_ERROR:     return "error";
    case FL_TICK:      return "tick";
    case FL_STALL:     return "stall";
    case FL_ABORT:     return "abort";
    case FL_RESHAPE:   return "reshape";
    case FL_TUNE:      return "tune";
    case FL_COMPRESS:  return "compress";
    case FL_TOPOLOGY:  return "topology";
    case FL_STEADY:    return "steady";
    case FL_HEARTBEAT_MISS: return "heartbeat_miss";
    case FL_ANOMALY:   return "anomaly";
    case FL_TRANSPORT: return "transport";
    case FL_P2P:       return "p2p";
    default:           return "unknown";
  }
}

void FlightRecorder::Initialize(
    int64_t capacity, std::chrono::steady_clock::time_point epoch) {
  std::lock_guard<std::mutex> lk(mu_);
  enabled_ = capacity > 0;
  epoch_ = epoch;
  next_seq_ = 0;
  head_ = 0;
  ring_.clear();
  names_.clear();
  name_ids_.clear();
  if (!enabled_) return;
  if (capacity > 65536) capacity = 65536;
  ring_.assign(static_cast<size_t>(capacity), Entry());
  // id 0: "no tensor" (tick/abort/reshape events); id 1: intern overflow.
  names_.push_back("");
  names_.push_back("<other>");
}

int32_t FlightRecorder::InternLocked(const std::string& name) {
  if (name.empty()) return 0;
  auto it = name_ids_.find(name);
  if (it != name_ids_.end()) return it->second;
  if (names_.size() >= kMaxInternedNames) return 1;
  int32_t id = static_cast<int32_t>(names_.size());
  std::string clean;
  clean.reserve(name.size());
  for (char c : name) clean += (c == ';' || c == '|') ? '_' : c;
  names_.push_back(clean);
  name_ids_[name] = id;
  return id;
}

void FlightRecorder::Record(uint8_t event, const std::string& name,
                            int64_t arg) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!enabled_) return;
  Entry& e = ring_[head_];
  e.seq = next_seq_++;
  e.ts_us = std::chrono::duration_cast<std::chrono::microseconds>(
                std::chrono::steady_clock::now() - epoch_)
                .count();
  e.event = event;
  e.name_id = InternLocked(name);
  e.arg = arg;
  head_ = (head_ + 1) % ring_.size();
  ++total_;
}

int64_t FlightRecorder::Events() const {
  std::lock_guard<std::mutex> lk(mu_);
  return total_;
}

std::string FlightRecorder::Dump() {
  std::lock_guard<std::mutex> lk(mu_);
  std::string out;
  if (!enabled_) return out;
  // Oldest entry sits at head_ once the ring has wrapped.
  const size_t n = ring_.size();
  for (size_t i = 0; i < n; ++i) {
    const Entry& e = ring_[(head_ + i) % n];
    if (e.seq < 0) continue;  // never written
    if (!out.empty()) out += ';';
    out += std::to_string(e.seq) + "|" + std::to_string(e.ts_us) + "|" +
           FlightEventName(e.event) + "|" +
           (e.name_id >= 0 && e.name_id < static_cast<int32_t>(names_.size())
                ? names_[e.name_id]
                : "") +
           "|" + std::to_string(e.arg);
  }
  return out;
}

}  // namespace hvdtpu
