// Core collective engine: enqueue -> negotiate -> fuse -> execute -> complete.
//
// TPU-native redesign of the reference engine
// (/root/reference/horovod/common/operations.cc):
//   * rank/size come from the launcher / pod-slice metadata, not MPI_Init
//   * control plane: rank-0 TCP coordinator (star), replacing
//     MPI_Gather/MPI_Bcast negotiation (operations.cc:1541-1678)
//   * data plane: bandwidth-optimal ring allreduce / allgather / pipelined
//     broadcast over direct TCP between ring neighbours, replacing
//     MPI_Allreduce/MPI_Allgatherv/MPI_Bcast (operations.cc:1144,828,1211);
//     on a TPU pod these host-side collectives ride DCN while the compiled
//     JAX path (horovod_tpu/jax) rides ICI via XLA collectives.
//   * completion: polling handle table (the reference's torch handle manager,
//     /root/reference/horovod/torch/handle_manager.cc, promoted to the core
//     so every framework binding shares it) -- no CUDA events.
// Tensor fusion, the coordinator's consistency checks, stall detection and
// the timeline keep the reference's semantics (operations.cc:1607-1642,
// :301-503, :1231-1276).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "autotune.h"
#include "flight.h"
#include "timeline.h"
#include "transport.h"
#include "wire.h"

namespace hvdtpu {

struct EngineOptions {
  int rank = 0;
  int size = 1;
  int local_rank = 0;
  int local_size = 1;
  std::string coord_endpoint;               // "host:port" (rank 0 listens)
  std::vector<std::string> data_endpoints;  // one per rank
  double cycle_time_ms = 5.0;
  int64_t fusion_threshold = 64 * 1024 * 1024;
  double stall_warning_sec = 60.0;
  // Hard deadline for a collective stuck in negotiation (a subset of ranks
  // never announced it): past this, the coordinator escalates from the
  // stall *warning* to a coordinated ABORT (ST_TIMEOUT) naming the stalled
  // tensors and missing ranks, so the job fails fast instead of hanging
  // until an outer launcher timeout.  <= 0 disables (warning-only, the
  // pre-fault-tolerance behavior).  HVD_TPU_COLLECTIVE_TIMEOUT_SEC.
  double collective_timeout_sec = 0.0;
  // Negotiation response cache (docs/performance.md): number of negotiated
  // collectives each rank remembers so repeats announce a compact slot
  // index instead of a full string request.  HVD_TPU_CACHE_CAPACITY
  // (default 1024); 0 disables (HVD_TPU_RESPONSE_CACHE=0 kill switch).
  int64_t cache_capacity = 1024;
  std::string timeline_path;
  // Online autotuning (docs/performance.md#autotuning): rank 0 scores
  // tuning windows of `autotune_window` negotiated collectives from the
  // throughput the coordinator already observes and broadcasts the next
  // (fusion_threshold, cycle_time_ms) candidate in the response list so
  // every rank applies it at the same tick boundary.  HVD_TPU_AUTOTUNE=1
  // opts in; the first `autotune_warmup` windows are discarded; a fix
  // value >= 0 pins that knob (HVD_TPU_AUTOTUNE_FIX=k=v,...).
  bool autotune = false;
  int64_t autotune_warmup = 2;
  int64_t autotune_window = 32;
  int64_t autotune_fix_fusion = -1;
  double autotune_fix_cycle_ms = -1.0;
  int64_t autotune_fix_compression = -1;
  int64_t autotune_fix_cross_algo = -1;
  // Wire-level gradient compression (docs/performance.md#wire-compression,
  // HVD_TPU_COMPRESSION off|bf16|fp8): fp32 allreduce buckets at least
  // `compression_min_bytes` big transfer as bf16 / fp8-e4m3 with fp32
  // master copies and per-tensor error-feedback residuals; reduction
  // still accumulates in f32 at every ring hop.  Agreed JOB-WIDE at init
  // (a mixed-env launch is a typed init error, not a silent split into
  // ranks that pack buckets differently), mutated only by the lockstep
  // tuned-parameter broadcasts, and re-agreed across elastic reshapes.
  uint8_t compression_mode = COMP_NONE;
  int64_t compression_min_bytes = 1024;
  // Two-level allreduce (docs/performance.md#two-level-topology):
  // node-local reduce-scatter -> one cross-node exchange PER LOCAL RANK
  // over its 1/local_size shard (local_size parallel DCN streams) ->
  // node-local allgather, chunk-pipelined so the local and cross phases
  // overlap.  The bandwidth-optimal successor of the reference's
  // ncclReduce -> MPI_Allreduce -> ncclBcast star
  // (HOROVOD_HIERARCHICAL_ALLREDUCE, operations.cc:1003-1048).  Requires
  // ranks grouped in contiguous blocks of local_size (the hvdrun layout).
  bool hierarchical_allreduce = false;
  // Ring-vs-tree boundary for the cross-node hop: hierarchical buckets
  // with payload under this many bytes take the recursive-doubling
  // (tree) exchange — log2(nodes) latency steps instead of
  // 2*(nodes-1) — and everything else takes the bandwidth-optimal ring.
  // HVD_TPU_CROSS_ALGO_THRESHOLD; autotuned as the fourth ParameterManager
  // axis; 0 = ring always.
  int64_t cross_algo_threshold = 64 * 1024;
  // Elastic membership (docs/fault-tolerance.md#elastic-membership,
  // HVD_TPU_ELASTIC): when a worker dies, the coordinator reshapes the
  // job around the survivors (new dense ranks, rebuilt ring, membership
  // epoch bump) instead of cascading a fatal abort, as long as at least
  // `min_size` ranks survive — below that the abort path (and with it
  // the hvdrun checkpoint-restart fallback) still fires.  Requires the
  // rank-0 coordinator to survive; forces the flat ring (hierarchical
  // topologies are not rebuilt).
  bool elastic = false;
  int64_t min_size = 1;
  // This process is a standby REJOINING a live elastic job
  // (HVD_TPU_REJOIN, spawned by hvdrun --min-np/--max-np): Init connects
  // to the coordinator, announces its data endpoint, and blocks until
  // admitted at the next reshape barrier, learning its dense rank and
  // the membership from the reshape broadcast.  rank/size/data_endpoints
  // in these options are placeholders until then.
  bool rejoin = false;
  // Control-plane coordinator tree (docs/performance.md
  // #control-plane-scaling, HVD_TPU_COORD_TREE): each host's
  // local-rank-0 becomes a sub-coordinator that accepts its node's
  // control sockets, folds announce bitsets / request lists into ONE
  // aggregate frame per tick, and relays rank 0's broadcasts back down —
  // rank 0 holds O(hosts) steady-state sockets and processes O(hosts)
  // frames per tick instead of O(ranks).  AUTO: the tree is built only
  // for multi-node contiguous layouts (the same job-wide agreement the
  // two-level data topology validates); single-host jobs keep the
  // degenerate one-level star, and elastic jobs force it (membership
  // reshapes rebuild the star only).
  bool coord_tree = true;
  // Decentralized steady state (HVD_TPU_STEADY_THRESHOLD): once the
  // coordinator sees the cache-hit slot stream repeat an identical cycle
  // this many times at quiesced boundaries, it broadcasts the pattern
  // and every rank self-clocks on an epoch counter, replaying the cached
  // responses with ZERO control-plane frames per cycle; any miss falls
  // back to full negotiation.  0 disables.  `steady_max_period` bounds
  // the detectable cycle length (slots per cycle).
  int64_t steady_threshold = 32;
  int64_t steady_max_period = 256;
};

struct HandleStatus {
  std::atomic<int32_t> code{ST_PENDING};
  std::string error;
  // Per-handle completion signalling: Wait() sleeps on THIS handle's cv,
  // and CompleteEntry wakes only this handle's waiters.  A single global
  // cv would make every completion wake every waiter — O(waiters x
  // completions) wakeups for the 100-collective broadcast groups the TF
  // binding enqueues (the scale the reference's per-handle
  // std::promise/future avoided by construction, torch handle manager).
  std::mutex mu;
  std::condition_variable cv;
  // Allgather result storage (engine-owned; exposed to the caller as a
  // zero-copy view via ResultPtr — the handle stays alive until the view
  // is dropped).
  std::vector<char> gathered;
  int64_t out_dim0 = 0;
  // Completion order stamps, written by the engine thread before `code`
  // flips.  Responses are built by rank 0 and broadcast, so both values are
  // identical on every rank for the same op — the property the XLA data
  // plane uses to agree on a cross-rank dispatch order without extra
  // round-trips (the role MPIResponseList ordering plays in the reference,
  // /root/reference/horovod/common/operations.cc:1644-1650).
  int64_t completion_seq = -1;   // per-engine monotonic completion index
  int64_t completion_tick = -1;  // index of the response list that carried it
  int64_t negotiation_us = -1;   // enqueue -> response arrival; -1 on errors
};

// One slot of the negotiation response cache: the request signature this
// rank last negotiated under `name` plus the agreed response to replay on
// a hit.  `dims` are THIS rank's dims (they differ per rank for ragged
// allgather; the stored response's rank_dim0 carries the full geometry).
struct CacheSlot {
  bool valid = false;
  uint64_t last_touch = 0;  // LRU stamp (monotonic counter, not time)
  std::string name;
  uint8_t op = OP_ALLREDUCE;
  uint8_t dtype = HVD_FLOAT32;
  int32_t root_rank = -1;
  std::vector<int64_t> dims;
  Response response;  // single-name response replayed on a hit
};

// Negotiation response cache (the role Horovod's response cache plays in
// the reference's successors): once a named collective has been fully
// negotiated, every rank stores the agreed response under a compact slot
// index.  Subsequent steps announce slot indices (RequestList.cache_bits)
// instead of string requests; the coordinator intersects and broadcasts
// hit indices; every rank replays the stored response.
//
// Determinism contract: Put/Touch/Erase happen ONLY while processing the
// broadcast response lists, in list order — identical on every rank — so
// slot numbering and LRU order stay in lockstep and a slot index means
// the same collective everywhere.  Lookup (rank-local, at queue drain)
// never mutates.
class ResponseCache {
 public:
  bool enabled() const { return capacity_ > 0; }
  void set_capacity(int64_t capacity) { capacity_ = capacity; }
  int64_t size() const { return static_cast<int64_t>(by_name_.size()); }
  // Exact-signature match (name, op, dtype, dims, root); -1 on miss.
  int Lookup(const Request& req) const;
  int SlotByName(const std::string& name) const;
  const CacheSlot* Get(int slot) const;
  // Insert or update `name` (touching it); returns the slot used.  When a
  // full cache forced an eviction, *evicted holds the victim's old
  // contents (evicted->valid true) and the victim's slot is reused.
  int Put(const std::string& name, uint8_t op, uint8_t dtype,
          const std::vector<int64_t>& dims, int32_t root_rank,
          const Response& response, CacheSlot* evicted);
  void Touch(int slot);
  void Erase(const std::string& name);
  void Clear();

 private:
  int64_t capacity_ = 0;
  uint64_t touch_counter_ = 0;
  std::vector<CacheSlot> slots_;
  std::unordered_map<std::string, int> by_name_;
};

// One enqueued tensor awaiting negotiation + execution.
struct TableEntry {
  std::string name;
  uint8_t op = OP_ALLREDUCE;
  uint8_t dtype = HVD_FLOAT32;
  std::vector<int64_t> dims;
  const void* in = nullptr;
  void* out = nullptr;
  int root_rank = -1;
  bool average = false;
  // Point-to-point plane (docs/pipeline.md): the counterpart rank and
  // disambiguation tag for OP_SEND/OP_RECV entries (-1/0 otherwise).
  int32_t p2p_peer = -1;
  int32_t p2p_tag = 0;
  // Stage-group scoping for allreduce: the sorted member ranks this op is
  // restricted to (empty = whole world).  Carried per-entry, never as
  // persistent engine state — see wire.h Request.stage_ranks.
  std::vector<int32_t> stage_ranks;
  int64_t handle = -1;
  std::chrono::steady_clock::time_point enqueued_at;
  // Negotiation latency (enqueue -> response arrival), stamped when the
  // response pops this entry; -1 on error/drain paths.  Surfaced per
  // handle so Python can feed the negotiation_sec histogram for the
  // engine data plane too (the XLA plane times its own metadata ops).
  int64_t negotiation_us = -1;
};

class Engine {
 public:
  // Out-of-line so translation units that instantiate an Engine (the
  // simscale harness) never need the private Coordinator definition.
  Engine();
  ~Engine();

  // Starts the background thread and blocks until sockets are connected (or
  // failed).  Returns 0 on success; on failure err holds the reason.
  int Init(const EngineOptions& opts, std::string* err);
  void Shutdown();

  bool Initialized() const { return initialized_.load(); }
  // rank/size mirror opts_ through atomics: elastic reshapes mutate the
  // membership on the engine thread mid-run, and Python API threads read
  // hvd.rank()/hvd.size() live (they must re-resolve after a reshape).
  int rank() const { return cur_rank_.load(); }
  int size() const { return cur_size_.load(); }
  // Elastic reshapes re-resolve the local identity too (elastic is
  // single-host only, so post-reshape local == global); static jobs keep
  // their launch-time values.
  int local_rank() const { return cur_local_rank_.load(); }
  int local_size() const { return cur_local_size_.load(); }

  // Returns a handle (>=0) or -1 if the engine is not initialized / shut
  // down.  For allgather, `out` may be null; the result is kept engine-side
  // until CopyResult.  `average` divides the allreduce result by size.
  // `peer`/`tag` scope OP_SEND/OP_RECV entries to their counterpart rank
  // (docs/pipeline.md); `stage_ranks` scopes an allreduce to a stage
  // group's sorted member ranks (empty = whole world).
  int64_t Enqueue(uint8_t op, const std::string& name, const void* in,
                  void* out, const std::vector<int64_t>& dims, uint8_t dtype,
                  int root_rank, bool average, int peer = -1, int tag = 0,
                  const std::vector<int32_t>& stage_ranks = {});

  // 1 = done, 0 = pending, -1 = unknown handle.
  int Poll(int64_t handle);
  // Blocks until done; returns status code.
  int32_t Wait(int64_t handle);
  int32_t StatusOf(int64_t handle, std::string* error);
  // Completion stamps for a finished handle (-1 while pending / unknown).
  int64_t CompletionSeq(int64_t handle);
  int64_t CompletionTick(int64_t handle);
  // Negotiation latency (µs, enqueue -> response arrival) for a finished
  // handle; -1 while pending, unknown, or failed before negotiation.
  int64_t NegotiationUs(int64_t handle);
  // Number of fully processed response lists; a tick t is "closed" (all its
  // completions are visible, on every rank) once TicksDone() > t.
  int64_t TicksDone() const { return ticks_done_.load(); }
  int64_t ResultBytes(int64_t handle);
  int64_t ResultDim0(int64_t handle);
  bool CopyResult(int64_t handle, void* dst, int64_t nbytes);
  // Zero-copy view of a completed allgather's engine-owned result buffer;
  // valid until Release(handle).  nullptr while pending/absent.
  void* ResultPtr(int64_t handle);
  void Release(int64_t handle);

  // Stall observability (Python metrics registry, common/metrics.py):
  // cumulative count of stalled-tensor warnings emitted by the rank-0
  // sweep, and a bounded log of the most recent ones serialized as
  // "name|seconds;name|seconds" (names sanitized of the separators).
  int64_t StallEvents();
  std::string StallInfo();

  // Coordinated-abort observability: the latched abort status (0 = never
  // aborted; ST_RANKS_DOWN / ST_TIMEOUT otherwise) with its structured
  // message, and a process-cumulative abort-event count for the metrics
  // registry (survives engine re-init, like StallEvents).
  int32_t AbortCode() const { return abort_code_.load(); }
  std::string AbortMessage();
  int64_t AbortEvents() const { return abort_events_.load(); }

  // Cross-rank clock alignment (docs/timeline.md): this rank's estimated
  // clock offset relative to rank 0's engine epoch (µs; subtract from
  // local timeline ts to land on rank 0's clock) and the RTT of the
  // winning NTP-style probe (the error bound).  0 on rank 0 and at size 1.
  int64_t ClockOffsetUs() const { return clock_offset_us_.load(); }
  int64_t ClockRttUs() const { return clock_rtt_us_.load(); }

  // Announce-order observability (rank-0 coordinator; straggler
  // attribution, docs/troubleshooting.md): cumulative count of fully
  // negotiated collectives, per-rank last-to-announce counts serialized
  // as "n0,n1,...", and a bounded log of the most recent negotiations as
  // "cumulative_count:last_rank|skew_us;..." (skew = first -> last
  // announce; count and entries under one lock hold).  All counts
  // are process-cumulative (survive re-init, like StallEvents); the XLA
  // plane's __xp.* metadata negotiations feed them too, since they ride
  // this same coordinator.
  int64_t AnnounceEvents();
  std::string AnnounceLog();
  std::string LastAnnounceCounts();

  // Response-cache observability (docs/performance.md): hit = a drained
  // request announced as a cache bit, miss = a full string request sent
  // while the cache was enabled, eviction = a capacity-forced slot reuse.
  // Process-cumulative (survive re-init, like StallEvents); size is the
  // current entry count of this engine's cache.
  int64_t CacheHits() const { return cache_hits_.load(); }
  int64_t CacheMisses() const { return cache_misses_.load(); }
  int64_t CacheEvictions() const { return cache_evictions_.load(); }
  int64_t CacheSize() const { return cache_size_.load(); }

  // Online-autotuning observability (docs/performance.md#autotuning).
  // Current applied parameters come from the lockstep broadcasts, so
  // they are identical on every rank of a healthy job; the per-window
  // search history and best score live at the coordinator (rank 0).
  bool AutotuneEnabled() const { return opts_.autotune; }
  bool AutotuneFrozen() const { return autotune_frozen_.load(); }
  // Rank 0: completed tuning windows; workers: the window count carried
  // by the last applied broadcast (equal once the search freezes).
  int64_t AutotuneWindows();
  int64_t CurrentFusionThreshold() const { return cur_fusion_.load(); }
  int64_t CurrentCycleTimeUs() const { return cur_cycle_us_.load(); }
  int64_t CurrentCrossAlgoThreshold() const {
    return cur_cross_algo_.load();
  }
  double AutotuneBestScore() { return tuner_.best_score(); }
  // Rank 0 search history: "window|fusion|cycle_us|score;...".
  std::string AutotuneHistory() { return tuner_.History(); }
  // Per-rank applied-parameter log, "tick|fusion|cycle_us|frozen;..." —
  // identical on every rank (the lockstep determinism contract; tests
  // allgather and compare it).
  std::string AutotuneApplied();
  // Manual parameter injection (hvd.autotune_set, rank 0 only): broadcast
  // `fusion` / `cycle_ms` / `compression` / `cross_algo` (< 0 keeps the
  // current value) next tick.  Returns 0 ok, 1 off the coordinator, 2
  // uninitialized.
  int AutotuneInject(int64_t fusion, double cycle_ms, int64_t compression,
                     int64_t cross_algo);
  // Fusion threshold in force at engine tick `tick` (the XLA plane's
  // bucket boundaries must follow autotuned thresholds in lockstep;
  // jax/eager_mesh.py).  Past ticks are stable: the history is
  // append-only with increasing tick stamps.
  int64_t FusionThresholdAt(int64_t tick);

  // Wire-compression observability (docs/performance.md#wire-compression).
  // The applied mode mirrors opts_ through an atomic (lockstep broadcasts
  // mutate it on the engine thread; Python API threads read it live);
  // CompressionModeAt serves the XLA plane's per-tick lockstep lookup the
  // way FusionThresholdAt does for bucket boundaries.  The byte/op
  // counters are process-cumulative (survive re-init, like StallEvents);
  // wire bytes count every allreduce bucket at its wire width and payload
  // bytes at the caller dtype's width, so the pair exposes both the
  // compression win and the legacy half-staging inflation.
  uint8_t CompressionModeNow() const {
    return static_cast<uint8_t>(cur_compression_.load());
  }
  int64_t CompressionModeAt(int64_t tick);
  // "wire|payload|ops_none|ops_bf16|ops_fp8|residual_bytes|
  //  residual_tensors|min_bytes" for the Python metrics sync.
  std::string CompressionInfo();
  // Bounded per-bucket decision log, "first_name|mode;..." in execution
  // order — identical on every rank of a healthy job (tests allgather
  // and compare it across cache replay and reshapes).
  std::string CompressionLog();

  // Two-level topology observability (docs/performance.md
  // #two-level-topology).  TopologyInfo serializes
  // "hier|nodes|local_size|threshold|ops_ring|ops_tree|local_bytes|
  //  cross_bytes|log_total" for the Python metrics sync: the cumulative
  // per-phase byte counters split by hop (local = intra-node ring, cross
  // = the DCN hop — the bytes the compression satellite claims shrink),
  // ring/tree bucket counts, and the cumulative per-bucket log count so
  // the Python side can delta-consume TopologyLog.  TopologyLog is the
  // bounded per-bucket phase record
  // "name|algo|local_rs_us|cross_us|local_ag_us;..." feeding the phase
  // histograms.
  std::string TopologyInfo();
  std::string TopologyLog();

  // Control-plane observability (docs/performance.md
  // #control-plane-scaling).  ControlInfo serializes
  // "tree|children|hosts|steady_active|pattern_len|steady_threshold|
  //  entries|exits|replays|steady_cycles|negotiated_ticks|frames_sent|
  //  frames_recv" for the Python metrics sync: the tree shape this rank
  // sees (children = control sockets it reads each tick), the
  // decentralized-steady-state counters (process-cumulative, like
  // StallEvents), and the control-frame counters the zero-frames-per-
  // steady-cycle contract is asserted against.
  std::string ControlInfo();
  bool SteadyActive() const { return steady_active_.load(); }
  int64_t CtrlFramesSent() const { return ctrl_frames_sent_.load(); }

  // Liveness observability (docs/fault-tolerance.md#failure-detection).
  // Serializes
  // "hb_ms|hb_miss|sent|recv|miss_events|evictions|clock_fanin|
  //  peer:age_us:misses peer:age_us:misses" — the detector config, the
  // process-cumulative heartbeat counters (StallEvents contract), rank
  // 0's init clock-sync probe fan-in (O(direct children), the tree-relay
  // satellite's assert surface), and the per-monitored-peer last-seen
  // age + consecutive-miss count at snapshot time.  Empty peer tail when
  // the detector is off (HVD_TPU_HEARTBEAT_MS=0 or size 1).
  std::string LivenessInfo();

  // Point-to-point plane observability (docs/pipeline.md,
  // docs/metrics.md#p2p).  Serializes
  // "sends|recvs|bytes_out|bytes_in|matched|unmatched|group_ops|channels"
  // — process-cumulative send/recv completions and payload bytes
  // (StallEvents contract), the matched-pair count, the live
  // unmatched gauge (this rank's announced-but-unpaired p2p entries),
  // stage-group allreduce count, and the number of dedicated lazy p2p
  // channels currently dialed.
  std::string P2pInfo();

  // Perf-introspection plane (docs/metrics.md#links / #anomalies).
  // LinkInfo passes through the transport layer's per-peer telemetry
  // (net.h NetLinkInfo: bytes, timed-send latency histogram, stall /
  // short-write counts, heartbeat-echo RTT).  AnomalyInfo serializes the
  // online detector's config + process-cumulative verdict counts as
  // "sigma|interval_ms|slow_link|straggler|cache_degraded|slow_phase";
  // AnomalyLog the bounded verdict log as
  // "kind|subject|detail|age_us;..." (newest last, separators sanitized
  // out of details) — the registry mirrors it whole, it is small.
  std::string LinkInfo();
  std::string AnomalyInfo();
  std::string AnomalyLog();

  // Elastic-membership observability (docs/fault-tolerance.md).  The
  // epoch counts reshapes survived by THIS engine lifetime (0 until the
  // first); reshape/lost/joined totals are process-cumulative like
  // StallEvents.  MembershipInfo serializes
  // "epoch|size|lost_csv|joined_csv" (cumulative rank lists, each in the
  // numbering of the epoch the change happened in).
  bool ElasticEnabled() const { return opts_.elastic; }
  int64_t MembershipEpoch() const { return membership_epoch_.load(); }
  int64_t ReshapeEvents() const { return reshapes_total_.load(); }
  std::string MembershipInfo();
  // Python acknowledges a reshape after resyncing state (hvd.run_elastic):
  // until then every fresh Enqueue fails fast with the retryable
  // ST_RESHAPE status, so no rank can stall waiting for peers that are
  // re-entering agreement.
  void MembershipAck() { reshape_ack_pending_.store(false); }
  bool ReshapeAckPending() const { return reshape_ack_pending_.load(); }

  // Flight recorder (postmortem plane, flight.h): the always-on bounded
  // ring of recent control-plane events this rank recorded.  Exposed so
  // c_api can serve the ring snapshot and cumulative event count to the
  // Python postmortem writer and the metrics registry.
  FlightRecorder& flight() { return flight_; }

  // Pending-tensor observability (postmortem dumps).  PendingInfo: THIS
  // rank's in-flight collectives as "name|op|age_us;..." (what was
  // enqueued but not completed when the dump was taken).  CoordPendingInfo
  // (rank 0): the coordinator's waiting-on view as
  // "name|age_us|missing_rank missing_rank ...;..." — which ranks each
  // stalled negotiation is still waiting for.  Both bounded and
  // separator-sanitized; CoordPendingInfo is a snapshot the engine thread
  // refreshes each tick (the coordinator tables are engine-thread-only).
  std::string PendingInfo();
  std::string CoordPendingInfo();

  // Cross-rank stall diagnosis: on the ST_TIMEOUT / ST_RANKS_DOWN abort
  // paths the coordinator aggregates its per-rank waiting-on knowledge
  // (last announce, last control frame) into a one-paragraph story that
  // rides the broadcast abort message — Diagnosis() returns that
  // paragraph (empty when no abort, or the abort carried none).
  std::string Diagnosis();

  // The engine-owned Chrome-tracing timeline.  Exposed so the XLA data
  // plane (Python, jax/eager_mesh.py) can emit its BUCKET_BUILD /
  // XLA_DISPATCH / DEVICE_WAIT activities into the SAME trace file as the
  // engine's NEGOTIATE/op events (the reference wraps every execution
  // phase, operations.cc:680-692).  Timeline methods are internally
  // mutex-guarded and no-ops when the timeline is disabled.
  Timeline& timeline() { return timeline_; }

 private:
  struct Coordinator;  // rank-0 only state

  void BackgroundLoop();
  bool RunLoopOnce();
  bool SetupSockets(std::string* err);
  // Transport seam bring-up (end of SetupSockets): wrap every topology
  // fd in a Channel, and — when the job-wide HVD_TPU_SHM agreement armed
  // shm — create/attach the per-node segment via a token relay over the
  // node-local ring sockets and point the local-ring channels at its
  // rings.  Chaos clauses naming an in-node link demote to TCP (auto) or
  // fail init with a typed error naming the clause (force / unsupported
  // drop-flaky shapes).
  bool SetupShmTransport(std::string* err);
  // Standby path (opts_.rejoin): connect to the coordinator, announce the
  // data endpoint, block until the admitting reshape broadcast arrives,
  // adopt the new membership, and build the ring.
  bool SetupRejoinSockets(std::string* err);
  void TeardownSockets();
  // Elastic membership (docs/fault-tolerance.md#elastic-membership).
  // Rank 0: drain pending joiner connects off the control listen socket
  // (non-blocking; a standby announces itself with a JOIN hello + an
  // endpoint frame).
  void CoordinatorAcceptJoiners();
  // Rank 0: finish a joiner's registration by assembling the rest of its
  // JOIN handshake (hello word already consumed) with bounded,
  // non-blocking reads — a partial frame can stall this at most
  // timeout_sec, never indefinitely.  False (fd NOT adopted; caller
  // closes) on a short/duplicate handshake.
  bool RegisterJoiner(int fd, double timeout_sec);
  // Rank 0: park a fully-handshaken joiner (endpoint already parsed) for
  // the next reshape barrier.  False (fd NOT adopted; caller closes) on
  // a duplicate endpoint.
  bool RegisterJoinerEndpoint(int fd, const std::string& ep);
  // Rank 0: whether this tick can be the reshape barrier (a death is
  // pending, or quiesced joiners await admission) and, if so, fill `out`
  // with the reshape verdict + new membership.
  bool CoordinatorMaybeReshape(ResponseList* out);
  // Every rank: adopt the broadcast membership — fail in-flight
  // collectives with the retryable ST_RESHAPE status, clear the response
  // cache and autotune search, update rank/size/endpoints, and rebuild
  // the data-plane ring.  On rebuild failure the engine falls back to a
  // fatal local abort (the launcher's restart path takes over).
  bool ApplyReshape(const ResponseList& rl);
  // Tear down and reconnect the flat ring for the current membership,
  // with epoch-tagged hellos so stale pre-reshape connects are rejected.
  bool RebuildRing(std::string* err);
  // NTP-style clock sync over the coordinator star (end of SetupSockets):
  // rank 0 probes each worker K times; the minimum-RTT round trip gives
  // the best offset estimate (worker_ts - probe midpoint), which rank 0
  // sends back so every rank knows its own offset.  Runs at every Init,
  // so restart epochs re-align too.
  bool ClockSync(std::string* err);
  int64_t EpochNowUs() const;
  // Rank 0: one negotiation reached full count; `last_rank` announced
  // last, `skew_us` first -> last announce (tree aggregates forward the
  // true per-rank announce timestamps, so the verdict names the true
  // straggler, not the sub-coordinator whose frame closed the count).
  void RecordAnnounce(int last_rank, int64_t skew_us);

  // Coordinator (rank 0) helpers.
  void CoordinatorHandle(const RequestList& rl, int from_rank);
  // One full string request (shared by wire requests and the synthesized
  // ones below).  `announce_ts` is the announce time on rank 0's clock
  // (µs since epoch); < 0 stamps on arrival (the direct-star form).
  void HandleOneRequest(const Request& req, int from_rank,
                        int64_t announce_ts = -1);
  // Response-cache coordination: count one rank's cache-bit announcements
  // (full count -> a broadcast hit); convert any bits still pending for
  // `name`'s slot back into full synthesized requests (a peer fell back
  // to string negotiation — renegotiation or cross-transport split — so
  // validation must see every rank); drain a capacity-evicted slot's
  // orphaned bits the same way.
  void CoordinatorHandleBits(const std::vector<uint32_t>& bits,
                             int from_rank);
  // One cache-bit announcement from one rank (the per-rank granule the
  // wire bits and the tree's aggregated BitGroups both decompose into).
  void HandleOneBit(uint32_t bit, int from_rank, int64_t announce_ts);
  void CoordinatorDrainBitsFor(const std::string& name);
  void CoordinatorDrainSlot(int slot, const CacheSlot& contents);
  // The request rank `rank` would have sent for the cached collective
  // (per-rank dim0 restored from the stored allgather geometry).
  Request SynthesizeFromSlot(const CacheSlot& slot, int rank) const;
  // Replay broadcast cache hits in order, re-fusing consecutive
  // same-dtype allreduces like the coordinator does for fresh responses.
  void ProcessCacheHits(const std::vector<uint32_t>& hits);
  ResponseList CoordinatorTick();
  Response BuildResponse(const std::string& name);
  // Decentralized steady state (docs/performance.md
  // #control-plane-scaling).  CoordinatorMaybeSteady runs after the tick
  // built its outgoing list: it feeds the cache-hit slot stream into the
  // pattern detector and, at a quiesced cycle boundary with the pattern
  // repeated `steady_threshold` times, stamps the STEADY verdict onto
  // the list.  ApplySteady arms self-clocked replay on every rank while
  // processing that (identical) list.
  void CoordinatorMaybeSteady(ResponseList* out);
  void ApplySteady(const ResponseList& rl);
  // One self-clocked pass of the engine loop while steady state is
  // armed: replay pattern-matching queue entries group by group with
  // zero control-plane frames, poll the parent socket for abort/shutdown
  // frames, and fall back to full negotiation on any miss.  Returns
  // false when the loop must exit (abort/shutdown).
  bool SteadyLoopOnce();
  // Leave steady state locally (miss, shutdown, defensive broadcast):
  // requeue un-replayed requests and resume per-tick frames.
  void ExitSteadyLocal(const std::string& reason);
  // Rank 0: note a rank's steady exit (frames resume only once ALL ranks
  // exited — broadcasting earlier would double-execute replays on ranks
  // still self-clocking).
  void NoteSteadyExit(int r);
  // Flight-record a steady-exit marker's miss coordinates (epoch/pos) as
  // the frame passes this node — the per-rank postmortem rings locate
  // the miss even though the aggregate's exit list carries only ranks.
  void NoteChildSteadyExit(const RequestList& frame, int child_rank);
  // Bounded wait for the parent's next broadcast, cascaded by tree
  // depth: rank 0 may legitimately block ~2T+5 probing a frozen
  // sub-coordinator before its verdict goes out, a sub must outwait
  // that, and a leaf must outwait its sub — equal bounds at every level
  // would expire downstream just before the true verdict arrives and
  // misblame the parent.
  double ParentWaitSec() const;
  bool AllSteadyExited() const;
  // Rank 0, steady/holding mode: drain whatever control frames arrived
  // without blocking (fallback announcements, steady exits, EOFs),
  // escalate deadline breaches, and broadcast an armed abort
  // immediately.  Returns false when the loop must exit.
  bool CoordinatorSteadyPoll();
  // Rank 0, steady/holding mode, elastic only: when a death armed the
  // reshape barrier (or a standby is waiting) while ranks self-clock
  // with the control plane dark, broadcast an empty revocation list —
  // self-clocking ranks treat any payload broadcast as a revocation —
  // and fall back to the normal loop so the barrier fires on the next
  // regular tick through the tested CoordinatorMaybeReshape path.
  // Returns 0 (nothing to do), 1 (revoked; end this steady pass), or
  // -1 (fatal; exit the loop).
  int MaybeRevokeSteadyForReshape();
  // Sub-coordinator, steady/holding mode: forward children's fallback
  // frames upward as aggregates and relay any parent broadcast down.
  // Returns false when the loop must exit.
  bool SubRelayPass();
  // Common tail every rank runs on a received/built broadcast list.
  bool ProcessResponseList(ResponseList& responses,
                           const RequestList& my_requests,
                           std::chrono::steady_clock::time_point tick_start);
  void CheckForStalledTensors();
  // Every-tick deadline sweep (rank 0): escalates a stall beyond
  // opts_.collective_timeout_sec to a coordinated abort.
  void CheckCollectiveTimeout();
  // Latch the abort status locally (any rank).  The BackgroundLoop exit
  // drain then fails everything pending with this status instead of the
  // generic shutdown message.
  void AbortLocal(int32_t code, const std::string& message);
  // Rank 0: record a dead/unresponsive worker (`reason` says which) and,
  // on the first death, arm the coordinated abort naming the missing
  // ranks and the tensors they left pending.
  void MarkRankDead(int r, const std::string& reason);

  // Data-plane heartbeat failure detector (docs/fault-tolerance.md
  // #failure-detection).  A dedicated monitor thread exchanges 16-byte
  // typed beacons with BOTH ring neighbours over dedicated data-listener
  // connections on the HVD_TPU_HEARTBEAT_MS cadence, entirely off the
  // engine tick — a busy local ring cannot starve them, and a frozen
  // peer's silence is observed in O(heartbeat) instead of
  // O(collective-timeout).  The monitor NEVER touches control sockets or
  // engine state: past HVD_TPU_HEARTBEAT_MISS silent intervals it
  // records the miss, wakes the engine thread (ShutdownFd on the shared
  // data fds), and queues the verdict for the engine thread to escalate
  // through the existing machinery (MarkRankDead on rank 0; an
  // out-of-band hb_report control frame on workers; a local typed abort
  // when the report path itself is dark — the partition case).
  void HeartbeatLoop();
  // Stop + join the monitor and close the beat sockets (Teardown).
  void StopHeartbeatMonitor();
  // Engine thread, rank 0: drain monitor-flagged peers into MarkRankDead.
  void CoordinatorDrainHeartbeatDeaths();
  // Engine thread, workers: flush monitor-flagged peers upward as an
  // out-of-band hb_report RequestList on `fd` (the parent control
  // socket).  The frame carries ONLY dead_ranks — the receiver processes
  // it and keeps waiting for this rank's real tick frame, preserving the
  // send-one-wait-one alternation.  False on send failure.
  bool SendHeartbeatReports(int fd);
  // Sliced replacement for the blocking parent WaitReadable: flushes
  // pending heartbeat reports between ~50ms slices and returns false
  // early when the monitor latched a local abort.  Plain WaitReadable
  // when the detector is off.
  bool WaitParentSliced(int fd, double total_sec);
  // Engine thread: when the monitor armed the local-abort verdict (its
  // report window expired with the control plane equally dark), latch
  // the typed abort here — AbortLocal clears the response cache, which
  // is not safe from the monitor thread.  True when it aborted.
  bool CheckHeartbeatLocalAbort();

  // Online anomaly detector (docs/metrics.md#anomalies).  A second
  // off-the-tick monitor thread (the HeartbeatLoop pattern) sweeps the
  // observability counters every HVD_TPU_ANOMALY_INTERVAL_MS: per-link
  // timed-send latency (cross-sectional robust baseline — each link's
  // level against the median + MAD of ALL links, so a link that is slow
  // FROM INIT still stands out), per-rank last-to-announce shares
  // (rank 0), response-cache hit rate, and per-phase topology timing
  // (temporal self-baselines).  A sustained excursion past
  // HVD_TPU_ANOMALY_SIGMA robust deviations emits one typed verdict per
  // episode through EmitAnomaly.  Reads only atomics, the net.h link
  // accessor, and mutex-guarded logs — never engine-thread state.
  void AnomalyLoop();
  void StopAnomalyMonitor();
  // Append a verdict: bounded log + cumulative count (anomaly_mu_), an
  // FL_ANOMALY flight event, and an ANOMALY timeline instant.
  void EmitAnomaly(int kind, const std::string& subject,
                   const std::string& detail);

  // Online autotuning (docs/performance.md#autotuning).  AttachTunedParams
  // runs at the coordinator after CoordinatorTick: it gives the
  // ParameterManager its per-tick chance to close a window / flush a
  // manual injection, and folds the proposal into the outgoing response
  // list.  ApplyTunedParams runs on EVERY rank while processing that
  // (identical) list, before cache-hit replay, so fusion-plan changes
  // take effect at the same tick boundary everywhere.
  void AttachTunedParams(ResponseList* out);
  void ApplyTunedParams(const ResponseList& rl);

  // Execution.  `from_cache` marks a replayed response: its cache slot was
  // already touched by ProcessCacheHits, so skip the (re-)insert.
  void PerformOperation(const Response& resp, bool from_cache = false);
  void ExecuteAllreduce(const Response& resp,
                        std::vector<TableEntry>& entries);
  void ExecuteAllgather(const Response& resp, TableEntry& e);
  void ExecuteBroadcast(const Response& resp, TableEntry& e);
  // Point-to-point plane (docs/pipeline.md).  ExecuteSendRecv moves one
  // matched pair's payload over the p2p channel toward the counterpart:
  // fp32 payloads honour the response's negotiated wire compression with
  // per-name error feedback (the allreduce residual contract), so
  // repeated micro-batch sends never accumulate rounding drift.
  void ExecuteSendRecv(const Response& resp, TableEntry& e);
  // Stage-scoped allreduce (DP inside one pipeline stage): leader
  // gather-reduce-broadcast over p2p channels among resp.stage_ranks.
  // O(G * bytes) at the leader — fine for the small per-stage DP groups
  // pipeline parallelism produces; the global ring stays untouched.
  void ExecuteGroupAllreduce(const Response& resp,
                             std::vector<TableEntry>& entries);
  // The channel to `peer`, picked identically on both ends: an existing
  // topology channel when the peer is a fabric neighbour (node-local shm
  // ring / cross ring / global ring), else a dedicated TCP connection
  // dialed lazily at first use (lower rank connects with a kHelloP2P
  // hello, higher rank accepts on the data listener — deterministic,
  // because both ends execute the same broadcast response at the same
  // list position).  nullptr + *err on dial failure.
  const Channel* GetP2pChannel(int peer, std::string* err);
  // Drop every dedicated p2p channel (Teardown + reshape: the membership
  // renumbered, so cached peer fds are stale).
  void CloseP2pChannels();
  void CompleteEntry(const TableEntry& e, int32_t code,
                     const std::string& error);

  // Wire compression (docs/performance.md#wire-compression).  The
  // coordinator (and the lockstep cache replay) choose a bucket's wire
  // format from the applied mode, the payload dtype, and the bucket's
  // payload byte size; engine thread only.
  uint8_t ChooseCompression(uint8_t dtype, int64_t bytes) const;
  // Record one executed allreduce bucket for the compression metrics and
  // the per-bucket decision log.
  void RecordCompressedOp(const std::string& name, uint8_t mode,
                          int64_t payload_bytes, int64_t wire_bytes);

  // Data plane primitives (ring over TCP).
  bool RingAllreduce(void* buf, int64_t count, uint8_t dtype,
                     std::string* err);
  // Compressed ring allreduce: the local buffer stays f32 (reduction
  // accumulates in f32 at every hop) while segments cross the wire in
  // `wire` format (f16/bf16/fp8) — compress on send, decompress on
  // receive.  Recompression of already-quantized values is exact, so the
  // allgather phase loses nothing beyond the per-hop quantization the
  // format implies.
  bool RingAllreduceWire(float* buf, int64_t count, uint8_t wire,
                         int N, int index, const Channel& left,
                         const Channel& right, std::string* err);
  // Ring allreduce over an arbitrary participant ring (used for both the
  // global ring and the per-shard cross-node rings).
  bool RingAllreduceOn(void* buf, int64_t count, uint8_t dtype, int n,
                       int index, const Channel& left, const Channel& right,
                       std::string* err);
  // Two-level allreduce (docs/performance.md#two-level-topology): local
  // reduce-scatter over the node ring -> every local rank drives a
  // cross-node exchange (ring or recursive-doubling tree) over its own
  // 1/local_size shard -> local allgather, with the chunks of one bucket
  // pipelined through the three phases (a helper thread drives the cross
  // hop while the engine thread keeps the local ring busy).  `dtype` is
  // the REDUCTION buffer's element type (f32 master for the wire-staged
  // path; the native dtype for int/f64 payloads); `local_wire` /
  // `cross_wire` narrow the respective hop's bytes (255 = raw dtype;
  // != 255 requires an f32 buffer): halves ship native-width on BOTH
  // hops, lossy compression applies to the cross (DCN) hop only.
  bool TwoLevelAllreduce(void* buf, int64_t count, uint8_t dtype,
                         uint8_t local_wire, uint8_t cross_wire,
                         bool use_tree, const std::string& name,
                         std::string* err);
  // One chunk's node-local ring steps (engine thread).  After
  // LocalReduceScatter local rank r owns fully reduced segment
  // (r+1) % local_size; LocalAllgather redistributes the reduced
  // segments.  `bytes_moved` accumulates this rank's sent wire bytes.
  bool LocalReduceScatter(char* data, int64_t n, uint8_t dtype,
                          uint8_t wire, int64_t* bytes_moved,
                          std::string* err);
  bool LocalAllgather(char* data, int64_t n, uint8_t dtype, uint8_t wire,
                      int64_t* bytes_moved, std::string* err);
  // One chunk's cross-node hop over this rank's shard (helper thread):
  // ring (RingAllreduceOn / RingAllreduceWire over the cross fds) or
  // recursive-doubling tree over the XOR-partner fds.
  bool CrossShardAllreduce(char* seg, int64_t n, uint8_t dtype,
                           uint8_t wire, bool use_tree,
                           int64_t* bytes_moved, std::string* err);
  bool CrossTreeAllreduce(char* seg, int64_t n, uint8_t dtype,
                          uint8_t wire, std::string* err);
  // Wake every peer blocked on this rank's topology sockets (local ring,
  // cross ring, tree partners) and mark them unusable: a mid-collective
  // failure must fail fast everywhere instead of stalling peers to the
  // 30s exchange timeout.  Close happens after helper threads joined.
  void ShutdownTopologyFds();
  void CloseTopologyFds();
  bool RingAllgather(char* buf, const std::vector<int64_t>& block_bytes,
                     std::string* err);
  bool RingBroadcast(void* buf, int64_t nbytes, int root, std::string* err);

  EngineOptions opts_;
  std::atomic<bool> initialized_{false};
  std::atomic<bool> shut_down_{false};
  // Latched on the first data-plane transport failure (ring or
  // hierarchical): a broken fabric must fail *every* subsequent
  // collective uniformly, not leave a half-functional job where
  // allreduce errors but broadcast/allgather still succeed.
  std::atomic<bool> data_plane_failed_{false};
  std::atomic<bool> loop_exited_{false};
  std::thread background_;

  std::mutex mu_;  // guards queue_, table_, handles_ map shape
  std::deque<Request> queue_;
  // Wakes the engine thread's steady-state idle wait the moment work
  // arrives: with the control plane dark there is no frame round trip
  // pacing the loop, and a blind poll cadence would either burn CPU
  // (hundreds of simulated ranks in one process) or add its period to
  // every replay cycle's latency.
  std::condition_variable queue_cv_;
  std::unordered_map<std::string, TableEntry> table_;

  std::mutex handles_mu_;
  std::unordered_map<int64_t, std::shared_ptr<HandleStatus>> handles_;
  std::atomic<int64_t> next_handle_{0};
  std::atomic<int64_t> completions_{0};  // CompleteEntry stamp counter
  std::atomic<int64_t> ticks_done_{0};   // processed response lists

  // Sockets.
  int coord_listen_fd_ = -1;                 // rank 0
  std::vector<int> coord_fds_;               // rank 0: fd per worker rank
  int coord_fd_ = -1;                        // workers: fd to rank 0
  // Control-plane coordinator tree (docs/performance.md
  // #control-plane-scaling).  Built by SetupSockets after the job-wide
  // layout agreement: non-lead workers of nodes >= 1 re-home their
  // control socket from rank 0 to their node's local-rank-0
  // (sub-coordinator), which accepted them over its DATA listener with a
  // typed hello — no extra endpoints.  Rank 0 keeps sockets only for its
  // own node's workers plus one per sub-coordinator.
  bool tree_enabled_ = false;   // this job agreed on the two-level tree
  bool is_sub_coord_ = false;   // local_rank 0 of a node >= 1
  std::vector<int> tree_child_fds_;    // sub: fd per local worker (1..L-1)
  std::vector<int> tree_child_ranks_;  // global rank per child fd
  std::vector<bool> tree_child_dead_;
  std::vector<int> coord_children_;    // rank 0: global ranks it reads
  // Sub-coordinator relay bookkeeping: deaths observed but not yet
  // forwarded, and whether this sub is in the steady/holding relay mode
  // (between its own steady exit and the next parent broadcast).
  std::vector<int32_t> pending_dead_reports_;
  bool sub_holding_ = false;

  // Decentralized steady state (engine-thread state unless atomic).
  std::atomic<bool> steady_active_{false};
  std::vector<uint32_t> steady_pattern_;
  std::vector<uint32_t> steady_groups_;   // per-replay-group sizes
  size_t steady_pos_ = 0;                 // next expected pattern index
  size_t steady_group_idx_ = 0;           // current group
  int64_t steady_epoch_ = 0;              // completed cycles this window
  std::vector<uint32_t> steady_pending_group_;  // drained, not yet replayed
  std::vector<Request> steady_pending_reqs_;    // their Requests (for requeue)
  std::chrono::steady_clock::time_point steady_group_wait_{};
  bool steady_exit_pending_ = false;  // next frame carries the exit flag
  int steady_idle_passes_ = 0;        // backoff state for the idle wait
  // Last control-socket duty pass: the duty rides the idle cadence, but
  // a pipeline that keeps the queue non-empty on every pass must still
  // see abort/shutdown frames within a bounded interval.
  std::chrono::steady_clock::time_point steady_last_poll_{};
  int64_t steady_exit_epoch_ = 0;
  int64_t steady_exit_pos_ = 0;
  // Control-plane metrics (process-cumulative, like StallEvents; the
  // atomics are read live by Python API threads).
  std::atomic<int> ctrl_children_{0};
  std::atomic<int> ctrl_hosts_{1};
  std::atomic<int64_t> ctrl_frames_sent_{0};
  std::atomic<int64_t> ctrl_frames_recv_{0};
  std::atomic<int64_t> steady_entries_{0};
  std::atomic<int64_t> steady_exits_{0};
  std::atomic<int64_t> steady_replays_{0};
  std::atomic<int64_t> steady_cycles_{0};
  std::atomic<int64_t> steady_pattern_len_{0};
  std::atomic<int64_t> negotiated_ticks_{0};
  int data_listen_fd_ = -1;
  int left_fd_ = -1, right_fd_ = -1;         // ring neighbours
  // Two-level topology (only when opts_.hierarchical_allreduce):
  int node_id_ = 0;                          // rank / local_size
  int n_nodes_ = 1;                          // size / local_size
  int local_left_fd_ = -1, local_right_fd_ = -1;  // node-local ring
  // EVERY local rank's own cross-node ring over its same-local-rank
  // peers (node±1, same local_rank) — local_size parallel DCN streams
  // instead of one leader NIC.
  int cross_left_fd_ = -1, cross_right_fd_ = -1;
  // Recursive-doubling partners for the tree exchange: fd per XOR level
  // (peer node = node_id ^ (1 << k)).  Built only when n_nodes is a
  // power of two; empty otherwise (tree requests fall back to the ring).
  std::vector<int> cross_tree_fds_;

  // Pluggable transport seam (docs/performance.md#transport).  Channels
  // wrap the fds above; the node-local pair additionally carries shm
  // rings when the segment armed.  shm_mode_/shm_ring_bytes_ come from
  // HVD_TPU_SHM / HVD_TPU_SHM_RING_BYTES; shm_agreed_ is the job-wide
  // init-agreement verdict (every rank must request the same mode, and
  // the topology must be two-level + non-elastic); shm_active_ is the
  // post-rendezvous truth for THIS node's ring.  topo_shm_ mirrors it
  // for lock-free TopologyInfo reads.
  ShmMode shm_mode_ = ShmMode::kAuto;
  int64_t shm_ring_bytes_ = 1 << 20;
  bool shm_agreed_ = false;
  bool shm_active_ = false;
  std::atomic<bool> topo_shm_{false};
  ShmSegment shm_seg_;
  Channel left_ch_, right_ch_;              // flat/global ring
  Channel local_left_ch_, local_right_ch_;  // node-local ring (shm-capable)
  Channel cross_left_ch_, cross_right_ch_;  // cross-node shard ring

  // Point-to-point plane (docs/pipeline.md).  Dedicated lazy channels to
  // non-neighbour peers, keyed by peer rank; engine thread only.  The
  // counters are process-cumulative (StallEvents contract) except the
  // matched/unmatched gauges, which Python reads live.
  std::unordered_map<int, Channel> p2p_chans_;
  std::atomic<int64_t> p2p_sends_{0};
  std::atomic<int64_t> p2p_recvs_{0};
  std::atomic<int64_t> p2p_bytes_out_{0};
  std::atomic<int64_t> p2p_bytes_in_{0};
  std::atomic<int64_t> p2p_matched_{0};
  std::atomic<int64_t> p2p_group_ops_{0};
  // Open dedicated-channel gauge (p2p_chans_ is engine-thread-only; the
  // Python metrics reader sees this atomic mirror instead).
  std::atomic<int64_t> p2p_channels_{0};

  // Data-plane heartbeat detector state.  The beat fds ride the data
  // listener (typed hello kind 6) to this rank's ring neighbours: rank r
  // dials (r+1)%size (beat_out_fd_) and accepts (r-1+size)%size
  // (beat_in_fd_); both sockets are full-duplex, so the monitor beats on
  // and watches BOTH.  hb_mu_ guards every non-atomic field below — the
  // monitor thread copies the fds/epoch under it each pass, the engine
  // thread swaps them there at a reshape (old fds are shut down, parked
  // in hb_graveyard_, and closed by the MONITOR on its next pass: the
  // fd numbers stay allocated until the only thread that might still
  // poll them has moved on).
  std::mutex hb_mu_;
  int beat_in_fd_ = -1, beat_out_fd_ = -1;
  int beat_in_peer_ = -1, beat_out_peer_ = -1;
  int64_t hb_epoch_ = 0;  // beats carry it; stale-epoch beats are ignored
  std::vector<int> hb_graveyard_;
  // Monitor-observed liveness per monitored peer rank: last-seen stamp
  // (µs on the engine epoch clock; 0 = never) and consecutive misses.
  std::unordered_map<int, int64_t> hb_last_seen_us_;
  std::unordered_map<int, int> hb_miss_counts_;
  // Monitor -> engine-thread escalation queues (hb_mu_):
  std::vector<int> pending_hb_dead_;    // rank 0: MarkRankDead these
  std::vector<int> pending_hb_report_;  // workers: hb_report these up
  // Data-plane fds the monitor may ShutdownFd when it flags a peer, so a
  // survivor blocked in a ring Exchange with the frozen rank wakes in
  // O(heartbeat) instead of hanging.  Engine-maintained under hb_mu_ and
  // CLEARED there before any CloseFd of a listed fd, so the monitor can
  // never shut down a recycled fd number.  hb_ctrl_wake_fd_ is this
  // rank's coordinator/parent control fd, shut down only at the
  // local-abort escalation (the engine is then parked in a parent wait
  // that must break before it can surface the typed verdict).
  std::vector<int> hb_wake_fds_;
  // Shm analogue of the wake registry: when the node segment is armed
  // the monitor also closes its rings (CloseRings) so a survivor blocked
  // in a shm drive loop wakes as fast as one blocked in a socket.
  // Cleared (under hb_mu_) before the segment is unmapped.
  ShmSegment* hb_wake_shm_ = nullptr;
  int hb_ctrl_wake_fd_ = -1;
  std::string hb_local_abort_msg_;
  std::atomic<bool> hb_local_abort_{false};
  std::atomic<bool> hb_stop_{false};
  std::thread hb_thread_;
  int hb_interval_ms_ = 0;  // 0 = detector off (env HVD_TPU_HEARTBEAT_MS)
  int hb_miss_limit_ = 10;  // env HVD_TPU_HEARTBEAT_MISS
  // Process-cumulative liveness counters (StallEvents contract).
  std::atomic<int64_t> hb_sent_{0};
  std::atomic<int64_t> hb_recv_{0};
  std::atomic<int64_t> hb_miss_events_{0};
  std::atomic<int64_t> hb_evictions_{0};
  // Rank 0: clock-sync probe fan-in of the last Init (number of peers
  // rank 0 probed directly — O(hosts) under the tree relay, O(ranks)
  // in the flat star).
  std::atomic<int64_t> clock_fanin_{0};

  // Online anomaly detector (docs/metrics.md#anomalies).  Sweep state
  // (windows, baselines) lives as AnomalyLoop locals — single-threaded,
  // no locking; only the verdict surface below is shared.  Verdict
  // counts are process-cumulative (StallEvents contract); the log is
  // bounded at 64 entries so an unread registry cannot grow it.
  std::thread anomaly_thread_;
  std::atomic<bool> anomaly_stop_{false};
  int anomaly_sigma_ = 5;         // env HVD_TPU_ANOMALY_SIGMA; 0 = off
  int anomaly_interval_ms_ = 500; // env HVD_TPU_ANOMALY_INTERVAL_MS
  struct AnomalyVerdict {
    int64_t ts_us;
    int kind;  // index into kAnomalyKinds
    std::string subject;
    std::string detail;
  };
  mutable std::mutex anomaly_mu_;
  std::deque<AnomalyVerdict> anomaly_log_;
  int64_t anomaly_counts_[4] = {0, 0, 0, 0};

  // Fusion buffer (lazily grown; analogue of the reference's persistent
  // fusion buffer, operations.cc:696-749).
  std::vector<char> fusion_buffer_;

  std::unique_ptr<Coordinator> coord_;
  uint8_t last_fused_dtype_ = 255;  // dtype of the current fusion group
  Timeline timeline_;
  FlightRecorder flight_;
  std::chrono::steady_clock::time_point last_stall_check_;

  // Coordinator waiting-on snapshot for CoordPendingInfo: rebuilt by the
  // engine thread each tick the coordinator tables are non-empty (the
  // tables themselves are engine-thread-only), read by API threads.
  std::mutex coord_info_mu_;
  std::string coord_pending_info_;
  // Rank 0: refresh coord_pending_info_ from message_table/cache_pending
  // (engine thread only; cheap — negotiations normally resolve within a
  // tick, so the tables are almost always empty).
  void UpdateCoordPendingInfo();
  // Rank 0, engine thread: the cross-rank diagnosis paragraph for the
  // stalled/dead ranks in `missing`, built from the coordinator's
  // per-rank last-announce / last-frame accounting.
  std::string BuildDiagnosis(const std::vector<int>& missing);

  // Negotiation response cache.  Engine-thread only: mutated while
  // processing response lists, read at queue drain; contents reset at
  // Init (restart epochs start cold) and cleared on coordinated abort.
  // The hit/miss/eviction counters are process-cumulative for metrics.
  ResponseCache cache_;
  std::atomic<int64_t> cache_hits_{0};
  std::atomic<int64_t> cache_misses_{0};
  std::atomic<int64_t> cache_evictions_{0};
  std::atomic<int64_t> cache_size_{0};

  // Adaptive tick (docs/performance.md): consecutive progress-less ticks
  // with work still outstanding — bounds how long the loop runs at full
  // speed before falling back to the HVD_TPU_CYCLE_TIME_MS idle cadence.
  int fast_ticks_ = 0;
  // Fusion-buffer reclamation: last time ExecuteAllreduce staged through
  // fusion_buffer_; after a sustained idle stretch the buffer (which only
  // ever grew before) is released back to the allocator.
  std::chrono::steady_clock::time_point last_fusion_use_{};

  // Stall log: one entry per (stalled tensor, sweep) warning, bounded so a
  // permanently wedged job cannot grow it; the counter is cumulative for
  // the process (survives engine re-init, matching the Python side's
  // consumed-events bookkeeping).
  std::mutex stall_mu_;
  int64_t stall_events_ = 0;
  std::deque<std::pair<std::string, double>> stall_log_;

  // Coordinated-abort state.  code is latched once per engine lifetime
  // (first abort wins); events_ is process-cumulative for metrics.
  std::atomic<int32_t> abort_code_{0};
  std::atomic<int64_t> abort_events_{0};
  std::mutex abort_mu_;  // guards abort_message_, abort_pending_info_
  std::string abort_message_;
  // Pending table frozen at the abort (the BackgroundLoop drain clears
  // table_ right after, but the postmortem dump must still say which
  // collectives were in flight when the job died).
  std::string abort_pending_info_;
  // The live table_ serialization PendingInfo() falls back from.
  std::string LivePendingInfo();

  // Clock alignment: the engine's ts epoch (set at Init, shared with the
  // timeline) and this rank's measured offset/RTT against rank 0.
  std::chrono::steady_clock::time_point epoch_{};
  std::atomic<int64_t> clock_offset_us_{0};
  std::atomic<int64_t> clock_rtt_us_{0};

  // Elastic membership (docs/fault-tolerance.md#elastic-membership).
  // cur_rank_/cur_size_ mirror opts_ for lock-free reads from Python API
  // threads (rank()/size() must re-resolve after a reshape).  The epoch
  // counts reshapes this engine lifetime; reshape/lost/joined totals are
  // process-cumulative for metrics.  reshape_ack_pending_ poisons fresh
  // enqueues with the retryable status until Python acknowledges the new
  // membership (hvd.run_elastic's resync calls MembershipAck first).
  std::atomic<int> cur_rank_{0};
  std::atomic<int> cur_size_{1};
  std::atomic<int> cur_local_rank_{0};
  std::atomic<int> cur_local_size_{1};
  std::atomic<int64_t> membership_epoch_{0};
  std::atomic<int64_t> reshapes_total_{0};
  std::atomic<bool> reshape_ack_pending_{false};
  std::mutex membership_mu_;  // guards the lists + reshape_message_
  std::vector<int32_t> ranks_lost_;    // cumulative, epoch-local numbering
  std::vector<int32_t> ranks_joined_;  // cumulative, new dense ranks
  std::string reshape_message_;    // the retryable status message

  // Online autotuning.  The tuner lives at the coordinator (rank 0 /
  // single-process); the applied-parameter state below is per-rank,
  // driven by the lockstep broadcasts.  cur_* mirror opts_ values for
  // lock-free reads from Python API threads (opts_ itself is engine-
  // thread-only once the loop runs).
  ParameterManager tuner_;
  std::atomic<int64_t> cur_fusion_{0};
  std::atomic<int64_t> cur_cycle_us_{0};
  std::atomic<int64_t> cur_cross_algo_{0};
  std::atomic<bool> autotune_frozen_{false};
  std::atomic<int64_t> applied_window_{0};
  std::mutex autotune_mu_;  // guards applied_log_, *_history_
  std::deque<std::string> applied_log_;  // "tick|fusion|cycle_us|comp|frozen"
  // (first_effective_tick, fusion_threshold) change points, appended in
  // tick order and BOUNDED (oldest change points collapse into the
  // floor entry — the plane only ever queries recently closed ticks);
  // FusionThresholdAt walks this short log linearly.
  std::deque<std::pair<int64_t, int64_t>> fusion_history_;
  // Same change-point log for the wire-compression mode, serving the XLA
  // plane's per-tick lockstep lookup (CompressionModeAt).
  std::deque<std::pair<int64_t, int64_t>> compression_history_;

  // Wire compression (docs/performance.md#wire-compression).
  // cur_compression_ mirrors opts_.compression_mode for lock-free reads
  // from Python API threads; residuals_ holds the per-tensor fp32
  // error-feedback buffers (engine thread only; the quantization error of
  // each step feeds the next step's pre-compression add).  Cleared at
  // Init, on reshape (the membership — and with it every sum — changed),
  // and bounded so a stream of never-repeating auto-named tensors cannot
  // grow it forever.  Byte/op counters are process-cumulative; the
  // residual gauges mirror the map for the metrics registry.
  std::atomic<int64_t> cur_compression_{COMP_NONE};
  // Mirrors opts_.compression_min_bytes for lock-free reads from Python
  // API threads (CompressionInfo): reshape/rejoin mutate opts_ on the
  // engine thread mid-run.
  std::atomic<int64_t> cur_comp_min_bytes_{0};
  std::unordered_map<std::string, std::vector<float>> residuals_;
  std::atomic<int64_t> comp_wire_bytes_{0};
  std::atomic<int64_t> comp_payload_bytes_{0};
  std::atomic<int64_t> comp_ops_none_{0};
  std::atomic<int64_t> comp_ops_bf16_{0};
  std::atomic<int64_t> comp_ops_fp8_{0};
  std::atomic<int64_t> residual_bytes_{0};
  std::atomic<int64_t> residual_tensors_{0};
  std::mutex comp_mu_;  // guards comp_log_
  std::deque<std::string> comp_log_;  // "first_name|mode", bounded

  // Two-level topology accounting (docs/performance.md
  // #two-level-topology).  Byte/op counters are process-cumulative (the
  // metrics contract StallEvents set); the per-bucket phase log is
  // bounded, with topo_log_total_ letting the Python sync delta-consume
  // it into the phase histograms.  topo_last_algo_ (-1 = none yet)
  // detects ring<->tree switches for the flight recorder.
  // Atomic mirrors of the topology shape for lock-free API-thread reads
  // (TopologyInfo): node_id_/n_nodes_/opts_.hierarchical_allreduce are
  // engine-thread state that RebuildRing resets at a reshape while
  // Python metric pollers snapshot concurrently (the opts_ mirror
  // pattern; a TSan-confirmed race before these existed).
  std::atomic<bool> topo_hier_{false};
  std::atomic<int> topo_nodes_{1};
  std::atomic<int64_t> topo_ops_ring_{0};
  std::atomic<int64_t> topo_ops_tree_{0};
  std::atomic<int64_t> topo_local_bytes_{0};
  std::atomic<int64_t> topo_cross_bytes_{0};
  std::atomic<int> topo_last_algo_{-1};
  // Cumulative per-phase time sums + timed-op count (process-cumulative):
  // the anomaly detector's per-phase input — sweep deltas give a mean
  // phase time per interval without parsing the bounded log.
  std::atomic<int64_t> topo_rs_us_{0};
  std::atomic<int64_t> topo_cross_us_{0};
  std::atomic<int64_t> topo_ag_us_{0};
  std::atomic<int64_t> topo_timed_ops_{0};
  std::mutex topo_mu_;  // guards topo_log_, topo_log_total_
  std::deque<std::string> topo_log_;  // "name|algo|rs_us|cross_us|ag_us"
  int64_t topo_log_total_ = 0;
  // One per-bucket record for log + histograms (any thread).
  void RecordTopologyOp(const std::string& name, bool tree,
                        int64_t local_rs_us, int64_t cross_us,
                        int64_t local_ag_us);

  // Announce-order accounting (rank 0).  Counts are process-cumulative;
  // the log is bounded so an unconsumed Python side cannot grow it.
  std::mutex announce_mu_;
  int64_t announce_events_ = 0;
  std::vector<int64_t> last_announce_counts_;
  std::deque<std::pair<int, int64_t>> announce_log_;
};

Engine* GlobalEngine();

}  // namespace hvdtpu
