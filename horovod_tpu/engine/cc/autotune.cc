#include "autotune.h"

#include <algorithm>
#include <cmath>
#include <cstdio>

namespace hvdtpu {

// Log-spaced grids (powers of 4 for the threshold, ~half-decades for the
// cycle).  Spanning 64 KB..256 MB and 0.5..50 ms keeps the climb short —
// a handful of windows per axis — while bracketing every regime the
// benches exercise (negotiation-bound 32 B allreduces to 100 MB CNN
// gradient buckets).
const std::vector<int64_t> kFusionGrid = {
    64 << 10, 256 << 10, 1 << 20, 4 << 20, 16 << 20, 64 << 20, 256 << 20};
const std::vector<double> kCycleGridMs = {0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                                          50.0};
// CompressionMode codes ordered by wire aggressiveness (none 0, bf16 1,
// fp8 2): climbing +1 moves fewer bytes per bucket.
const std::vector<int64_t> kCompressionGrid = {0, 1, 2};
// Ring-vs-tree boundary for the two-level cross-node hop (bytes; buckets
// under the boundary take the recursive-doubling tree).  0 = ring always;
// the top end brackets the latency-bound bucket sizes the small-allreduce
// bench exercises.
const std::vector<int64_t> kCrossAlgoGrid = {0, 16 << 10, 64 << 10,
                                             256 << 10, 1 << 20};

namespace {

// Relative improvement required to count as "the job got faster": global
// progress below this for kFreezeStall consecutive windows freezes the
// search.  Move acceptance uses the tighter kEpsMove so plateaus
// terminate a climb quickly; the best-so-far memory protects the final
// choice from noise-accepted moves.
constexpr double kEpsImprove = 0.05;
constexpr double kEpsMove = 0.02;
constexpr int kFreezeStall = 6;
// A window must span at least this much wall time: at wire-speed op rates
// an op-count-only window would close in microseconds and score pure
// scheduler noise.  50 ms spans several steps of a fast configuration —
// the single-step windows this replaced measured noisily enough to
// freeze the search at the wrong grid point every few runs.
constexpr double kMinWindowSec = 0.05;
constexpr size_t kHistoryCap = 512;

template <typename T>
int SnapLog(const std::vector<T>& grid, double value) {
  if (value <= 0) return 0;
  int best = 0;
  double best_d = -1;
  for (size_t i = 0; i < grid.size(); ++i) {
    if (static_cast<double>(grid[i]) <= 0) continue;  // log(0): see below
    double d = std::fabs(std::log(static_cast<double>(grid[i])) -
                         std::log(value));
    if (best_d < 0 || d < best_d) {
      best_d = d;
      best = static_cast<int>(i);
    }
  }
  // A non-positive grid point (the cross-algo grid's "0 = ring always")
  // is only reachable by a non-positive value, handled above.
  return best;
}

}  // namespace

void ParameterManager::Configure(bool enabled, int64_t warmup_windows,
                                 int64_t window_ops, int64_t fix_fusion,
                                 double fix_cycle_ms,
                                 int64_t fix_compression,
                                 int64_t fix_cross_algo,
                                 int64_t init_fusion, double init_cycle_ms,
                                 int64_t init_compression,
                                 int64_t init_cross_algo) {
  std::lock_guard<std::mutex> lk(mu_);
  enabled_ = enabled;
  done_ = !enabled;
  warmup_left_ = std::max<int64_t>(warmup_windows, 0);
  window_ops_ = std::max<int64_t>(window_ops, 1);
  axes_fusion_ = fix_fusion >= 0 ? std::vector<int64_t>{fix_fusion}
                                 : kFusionGrid;
  axes_cycle_ = fix_cycle_ms >= 0 ? std::vector<double>{fix_cycle_ms}
                                  : kCycleGridMs;
  axes_comp_ = fix_compression >= 0
                   ? std::vector<int64_t>{fix_compression}
                   : kCompressionGrid;
  axes_algo_ = fix_cross_algo >= 0 ? std::vector<int64_t>{fix_cross_algo}
                                   : kCrossAlgoGrid;
  init_fusion_ = init_fusion;
  init_cycle_ms_ = init_cycle_ms;
  init_comp_ = init_compression;
  init_algo_ = init_cross_algo;
  idx_[0] = SnapLog(axes_fusion_, static_cast<double>(init_fusion));
  idx_[1] = SnapLog(axes_cycle_, init_cycle_ms);
  idx_[2] = 0;
  for (size_t i = 0; i < axes_comp_.size(); ++i)
    if (axes_comp_[i] == init_compression) idx_[2] = static_cast<int>(i);
  idx_[3] = SnapLog(axes_algo_, static_cast<double>(init_cross_algo));
  // Cycle first, climbing down: the idle-cadence co-arrival sleep is the
  // dominant knob for the negotiation-bound steady state (docs/
  // performance.md), and a too-high cycle drowns any fusion signal.
  axis_ = axes_cycle_.size() > 1 ? 1
          : axes_fusion_.size() > 1 ? 0
          : axes_comp_.size() > 1  ? 2
                                    : 3;
  dir_ = axis_ == 1 ? -1 : +1;
  tried_flip_ = false;
  have_anchor_ = false;
  anchored_ = false;
  win_bytes_ = win_ops_ = 0;
  win_open_ = false;
  memory_.clear();
  have_best_ = false;
  stall_windows_ = 0;
  inject_pending_ = false;
  windows_ = 0;
  best_score_ = 0.0;
  history_.clear();
}

void ParameterManager::Record(int64_t bytes, int64_t n) {
  if (!active()) return;
  if (!win_open_) {
    // The window opens at its first op, not at the previous close: the
    // score is collective throughput while work flows, and a long idle
    // stretch between steps must not dilute it.
    win_open_ = true;
    win_start_ = std::chrono::steady_clock::now();
  }
  win_bytes_ += bytes;
  win_ops_ += n;
}

ParameterManager::Proposal ParameterManager::MakeProposal(bool frozen) {
  Proposal p;
  p.present = true;
  p.frozen = frozen;
  p.fusion_threshold = GridFusion();
  p.cycle_time_us = static_cast<int64_t>(GridCycleMs() * 1000.0);
  p.compression = GridCompression();
  p.cross_algo_threshold = GridCrossAlgo();
  std::lock_guard<std::mutex> lk(mu_);
  p.window = windows_;
  return p;
}

void ParameterManager::Inject(int64_t fusion, double cycle_ms,
                              int64_t compression, int64_t cross_algo) {
  std::lock_guard<std::mutex> lk(mu_);
  inject_pending_ = true;
  inject_fusion_ = fusion;
  inject_cycle_ms_ = cycle_ms;
  inject_comp_ = compression;
  inject_algo_ = cross_algo;
}

void ParameterManager::Tick(std::chrono::steady_clock::time_point now,
                            int64_t cur_fusion, double cur_cycle_ms,
                            int64_t cur_compression,
                            int64_t cur_cross_algo, Proposal* out) {
  {
    // Manual injection (hvd.autotune_set) broadcasts exactly the caller's
    // values this tick — works with the tuner disabled or frozen (the
    // pluggable-policy seam).  The search, if live, resumes from the
    // nearest grid point with a fresh window.  An unset knob keeps the
    // engine's applied value, NOT a grid snap — injecting one knob must
    // not silently move the others.
    std::lock_guard<std::mutex> lk(mu_);
    if (inject_pending_) {
      inject_pending_ = false;
      int64_t fusion = inject_fusion_ >= 0 ? inject_fusion_ : cur_fusion;
      double cycle = inject_cycle_ms_ >= 0 ? inject_cycle_ms_
                                           : cur_cycle_ms;
      int64_t comp = inject_comp_ >= 0 ? inject_comp_ : cur_compression;
      int64_t algo = inject_algo_ >= 0 ? inject_algo_ : cur_cross_algo;
      if (inject_fusion_ >= 0)
        idx_[0] = SnapLog(axes_fusion_, static_cast<double>(fusion));
      if (inject_cycle_ms_ >= 0) idx_[1] = SnapLog(axes_cycle_, cycle);
      if (inject_comp_ >= 0)
        for (size_t i = 0; i < axes_comp_.size(); ++i)
          if (axes_comp_[i] == comp) idx_[2] = static_cast<int>(i);
      if (inject_algo_ >= 0)
        idx_[3] = SnapLog(axes_algo_, static_cast<double>(algo));
      have_anchor_ = false;
      tried_flip_ = false;
      // De-anchor: the next window runs under the EXACT injected values,
      // which may sit off-grid — its score must be discarded (and the
      // snapped anchor re-broadcast) rather than attributed to the grid
      // point in memory_/history_, same as the raw initial params.
      anchored_ = false;
      win_open_ = false;
      win_bytes_ = win_ops_ = 0;
      out->present = true;
      // "frozen" means a search CONVERGED; a disabled tuner's done_
      // state must not let a manual injection report one.
      out->frozen = enabled_ && done_;
      out->fusion_threshold = fusion;
      out->cycle_time_us = static_cast<int64_t>(cycle * 1000.0);
      out->compression = comp;
      out->cross_algo_threshold = algo;
      out->window = windows_;
      return;
    }
  }
  if (!active() || !win_open_) return;
  double elapsed =
      std::chrono::duration<double>(now - win_start_).count();
  if (win_ops_ < window_ops_ || elapsed < kMinWindowSec) return;
  // Score: payload bytes negotiated per second, with a 1-byte-per-op
  // floor so windows of negotiation-only agreements (the XLA plane's
  // cached metadata no-ops move zero coordinator-visible bytes) still
  // score proportionally to op throughput.
  double score = static_cast<double>(win_bytes_ + win_ops_) / elapsed;
  win_open_ = false;
  win_bytes_ = win_ops_ = 0;
  CloseWindow(score, out);
}

void ParameterManager::CloseWindow(double score, Proposal* out) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    ++windows_;
    // History records the params the window actually RAN under: before
    // the anchor broadcast that is the raw (un-snapped) initial env
    // values, not the grid point they snap to.
    int64_t fus = anchored_ ? GridFusion() : init_fusion_;
    double cyc = anchored_ ? GridCycleMs() : init_cycle_ms_;
    int64_t cmp = anchored_ ? GridCompression() : init_comp_;
    int64_t alg = anchored_ ? GridCrossAlgo() : init_algo_;
    char buf[144];
    snprintf(buf, sizeof(buf), "%lld|%lld|%lld|%lld|%lld|%.1f",
             static_cast<long long>(windows_),
             static_cast<long long>(fus),
             static_cast<long long>(cyc * 1000.0),
             static_cast<long long>(cmp),
             static_cast<long long>(alg), score);
    history_.emplace_back(buf);
    while (history_.size() > kHistoryCap) history_.pop_front();
  }
  if (warmup_left_ > 0) {
    // Warmup windows are discarded: they ran under the raw (un-snapped)
    // initial params and include negotiation cold start.  The last one
    // broadcasts the snapped anchor point so the search measures grid
    // values from here on.
    if (--warmup_left_ == 0) BroadcastAnchor(out);
    return;
  }
  if (!anchored_) {
    // HVD_TPU_AUTOTUNE_WARMUP=0: the snapped anchor was never broadcast,
    // and this window ran under the raw initial params — broadcasting
    // the snap now and DISCARDING the score keeps a raw-params
    // measurement from being attributed to the grid point in memory_.
    BroadcastAnchor(out);
    return;
  }
  Step(score, out);
}

void ParameterManager::BroadcastAnchor(Proposal* out) {
  anchored_ = true;
  if (axes_fusion_.size() == 1 && axes_cycle_.size() == 1 &&
      axes_comp_.size() == 1 && axes_algo_.size() == 1) {
    // Every knob pinned: nothing to search.  Broadcast the pinned point
    // once, frozen.
    FreezeAtBest(out);
  } else {
    *out = MakeProposal(false);
  }
}

void ParameterManager::Step(double score, Proposal* out) {
  std::array<int, 4> point{{idx_[0], idx_[1], idx_[2], idx_[3]}};
  auto& mem = memory_[point];
  mem.first += score;
  mem.second += 1;
  if (!have_best_ || score > best_score_ * (1.0 + kEpsImprove)) {
    have_best_ = true;
    best_point_ = point;
    stall_windows_ = 0;
    std::lock_guard<std::mutex> lk(mu_);
    best_score_ = std::max(best_score_, score);
  } else {
    ++stall_windows_;
  }
  if (stall_windows_ >= kFreezeStall) {
    FreezeAtBest(out);
    return;
  }
  if (!have_anchor_) {
    // This window measured the anchor of the current axis.
    have_anchor_ = true;
    anchor_score_ = score;
    anchor_idx_ = idx_[axis_];
    tried_flip_ = false;
    if (MoveOn(axis_, dir_)) {
      *out = MakeProposal(false);
    } else if (MoveOn(axis_, -dir_)) {
      dir_ = -dir_;
      *out = MakeProposal(false);
    } else {
      SwitchAxis(score);
      if (!done_) *out = MakeProposal(false);
      else FreezeAtBest(out);
    }
    return;
  }
  // This window measured a moved-to point.
  if (score > anchor_score_ * (1.0 + kEpsMove)) {
    // Improvement: keep climbing the same direction.  The opposite
    // direction is now known worse (it leads back through the old
    // anchor), so a later rejection ends this axis instead of flipping.
    anchor_score_ = score;
    anchor_idx_ = idx_[axis_];
    tried_flip_ = true;
    if (MoveOn(axis_, dir_)) {
      *out = MakeProposal(false);
    } else {
      SwitchAxis(score);
      if (!done_) *out = MakeProposal(false);
      else FreezeAtBest(out);
    }
    return;
  }
  // Worse (or flat): step back to the anchor; try the other direction
  // once, else hand the climb to the other knob.
  idx_[axis_] = anchor_idx_;
  if (!tried_flip_ && MoveOn(axis_, -dir_)) {
    tried_flip_ = true;
    dir_ = -dir_;
    *out = MakeProposal(false);
    return;
  }
  SwitchAxis(anchor_score_);
  if (!done_) *out = MakeProposal(false);
  else FreezeAtBest(out);
}

bool ParameterManager::MoveOn(int axis, int dir) {
  int n = axis == 0   ? static_cast<int>(axes_fusion_.size())
          : axis == 1 ? static_cast<int>(axes_cycle_.size())
          : axis == 2 ? static_cast<int>(axes_comp_.size())
                      : static_cast<int>(axes_algo_.size());
  int next = idx_[axis] + dir;
  if (next < 0 || next >= n) return false;
  idx_[axis] = next;
  return true;
}

void ParameterManager::SwitchAxis(double last_score) {
  // Hand the climb to the next knob; the measurement of the CURRENT
  // point becomes its anchor, so no window is wasted re-measuring.
  for (int attempt = 0; attempt < 4; ++attempt) {
    axis_ = (axis_ + 1) % 4;
    // Heuristic first direction: bigger fusion buckets, tighter cycle,
    // more aggressive wire compression, wider tree boundary.
    dir_ = axis_ == 1 ? -1 : +1;
    have_anchor_ = true;
    anchor_score_ = last_score;
    anchor_idx_ = idx_[axis_];
    tried_flip_ = false;
    if (MoveOn(axis_, dir_)) return;
    if (MoveOn(axis_, -dir_)) {
      dir_ = -dir_;
      return;
    }
    // This axis is pinned (single-point grid); try the next one.
  }
  // No knob can move: the search space is exhausted.
  done_ = true;
}

void ParameterManager::FreezeAtBest(Proposal* out) {
  // Freeze at the argmax of MEAN score over everything measured.
  // best_point_ only tracks >kEpsImprove jumps (the stall detector's
  // view), so a run of small accepted moves can leave the real best only
  // in memory_; means, not maxes, keep one lucky window from deciding
  // the job's permanent parameters.
  const std::array<int, 4>* argmax = nullptr;
  double argmax_score = 0.0;
  for (const auto& kv : memory_) {
    double mean = kv.second.first / kv.second.second;
    if (argmax == nullptr || mean > argmax_score) {
      argmax = &kv.first;
      argmax_score = mean;
    }
  }
  if (argmax != nullptr) {
    for (int a = 0; a < 4; ++a) idx_[a] = (*argmax)[a];
    // The reported best score must describe the FROZEN point: assign the
    // argmax mean outright — best_score_ may hold a lucky spike from a
    // point the mean ranking rejected.
    std::lock_guard<std::mutex> lk(mu_);
    best_score_ = argmax_score;
  } else if (have_best_) {
    for (int a = 0; a < 4; ++a) idx_[a] = best_point_[a];
  }
  done_ = true;
  *out = MakeProposal(true);
}

int64_t ParameterManager::windows() const {
  std::lock_guard<std::mutex> lk(mu_);
  return windows_;
}

double ParameterManager::best_score() const {
  std::lock_guard<std::mutex> lk(mu_);
  return best_score_;
}

std::string ParameterManager::History() const {
  std::lock_guard<std::mutex> lk(mu_);
  std::string out;
  for (const auto& e : history_) {
    if (!out.empty()) out += ';';
    out += e;
  }
  return out;
}

}  // namespace hvdtpu
