// C API exported to Python over ctypes.  Counterpart of the reference's
// extern "C" block (/root/reference/horovod/common/operations.cc:1731-1813)
// plus the torch handle API (/root/reference/horovod/torch/interface.h:16-75),
// unified: every framework binding (numpy/jax-eager/tf-eager/torch) talks to
// the engine through these same dozen functions.
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "engine.h"
#include "simscale.h"

using hvdtpu::Engine;
using hvdtpu::EngineOptions;
using hvdtpu::GlobalEngine;

namespace {
std::mutex g_err_mu;
std::string g_init_error;
thread_local std::string tl_error;

std::vector<std::string> SplitCommas(const char* s) {
  std::vector<std::string> out;
  if (!s) return out;
  std::string cur;
  for (const char* p = s; *p; ++p) {
    if (*p == ',') {
      if (!cur.empty()) out.push_back(cur);
      cur.clear();
    } else {
      cur += *p;
    }
  }
  if (!cur.empty()) out.push_back(cur);
  return out;
}
}  // namespace

extern "C" {

int hvd_tpu_init(int rank, int size, int local_rank, int local_size,
                 const char* coord_endpoint, const char* data_endpoints,
                 double cycle_time_ms, long long fusion_threshold,
                 double stall_warning_sec, const char* timeline_path,
                 int hierarchical_allreduce, double collective_timeout_sec,
                 long long cache_capacity, int autotune,
                 long long autotune_warmup, long long autotune_window,
                 long long autotune_fix_fusion,
                 double autotune_fix_cycle_ms, int elastic,
                 long long min_size, int rejoin, int compression_mode,
                 long long compression_min_bytes,
                 long long autotune_fix_compression,
                 long long cross_algo_threshold,
                 long long autotune_fix_cross_algo, int coord_tree,
                 long long steady_threshold, long long steady_max_period) {
  EngineOptions opts;
  opts.rank = rank;
  opts.size = size;
  opts.local_rank = local_rank;
  opts.local_size = local_size;
  opts.coord_endpoint = coord_endpoint ? coord_endpoint : "";
  opts.data_endpoints = SplitCommas(data_endpoints);
  opts.cycle_time_ms = cycle_time_ms;
  opts.fusion_threshold = fusion_threshold;
  opts.stall_warning_sec = stall_warning_sec;
  opts.timeline_path = timeline_path ? timeline_path : "";
  opts.hierarchical_allreduce = hierarchical_allreduce != 0;
  opts.collective_timeout_sec = collective_timeout_sec;
  opts.cache_capacity = cache_capacity;
  opts.autotune = autotune != 0;
  opts.autotune_warmup = autotune_warmup;
  opts.autotune_window = autotune_window;
  opts.autotune_fix_fusion = autotune_fix_fusion;
  opts.autotune_fix_cycle_ms = autotune_fix_cycle_ms;
  opts.elastic = elastic != 0;
  opts.min_size = min_size > 0 ? min_size : 1;
  opts.rejoin = rejoin != 0;
  opts.compression_mode = static_cast<uint8_t>(compression_mode);
  opts.compression_min_bytes =
      compression_min_bytes >= 0 ? compression_min_bytes : 0;
  opts.autotune_fix_compression = autotune_fix_compression;
  opts.cross_algo_threshold =
      cross_algo_threshold >= 0 ? cross_algo_threshold : 64 * 1024;
  opts.autotune_fix_cross_algo = autotune_fix_cross_algo;
  opts.coord_tree = coord_tree != 0;
  opts.steady_threshold = steady_threshold >= 0 ? steady_threshold : 0;
  opts.steady_max_period =
      steady_max_period > 0 ? steady_max_period : 256;
  std::string err;
  int rc = GlobalEngine()->Init(opts, &err);
  if (rc != 0) {
    std::lock_guard<std::mutex> lk(g_err_mu);
    g_init_error = err;
  }
  return rc;
}

const char* hvd_tpu_init_error() {
  std::lock_guard<std::mutex> lk(g_err_mu);
  return g_init_error.c_str();
}

void hvd_tpu_shutdown() { GlobalEngine()->Shutdown(); }

int hvd_tpu_initialized() { return GlobalEngine()->Initialized() ? 1 : 0; }
int hvd_tpu_rank() {
  return GlobalEngine()->Initialized() ? GlobalEngine()->rank() : -1;
}
int hvd_tpu_size() {
  return GlobalEngine()->Initialized() ? GlobalEngine()->size() : -1;
}
int hvd_tpu_local_rank() {
  return GlobalEngine()->Initialized() ? GlobalEngine()->local_rank() : -1;
}
int hvd_tpu_local_size() {
  return GlobalEngine()->Initialized() ? GlobalEngine()->local_size() : -1;
}

// op: 0=allreduce 1=allgather 2=broadcast; dtype: see wire.h DataType.
// Returns handle >= 0, or -1 if the engine is not running.
long long hvd_tpu_enqueue(int op, const char* name, const void* in, void* out,
                          const long long* dims, int ndim, int dtype,
                          int root_rank, int average) {
  std::vector<int64_t> d(dims, dims + ndim);
  return GlobalEngine()->Enqueue(static_cast<uint8_t>(op), name ? name : "",
                                 in, out, d, static_cast<uint8_t>(dtype),
                                 root_rank, average != 0);
}

// Point-to-point plane (docs/pipeline.md).  op: 4=send 5=recv; `peer` is
// the counterpart rank, `tag` disambiguates concurrent transfers between
// the same pair (it suffixes the negotiated name on the Python side).
// Precondition failures (self-send, peer out of range) ride the returned
// handle as a typed ST_PRECONDITION error.
long long hvd_tpu_enqueue_p2p(int op, const char* name, const void* in,
                              void* out, const long long* dims, int ndim,
                              int dtype, int peer, int tag) {
  std::vector<int64_t> d(dims, dims + ndim);
  return GlobalEngine()->Enqueue(static_cast<uint8_t>(op), name ? name : "",
                                 in, out, d, static_cast<uint8_t>(dtype), -1,
                                 false, peer, tag);
}

// Stage-scoped allreduce: `ranks` (ascending, nranks of them, this rank
// among them) restricts the reduction to a stage group's membership —
// the data-parallel reduction inside one pipeline stage.
long long hvd_tpu_enqueue_group(const char* name, const void* in, void* out,
                                const long long* dims, int ndim, int dtype,
                                int average, const long long* ranks,
                                int nranks) {
  std::vector<int64_t> d(dims, dims + ndim);
  std::vector<int32_t> members;
  members.reserve(nranks > 0 ? nranks : 0);
  for (int i = 0; i < nranks; ++i)
    members.push_back(static_cast<int32_t>(ranks[i]));
  return GlobalEngine()->Enqueue(hvdtpu::OP_ALLREDUCE, name ? name : "", in,
                                 out, d, static_cast<uint8_t>(dtype), -1,
                                 average != 0, -1, 0, members);
}

// "sends|recvs|bytes_out|bytes_in|matched|unmatched|group_ops|channels"
// (docs/metrics.md#p2p).
const char* hvd_tpu_p2p_info() {
  static thread_local std::string tl_p2p_info;
  tl_p2p_info = GlobalEngine()->P2pInfo();
  return tl_p2p_info.c_str();
}

int hvd_tpu_poll(long long handle) {
  return GlobalEngine()->Poll(handle);
}

int hvd_tpu_wait(long long handle) {
  return GlobalEngine()->Wait(handle);
}

int hvd_tpu_status(long long handle) {
  return GlobalEngine()->StatusOf(handle, nullptr);
}

const char* hvd_tpu_error(long long handle) {
  GlobalEngine()->StatusOf(handle, &tl_error);
  return tl_error.c_str();
}

// Completion-order stamps for the XLA data plane's dispatch agreement.
// -1 while the handle is pending or unknown.
long long hvd_tpu_completion_seq(long long handle) {
  return GlobalEngine()->CompletionSeq(handle);
}

long long hvd_tpu_completion_tick(long long handle) {
  return GlobalEngine()->CompletionTick(handle);
}

// Negotiation latency (µs, enqueue -> agreed response arriving at this
// rank) for a finished handle; -1 while pending / unknown / failed before
// negotiation.  Feeds the negotiation_sec histogram for the engine data
// plane (docs/metrics.md).
long long hvd_tpu_negotiation_us(long long handle) {
  return GlobalEngine()->NegotiationUs(handle);
}

long long hvd_tpu_ticks_done() { return GlobalEngine()->TicksDone(); }

long long hvd_tpu_result_nbytes(long long handle) {
  return GlobalEngine()->ResultBytes(handle);
}

long long hvd_tpu_result_dim0(long long handle) {
  return GlobalEngine()->ResultDim0(handle);
}

int hvd_tpu_copy_result(long long handle, void* dst, long long nbytes) {
  return GlobalEngine()->CopyResult(handle, dst, nbytes) ? 0 : 1;
}

// Zero-copy view of a completed allgather's engine-owned result; valid
// until hvd_tpu_release(handle).  NULL while pending or for empty results.
void* hvd_tpu_result_ptr(long long handle) {
  return GlobalEngine()->ResultPtr(handle);
}

void hvd_tpu_release(long long handle) { GlobalEngine()->Release(handle); }

// Stall observability for the Python metrics registry: cumulative count
// of (tensor, sweep) stall warnings from the rank-0 coordinator sweep,
// plus a bounded "name|seconds;..." log of the most recent ones.
long long hvd_tpu_stall_count() { return GlobalEngine()->StallEvents(); }

const char* hvd_tpu_stall_info() {
  static thread_local std::string tl_stall_info;
  tl_stall_info = GlobalEngine()->StallInfo();
  return tl_stall_info.c_str();
}

// Coordinated-abort observability (docs/fault-tolerance.md): the latched
// abort status of this engine (0 = never aborted; ST_RANKS_DOWN=6 /
// ST_TIMEOUT=7 otherwise) with its structured message, and the
// process-cumulative abort-event count for the metrics registry.
int hvd_tpu_abort_code() { return GlobalEngine()->AbortCode(); }

const char* hvd_tpu_abort_message() {
  static thread_local std::string tl_abort_message;
  tl_abort_message = GlobalEngine()->AbortMessage();
  return tl_abort_message.c_str();
}

long long hvd_tpu_abort_count() { return GlobalEngine()->AbortEvents(); }

// Response-cache observability (docs/performance.md): process-cumulative
// hit/miss/eviction counts (survive re-init, like stalls) plus the
// current entry count of this engine's cache.
long long hvd_tpu_cache_hit_count() { return GlobalEngine()->CacheHits(); }

long long hvd_tpu_cache_miss_count() {
  return GlobalEngine()->CacheMisses();
}

long long hvd_tpu_cache_eviction_count() {
  return GlobalEngine()->CacheEvictions();
}

long long hvd_tpu_cache_size() { return GlobalEngine()->CacheSize(); }

// Postmortem plane (docs/troubleshooting.md#reading-a-postmortem).
// Flight recorder: process-cumulative event count for the metrics
// registry, and a non-destructive ring snapshot
// ("seq|ts_us|event|name|arg;...", oldest first) for the dump writer.
long long hvd_tpu_flight_count() {
  return GlobalEngine()->flight().Events();
}

const char* hvd_tpu_flight_dump() {
  static thread_local std::string tl_flight_dump;
  tl_flight_dump = GlobalEngine()->flight().Dump();
  return tl_flight_dump.c_str();
}

// Pending-tensor tables: this rank's in-flight collectives
// ("name|op|age_us;...") and — on rank 0 — the coordinator's waiting-on
// snapshot ("name|age_us|missing_rank missing_rank;...").
const char* hvd_tpu_pending_info() {
  static thread_local std::string tl_pending_info;
  tl_pending_info = GlobalEngine()->PendingInfo();
  return tl_pending_info.c_str();
}

const char* hvd_tpu_coord_pending_info() {
  static thread_local std::string tl_coord_pending;
  tl_coord_pending = GlobalEngine()->CoordPendingInfo();
  return tl_coord_pending.c_str();
}

// The cross-rank diagnosis paragraph the coordinator folded into the
// broadcast abort message (empty before an abort, or when the abort
// carried none).
const char* hvd_tpu_diagnosis() {
  static thread_local std::string tl_diagnosis;
  tl_diagnosis = GlobalEngine()->Diagnosis();
  return tl_diagnosis.c_str();
}

// Cross-rank clock alignment (docs/timeline.md): this rank's estimated
// clock offset against rank 0 (µs) and the RTT error bound of the winning
// NTP-style probe.  0 on rank 0 / single-process jobs.
long long hvd_tpu_clock_offset_us() {
  return GlobalEngine()->ClockOffsetUs();
}

long long hvd_tpu_clock_rtt_us() { return GlobalEngine()->ClockRttUs(); }

// Data-plane liveness (docs/fault-tolerance.md#failure-detection):
// "interval_ms|miss_limit|sent|recv|miss_events|evictions|clock_fanin|"
// followed by space-separated "peer:last_seen_age_us:misses" entries for
// the directly monitored beacon neighbours.  interval_ms 0 = detector
// disabled.
const char* hvd_tpu_liveness_info() {
  static thread_local std::string tl_liveness;
  tl_liveness = GlobalEngine()->LivenessInfo();
  return tl_liveness.c_str();
}

// Per-peer link telemetry (docs/metrics.md#links): "enabled|" then
// semicolon-separated
// "peer:bytes_out:bytes_in:sends:recvs:stalls:short_writes:send_us_sum:
//  send_us_count:b0,..,b9:rtt_last_us:rtt_ewma_us:rtt_samples" entries.
// rtt_last_us is -1 until the first heartbeat echo lands.
const char* hvd_tpu_link_info() {
  static thread_local std::string tl_link;
  tl_link = GlobalEngine()->LinkInfo();
  return tl_link.c_str();
}

// Anomaly detector config + cumulative verdict counts:
// "sigma|interval_ms|slow_link|straggler|cache_degraded|slow_phase".
// sigma 0 = detector disabled.
const char* hvd_tpu_anomaly_info() {
  static thread_local std::string tl_anomaly;
  tl_anomaly = GlobalEngine()->AnomalyInfo();
  return tl_anomaly.c_str();
}

// Bounded verdict log, oldest first: "kind|subject|detail|age_us;..."
// (separators sanitized out of subject/detail).
const char* hvd_tpu_anomaly_log() {
  static thread_local std::string tl_anomaly_log;
  tl_anomaly_log = GlobalEngine()->AnomalyLog();
  return tl_anomaly_log.c_str();
}

// Announce-order observability for the Python metrics registry (straggler
// attribution, rank-0 coordinator view): cumulative negotiation count, a
// bounded log of the most recent ones as
// "cumulative_count:last_rank|skew_us;..." (count and entries serialized
// atomically), and exact per-rank last-to-announce counts as "n0,n1,...".
long long hvd_tpu_announce_count() { return GlobalEngine()->AnnounceEvents(); }

const char* hvd_tpu_announce_log() {
  static thread_local std::string tl_announce_log;
  tl_announce_log = GlobalEngine()->AnnounceLog();
  return tl_announce_log.c_str();
}

const char* hvd_tpu_last_announce_counts() {
  static thread_local std::string tl_last_announce;
  tl_last_announce = GlobalEngine()->LastAnnounceCounts();
  return tl_last_announce.c_str();
}

// Online-autotuning observability and control (docs/performance.md
// #autotuning).  The applied parameters come from lockstep broadcasts, so
// they agree across the ranks of a healthy job; history/best-score are
// coordinator-side (rank 0).
int hvd_tpu_autotune_enabled() {
  return GlobalEngine()->AutotuneEnabled() ? 1 : 0;
}

int hvd_tpu_autotune_frozen() {
  return GlobalEngine()->AutotuneFrozen() ? 1 : 0;
}

long long hvd_tpu_autotune_windows() {
  return GlobalEngine()->AutotuneWindows();
}

long long hvd_tpu_autotune_fusion_threshold() {
  return GlobalEngine()->CurrentFusionThreshold();
}

long long hvd_tpu_autotune_cycle_time_us() {
  return GlobalEngine()->CurrentCycleTimeUs();
}

double hvd_tpu_autotune_best_score() {
  return GlobalEngine()->AutotuneBestScore();
}

// Rank-0 per-window search history, "window|fusion|cycle_us|score;...".
const char* hvd_tpu_autotune_history() {
  static thread_local std::string tl_autotune_history;
  tl_autotune_history = GlobalEngine()->AutotuneHistory();
  return tl_autotune_history.c_str();
}

// Per-rank applied-parameter log, "tick|fusion|cycle_us|frozen;..." —
// identical on every rank (the lockstep determinism contract).
const char* hvd_tpu_autotune_applied() {
  static thread_local std::string tl_autotune_applied;
  tl_autotune_applied = GlobalEngine()->AutotuneApplied();
  return tl_autotune_applied.c_str();
}

// Manual parameter injection (hvd.autotune_set; the pluggable-policy
// seam): broadcast fusion/cycle/compression/cross-algo (< 0 keeps the
// current value) at the next tick.  0 ok, 1 not-the-coordinator, 2
// uninitialized.
int hvd_tpu_autotune_set(long long fusion_threshold, double cycle_time_ms,
                         long long compression,
                         long long cross_algo_threshold) {
  return GlobalEngine()->AutotuneInject(fusion_threshold, cycle_time_ms,
                                        compression, cross_algo_threshold);
}

// Two-level cross-node ring-vs-tree boundary currently applied (bytes;
// lockstep-broadcast state, identical on every rank of a healthy job).
long long hvd_tpu_autotune_cross_algo_threshold() {
  return GlobalEngine()->CurrentCrossAlgoThreshold();
}

// Fusion threshold in force at engine tick `tick` (the XLA plane keys its
// bucket boundaries off this so autotuned thresholds move them in
// lockstep across ranks).
long long hvd_tpu_fusion_threshold_at(long long tick) {
  return GlobalEngine()->FusionThresholdAt(tick);
}

// Wire compression (docs/performance.md#wire-compression).  The applied
// mode is lockstep-broadcast state, identical on every rank of a healthy
// job; the _at(tick) form serves the XLA plane's per-tick lookup the way
// hvd_tpu_fusion_threshold_at does for bucket boundaries.
int hvd_tpu_compression_mode() {
  return GlobalEngine()->CompressionModeNow();
}

long long hvd_tpu_compression_mode_at(long long tick) {
  return GlobalEngine()->CompressionModeAt(tick);
}

// "wire|payload|ops_none|ops_bf16|ops_fp8|residual_bytes|
//  residual_tensors|min_bytes" — process-cumulative byte/op counters for
// the Python metrics sync, plus the residual-buffer gauges.
const char* hvd_tpu_compression_info() {
  static thread_local std::string tl_compression_info;
  tl_compression_info = GlobalEngine()->CompressionInfo();
  return tl_compression_info.c_str();
}

// Bounded per-bucket decision log, "first_name|mode;..." in execution
// order — identical across the ranks of a healthy job (the lockstep
// contract tests allgather-compare).
const char* hvd_tpu_compression_log() {
  static thread_local std::string tl_compression_log;
  tl_compression_log = GlobalEngine()->CompressionLog();
  return tl_compression_log.c_str();
}

// Two-level topology observability (docs/performance.md
// #two-level-topology).  Info serializes "hier|nodes|local_size|
// threshold|ops_ring|ops_tree|local_bytes|cross_bytes|log_total";
// the log is the bounded per-bucket phase record
// "name|algo|local_rs_us|cross_us|local_ag_us;..." the Python sync
// delta-consumes into the topology phase histograms.
const char* hvd_tpu_topology_info() {
  static thread_local std::string tl_topology_info;
  tl_topology_info = GlobalEngine()->TopologyInfo();
  return tl_topology_info.c_str();
}

const char* hvd_tpu_topology_log() {
  static thread_local std::string tl_topology_log;
  tl_topology_log = GlobalEngine()->TopologyLog();
  return tl_topology_log.c_str();
}

// Elastic-membership observability and control
// (docs/fault-tolerance.md#elastic-membership).  The epoch counts
// reshapes survived by this engine lifetime; the reshape total is
// process-cumulative.  Info serializes "epoch|size|lost_csv|joined_csv".
// Ack clears the post-reshape enqueue poison after Python has resynced
// state in the new membership (hvd.run_elastic calls it).
int hvd_tpu_elastic_enabled() {
  return GlobalEngine()->ElasticEnabled() ? 1 : 0;
}

long long hvd_tpu_membership_epoch() {
  return GlobalEngine()->MembershipEpoch();
}

long long hvd_tpu_membership_reshapes() {
  return GlobalEngine()->ReshapeEvents();
}

const char* hvd_tpu_membership_info() {
  static thread_local std::string tl_membership_info;
  tl_membership_info = GlobalEngine()->MembershipInfo();
  return tl_membership_info.c_str();
}

int hvd_tpu_membership_ack_pending() {
  return GlobalEngine()->ReshapeAckPending() ? 1 : 0;
}

void hvd_tpu_membership_ack() { GlobalEngine()->MembershipAck(); }

// Control-plane observability (docs/performance.md
// #control-plane-scaling): "tree|children|hosts|steady_active|
// pattern_len|steady_threshold|entries|exits|replays|steady_cycles|
// negotiated_ticks|frames_sent|frames_recv" — the tree shape this rank
// sees, the decentralized-steady-state counters (process-cumulative),
// and the control-frame counters the zero-frames-per-steady-cycle
// contract is asserted against.
const char* hvd_tpu_control_info() {
  static thread_local std::string tl_control_info;
  tl_control_info = GlobalEngine()->ControlInfo();
  return tl_control_info.c_str();
}

// Whether this rank is currently self-clocking in the decentralized
// steady state (zero control-plane frames per replay cycle).
int hvd_tpu_steady_active() {
  return GlobalEngine()->SteadyActive() ? 1 : 0;
}

// Simulated-scale negotiation harness (bench.py
// BENCH_MODEL=negotiation_scale): run `size` in-process engine ranks
// over loopback and measure per-cycle negotiation latency star-vs-tree
// and negotiated-vs-steady.  Writes a one-line JSON report into `out`
// (truncated to out_len); returns 0 on success, 1 when the report
// signals a setup/driver failure.
int hvd_tpu_simscale_run(int size, int local_size, int ops_per_cycle,
                         int warm_cycles, int steady_cycles,
                         long long steady_threshold, int coord_tree,
                         int base_port, double timeout_sec, char* out,
                         long long out_len) {
  std::string rep = hvdtpu::SimScaleRun(
      size, local_size, ops_per_cycle, warm_cycles, steady_cycles,
      steady_threshold, coord_tree, base_port, timeout_sec);
  if (out && out_len > 0) {
    size_t n = std::min(static_cast<size_t>(out_len - 1), rep.size());
    memcpy(out, rep.data(), n);
    out[n] = '\0';
  }
  return rep.compare(0, 8, "{\"ok\":1,") == 0 ? 0 : 1;
}

// Timeline hooks for the XLA data plane (jax/eager_mesh.py): plane-side
// execution phases land in the same Chrome-tracing file as the engine's
// events.  All are no-ops when HOROVOD_TIMELINE is unset.
int hvd_tpu_timeline_enabled() {
  return GlobalEngine()->timeline().Enabled() ? 1 : 0;
}

void hvd_tpu_timeline_op_start(const char* name, const char* op) {
  GlobalEngine()->timeline().Start(name ? name : "", op ? op : "");
}

void hvd_tpu_timeline_activity_start(const char* name, const char* activity) {
  GlobalEngine()->timeline().ActivityStart(name ? name : "",
                                           activity ? activity : "");
}

void hvd_tpu_timeline_activity_end(const char* name) {
  GlobalEngine()->timeline().ActivityEnd(name ? name : "");
}

void hvd_tpu_timeline_op_end(const char* name, long long bytes) {
  GlobalEngine()->timeline().End(name ? name : "", bytes);
}

// Instant event on `name`'s row — the Python span API's trace_marker.
void hvd_tpu_timeline_instant(const char* name, const char* label) {
  GlobalEngine()->timeline().Instant(name ? name : "", label ? label : "");
}

// Flush buffered trace events to disk without closing the file: the
// fault injector calls this before an injected crash so the post-mortem
// trace parses (docs/timeline.md).
void hvd_tpu_timeline_flush() { GlobalEngine()->timeline().Flush(); }

}  // extern "C"
