// Online parameter autotuning (docs/performance.md#autotuning).
//
// The engine's two dominant performance knobs — the tensor-fusion
// threshold and the negotiation cycle time — default to static values no
// single workload agrees on (a 32-byte-allreduce transformer step wants a
// tight cycle, a 100 MB-gradient CNN step wants big fusion buckets).  The
// ParameterManager is the engine-side analogue of the reference's later
// ParameterManager autotuner: rank 0 scores each tuning window from the
// throughput the coordinator already observes (payload bytes of every
// negotiated collective / wall time over the window), proposes the next
// (fusion_threshold, cycle_time_ms) candidate, and the engine broadcasts
// it inside the existing coordinator response list so EVERY rank applies
// it at the same tick boundary — the same lockstep-mutation contract the
// negotiation response cache rides.
//
// Search policy: warmup (discard the first W windows) -> coordinate-
// descent hill-climb over a log-spaced grid, one knob at a time, with a
// best-so-far memory of every (point -> score) measured; when the score
// stops improving by more than epsilon for K consecutive windows the
// tuner FREEZES at the best point ever seen and the steady-state fast
// path runs untouched.  HVD_TPU_AUTOTUNE_FIX pins a knob by collapsing
// its grid to the fixed value.
//
// Threading: Record()/Tick() run on the engine thread only (rank 0 /
// single-process); the observability getters are called from Python API
// threads and are guarded by an internal mutex.
#pragma once

#include <array>
#include <chrono>
#include <cstdint>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace hvdtpu {

// Log-spaced candidate grids.  Mirrored in Python
// (horovod_tpu/common/autotune.py) for docs and tests — keep in sync.
extern const std::vector<int64_t> kFusionGrid;   // bytes
extern const std::vector<double> kCycleGridMs;   // milliseconds
// Wire-compression axis (CompressionMode codes, none -> bf16 -> fp8):
// searched only when the job opted into compression at init — the tuner
// must never turn a lossy wire format on for a job that asked for exact
// fp32 (engine.cc pins the axis at the env value in that case).
extern const std::vector<int64_t> kCompressionGrid;
// Two-level cross-node ring-vs-tree boundary (bytes; buckets under it take
// the recursive-doubling tree exchange): searched only when the job runs
// the hierarchical topology — on the flat ring the knob is dead and
// engine.cc pins the axis at the env value.  0 = ring always.
extern const std::vector<int64_t> kCrossAlgoGrid;

class ParameterManager {
 public:
  struct Proposal {
    bool present = false;
    bool frozen = false;
    int64_t fusion_threshold = 0;
    int64_t cycle_time_us = 0;
    int64_t compression = 0;  // CompressionMode code
    int64_t cross_algo_threshold = 0;  // ring-vs-tree boundary, bytes
    int64_t window = 0;  // completed-window count when proposed
  };

  // `fix_fusion` / `fix_cycle_ms` / `fix_compression` / `fix_cross_algo`
  // pin a knob (< 0 = tune it); the initial values seed the search
  // (snapped to the nearest grid point in log space at the first
  // post-warmup broadcast).
  void Configure(bool enabled, int64_t warmup_windows, int64_t window_ops,
                 int64_t fix_fusion, double fix_cycle_ms,
                 int64_t fix_compression, int64_t fix_cross_algo,
                 int64_t init_fusion, double init_cycle_ms,
                 int64_t init_compression, int64_t init_cross_algo);

  bool enabled() const { return enabled_; }
  // Still searching: windows are being scored and candidates proposed.
  bool active() const { return enabled_ && !done_; }

  // Rank 0: account `n` negotiated collectives carrying `bytes` of
  // payload toward the current window (fresh negotiations and cache-bit
  // agreements both count; called where the coordinator aggregates
  // announces).
  void Record(int64_t bytes, int64_t n);

  // Rank 0, once per engine tick: closes the window when due and fills
  // `out` with the next candidate (or the freeze verdict).  `out->present`
  // stays false on ticks with nothing to broadcast.  `cur_fusion` /
  // `cur_cycle_ms` / `cur_compression` / `cur_cross_algo` are the
  // engine's currently APPLIED values — a manual injection that sets only
  // some knobs keeps the others at their applied values (which need not
  // be grid points).
  void Tick(std::chrono::steady_clock::time_point now, int64_t cur_fusion,
            double cur_cycle_ms, int64_t cur_compression,
            int64_t cur_cross_algo, Proposal* out);

  // Manual injection (hvd.autotune_set, the pluggable-policy seam): the
  // injected values are broadcast on the next tick and the search state
  // snaps to the nearest grid point so a resumed search continues from
  // there.  Values < 0 keep the current value for that knob.
  void Inject(int64_t fusion, double cycle_ms, int64_t compression,
              int64_t cross_algo);

  // Observability (any thread).
  int64_t windows() const;
  double best_score() const;
  // "window|fusion_bytes|cycle_us|score;..." — one entry per scored
  // window (the params the window ran under), bounded.
  std::string History() const;

 private:
  int64_t GridFusion() const { return axes_fusion_[idx_[0]]; }
  double GridCycleMs() const { return axes_cycle_[idx_[1]]; }
  int64_t GridCompression() const { return axes_comp_[idx_[2]]; }
  int64_t GridCrossAlgo() const { return axes_algo_[idx_[3]]; }
  Proposal MakeProposal(bool frozen);
  // Broadcast the snapped anchor point (or the freeze verdict when both
  // knobs are pinned); the measured score of the window that triggered
  // it is discarded — it ran under the raw initial params.
  void BroadcastAnchor(Proposal* out);
  void CloseWindow(double score, Proposal* out);
  // Advance the hill climb after measuring `score` at the current point;
  // fills `out` when the move (or freeze) changes the broadcast params.
  void Step(double score, Proposal* out);
  bool MoveOn(int axis, int dir);    // try idx_[axis] += dir; false if OOB
  void SwitchAxis(double last_score);
  void FreezeAtBest(Proposal* out);

  bool enabled_ = false;
  bool done_ = false;          // frozen (or nothing tunable)
  bool anchored_ = false;      // snapped anchor point broadcast yet?
  int64_t warmup_left_ = 0;
  int64_t window_ops_ = 32;

  std::vector<int64_t> axes_fusion_;
  std::vector<double> axes_cycle_;
  std::vector<int64_t> axes_comp_;
  std::vector<int64_t> axes_algo_;
  // Raw initial env values — what warmup windows actually run under
  // (the applied params change only at the first broadcast).
  int64_t init_fusion_ = 0;
  double init_cycle_ms_ = 0.0;
  int64_t init_comp_ = 0;
  int64_t init_algo_ = 0;
  int idx_[4] = {0, 0, 0, 0};  // grid point (fusion, cycle, comp, algo)
  int axis_ = 1;               // knob being climbed (cycle first: the
                               // idle-cadence win is the common case)
  int dir_ = -1;               // climb direction on axis_
  bool tried_flip_ = false;    // other direction already tried from anchor
  bool have_anchor_ = false;   // anchor_score_ valid for axis_
  double anchor_score_ = 0.0;  // best score at the anchor point of axis_
  int anchor_idx_ = 0;

  // Window accumulation (engine thread only).
  int64_t win_bytes_ = 0;
  int64_t win_ops_ = 0;
  bool win_open_ = false;
  std::chrono::steady_clock::time_point win_start_{};

  // Best-so-far memory over measured grid points: (score sum, samples).
  // The freeze verdict takes the argmax of per-point MEANS — repeated
  // visits (anchors are re-measured on every axis switch) average out
  // window noise instead of keeping a lucky spike.
  std::map<std::array<int, 4>, std::pair<double, int>> memory_;
  std::array<int, 4> best_point_{{0, 0, 0, 0}};
  bool have_best_ = false;
  int stall_windows_ = 0;

  // Manual injection mailbox (API thread -> engine thread).
  mutable std::mutex mu_;  // guards inject_*, windows_, best_score_, history_
  bool inject_pending_ = false;
  int64_t inject_fusion_ = -1;
  double inject_cycle_ms_ = -1.0;
  int64_t inject_comp_ = -1;
  int64_t inject_algo_ = -1;

  int64_t windows_ = 0;
  double best_score_ = 0.0;
  std::deque<std::string> history_;
};

}  // namespace hvdtpu
