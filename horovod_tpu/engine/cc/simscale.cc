#include "simscale.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <memory>
#include <thread>
#include <vector>

#include "engine.h"
#include "net.h"

namespace hvdtpu {

namespace {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

double Pct(std::vector<int64_t>& v, double q) {
  if (v.empty()) return 0.0;
  std::sort(v.begin(), v.end());
  size_t idx = static_cast<size_t>(q * (v.size() - 1) + 0.5);
  return static_cast<double>(v[std::min(idx, v.size() - 1)]);
}

// Probe-bindable loopback port at or after `*next` (advancing it), or
// -1 when the scan runs out.  A fixed contiguous block collides with
// whatever ephemeral connections the host happens to hold (a single
// taken port stalls the whole rendezvous to its accept timeout); the
// engines all live in this process, so the endpoint list can simply
// carry whichever ports probe free.
int ProbeFreePort(int* next) {
  for (; *next < 65000; ++*next) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    // Probe exactly what the engine's Listen will bind (0.0.0.0): a
    // port held on a non-loopback interface passes a loopback-only
    // probe and then fails the real bind.
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(*next));
    bool ok = bind(fd, reinterpret_cast<sockaddr*>(&addr),
                   sizeof(addr)) == 0;
    close(fd);
    if (ok) return (*next)++;
  }
  return -1;
}

// Parse field `idx` of the '|'-separated ControlInfo string.
int64_t InfoField(const std::string& info, int idx) {
  size_t pos = 0;
  for (int i = 0; i < idx; ++i) {
    pos = info.find('|', pos);
    if (pos == std::string::npos) return 0;
    ++pos;
  }
  return atoll(info.c_str() + pos);
}

}  // namespace

std::string SimScaleRun(int size, int local_size, int ops_per_cycle,
                        int warm_cycles, int steady_cycles,
                        long long steady_threshold, int coord_tree,
                        int base_port, double timeout_sec) {
  if (size < 2 || size > 1024 || ops_per_cycle < 1 || local_size < 1 ||
      size % local_size != 0)
    return "{\"ok\":0,\"error\":\"bad harness geometry\"}";
  // Allocate the fleet's ports BELOW the kernel's ephemeral range when
  // it leaves room: the rendezvous storm's own outgoing connections
  // draw ephemeral source ports, and a probed-free port inside that
  // range can be stolen as somebody's source port in the window between
  // the probe and the engine's bind (observed as one-in-few 256-rank
  // init failures).  Ports under the range can only collide with real
  // listeners, which the probe sees reliably.
  int eph_lo = 32768;
  if (FILE* f = fopen("/proc/sys/net/ipv4/ip_local_port_range", "r")) {
    int a, b;
    if (fscanf(f, "%d %d", &a, &b) == 2) eph_lo = a;
    fclose(f);
  }
  int scan;
  if (eph_lo - 1100 > size + 8) {
    int span = eph_lo - 1100 - (size + 8);
    scan = 1100 + (base_port > 0 ? base_port % span : 0);
  } else {
    scan = base_port > 0 ? base_port : 20000;
  }
  int coord_port = ProbeFreePort(&scan);
  if (coord_port < 0)
    return "{\"ok\":0,\"error\":\"no free loopback ports\"}";
  std::string coord_ep = "127.0.0.1:" + std::to_string(coord_port);
  std::vector<std::string> data_eps;
  for (int r = 0; r < size; ++r) {
    int p = ProbeFreePort(&scan);
    if (p < 0) return "{\"ok\":0,\"error\":\"no free loopback ports\"}";
    data_eps.push_back("127.0.0.1:" + std::to_string(p));
  }

  std::vector<std::unique_ptr<Engine>> engines;
  for (int r = 0; r < size; ++r) engines.emplace_back(new Engine());

  // Concurrent Init: the socket rendezvous blocks until every rank
  // connected, so all N must run simultaneously.
  std::atomic<int> init_fail{-1};
  std::vector<std::string> init_errs(size);
  {
    std::vector<std::thread> ts;
    for (int r = 0; r < size; ++r)
      ts.emplace_back([&, r]() {
        EngineOptions o;
        o.rank = r;
        o.size = size;
        o.local_rank = r % local_size;
        o.local_size = local_size;
        o.coord_endpoint = coord_ep;
        o.data_endpoints = data_eps;
        o.cycle_time_ms = 1.0;
        o.stall_warning_sec = 600.0;
        // The harness's own hang watchdog: a wedged negotiation aborts
        // typed instead of deadlocking the bench process.
        o.collective_timeout_sec = timeout_sec;
        o.cache_capacity = 1024;
        o.coord_tree = coord_tree != 0;
        o.steady_threshold = steady_threshold;
        if (engines[r]->Init(o, &init_errs[r]) != 0) init_fail.store(r);
      });
    for (auto& t : ts) t.join();
  }
  if (init_fail.load() >= 0) {
    int r = init_fail.load();
    std::string msg = init_errs[r];
    for (auto& c : msg)
      if (c == '"' || c == '\\' || c == '\n') c = ' ';
    for (auto& e : engines) e->Shutdown();
    return "{\"ok\":0,\"error\":\"rank " + std::to_string(r) +
           " init failed: " + msg + "\"}";
  }

  // Driver threads: per cycle, enqueue-all-then-wait a fixed set of
  // NOOP names (the XLA-metadata negotiation pattern at scale).  Rank
  // 0's driver records per-cycle enqueue->complete latency.  Drivers
  // PACE between cycles: on real hardware every rank is its own host,
  // but here hundreds of rank fleets share one machine, and an unpaced
  // free-run saturates the cores so the measured "cycle latency" is the
  // simulation's run-queue depth, not the control plane.  The pace gap
  // (the step's compute time, in a real job) is excluded from the
  // measurement and SCALES with the fleet so the simulation's aggregate
  // wake rate — its CPU footprint on this one machine — stays constant
  // across sizes; what the cells compare is the measured per-cycle
  // control-plane cost, which the pace sits outside of.
  const auto kPace =
      std::chrono::microseconds(3000 * std::max(1, size / 16));
  const int total_cycles = warm_cycles + steady_cycles;
  std::vector<int64_t> cycle_us(total_cycles, 0);
  std::atomic<bool> drive_fail{false};
  // Frame counters are snapshotted per rank the first cycle AFTER that
  // rank's engine reports steady (so a late arming never counts tail
  // negotiation frames into the delta), falling back to the warm/steady
  // boundary when steady never arms — then the delta is the star
  // baseline's per-cycle frame cost, which is the point of comparison.
  std::vector<int64_t> frames_at_boundary(size, -1);
  {
    std::vector<std::thread> ts;
    for (int r = 0; r < size; ++r)
      ts.emplace_back([&, r]() {
        Engine* e = engines[r].get();
        std::vector<int64_t> dims{1};
        for (int c = 0; c < total_cycles && !drive_fail.load(); ++c) {
          if (frames_at_boundary[r] < 0 &&
              (e->SteadyActive() || c == warm_cycles))
            frames_at_boundary[r] = e->CtrlFramesSent();
          int64_t t0 = NowUs();
          std::vector<int64_t> handles;
          handles.reserve(ops_per_cycle);
          for (int k = 0; k < ops_per_cycle; ++k) {
            int64_t h = e->Enqueue(OP_NOOP, "sim." + std::to_string(k),
                                   nullptr, nullptr, dims, HVD_FLOAT32, -1,
                                   false);
            if (h < 0) {
              drive_fail.store(true);
              return;
            }
            handles.push_back(h);
          }
          for (int64_t h : handles) {
            if (e->Wait(h) != ST_OK) {
              drive_fail.store(true);
              return;
            }
            e->Release(h);
          }
          if (r == 0) cycle_us[c] = NowUs() - t0;
          std::this_thread::sleep_for(kPace);
        }
      });
    for (auto& t : ts) t.join();
  }

  // Post-run accounting BEFORE shutdown (shutdown frames would pollute
  // the steady-frame delta).
  bool steady_entered = false;
  int64_t steady_cycle_count = 0;
  int64_t frames_delta_max = 0;
  for (int r = 0; r < size; ++r) {
    std::string info = engines[r]->ControlInfo();
    steady_entered = steady_entered || InfoField(info, 3) != 0 ||
                     InfoField(info, 6) > 0;  // active now, or entered
    steady_cycle_count = std::max(steady_cycle_count, InfoField(info, 9));
    if (frames_at_boundary[r] >= 0)
      frames_delta_max =
          std::max(frames_delta_max,
                   engines[r]->CtrlFramesSent() - frames_at_boundary[r]);
  }
  int64_t coord_children = InfoField(engines[0]->ControlInfo(), 1);
  int64_t negotiated = InfoField(engines[0]->ControlInfo(), 10);
  // Heartbeat-overhead surface (docs/performance.md#control-plane-
  // scaling): the detector rides env (HVD_TPU_HEARTBEAT_MS), so the
  // bench toggles it per cell and compares steady p50s; the frame count
  // proves which regime each cell actually ran in.  clock_fanin is rank
  // 0's init clock-sync probe count — O(direct children), the
  // tree-relay satellite's assert surface.
  int64_t hb_frames_sent = 0;
  for (int r = 0; r < size; ++r)
    hb_frames_sent = std::max(
        hb_frames_sent, InfoField(engines[r]->LivenessInfo(), 2));
  int64_t clock_fanin = InfoField(engines[0]->LivenessInfo(), 6);

  bool failed = drive_fail.load();
  {
    std::vector<std::thread> ts;
    for (auto& e : engines)
      ts.emplace_back([&e]() { e->Shutdown(); });
    for (auto& t : ts) t.join();
  }
  engines.clear();
  if (failed)
    return "{\"ok\":0,\"error\":\"a driver saw a failed collective "
           "(timeout or abort) - see stderr\"}";

  std::vector<int64_t> warm(cycle_us.begin() + std::min(2, warm_cycles),
                            cycle_us.begin() + warm_cycles);
  std::vector<int64_t> steady(cycle_us.begin() + warm_cycles,
                              cycle_us.end());
  char out[640];
  snprintf(out, sizeof(out),
           "{\"ok\":1,\"size\":%d,\"tree\":%d,\"steady_entered\":%d,"
           "\"warm_p50_us\":%.1f,\"warm_p90_us\":%.1f,"
           "\"steady_p50_us\":%.1f,\"steady_p90_us\":%.1f,"
           "\"steady_frames_delta\":%lld,\"steady_cycles\":%lld,"
           "\"coord_children\":%lld,\"negotiated_cycles\":%lld,"
           "\"hb_frames_sent\":%lld,\"clock_fanin\":%lld,"
           "\"link_sends\":%lld}",
           size, coord_tree ? 1 : 0, steady_entered ? 1 : 0,
           Pct(warm, 0.5), Pct(warm, 0.9), Pct(steady, 0.5),
           Pct(steady, 0.9), static_cast<long long>(frames_delta_max),
           static_cast<long long>(steady_cycle_count),
           static_cast<long long>(coord_children),
           static_cast<long long>(negotiated),
           static_cast<long long>(hb_frames_sent),
           static_cast<long long>(clock_fanin),
           static_cast<long long>(NetLinkSendsTotal()));
  return out;
}

}  // namespace hvdtpu
