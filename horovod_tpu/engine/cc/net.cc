#include "net.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <mutex>
#include <thread>
#include <unordered_map>

namespace hvdtpu {

namespace {

double NowSec() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void SetCommonOpts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Large kernel buffers keep the bandwidth-optimal ring streaming instead
  // of stalling on window exhaustion at multi-megabyte segments.
  int buf = 8 * 1024 * 1024;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

bool ResolveAddr(const std::string& host, int port, sockaddr_in* addr) {
  memset(addr, 0, sizeof(*addr));
  addr->sin_family = AF_INET;
  addr->sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr->sin_addr) == 1) return true;
  struct addrinfo hints, *res = nullptr;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
    return false;
  addr->sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  return true;
}

// ---------------------------------------------------------------------------
// Link-fault injection (HVD_TPU_NET_FAULT_SPEC).  One process-global
// table: the spec is identical on every rank (each applies the clauses
// touching its own links), clauses are parsed once per engine Init, and
// every per-send decision is a mutex-guarded map lookup — zero cost when
// no spec is armed (one relaxed atomic load).
// ---------------------------------------------------------------------------

struct FaultClause {
  bool partition = false;          // partition=G1/G2 (drop across groups)
  int a = -1, b = -1;              // link=A-B endpoints
  std::vector<int> group_a, group_b;
  bool drop = false;
  double delay_ms = 0.0, jitter_ms = 0.0;
  double flaky = 0.0;              // per-send chopped-write probability
  double after_sec = 0.0;          // clause arms this long after Init
  std::string text;                // source clause (typed-error messages)
};

struct FaultFd {
  int peer = -1;
  int clause = -1;   // index into g_fault_clauses; -1 = no clause matches
  uint32_t rng = 1;  // deterministic per-link LCG state
};

std::mutex g_fault_mu;
std::vector<FaultClause> g_fault_clauses;
std::unordered_map<int, FaultFd> g_fault_fds;
// Peer-rank-keyed clause state for the shm seam (rings have no fd);
// lazily resolved, reset by NetFaultInit like the fd registry.
std::unordered_map<int, FaultFd> g_fault_peers;
int g_fault_rank = -1;
uint32_t g_fault_seed = 0;
double g_fault_t0 = 0.0;
std::atomic<bool> g_fault_armed{false};

// ---------------------------------------------------------------------------
// Per-peer link telemetry (net.h NetLink*).  Shares g_fault_mu with the
// fd -> peer registry above so one lock hold covers both the lookup and
// the stat update; keyed by PEER RANK (stats survive fd churn and
// re-init — the StallEvents process-cumulative contract).
// ---------------------------------------------------------------------------

struct LinkStats {
  long long bytes_out = 0, bytes_in = 0;
  long long sends = 0, recvs = 0;
  long long stalls = 0;        // EAGAIN retry events on a send path
  long long short_writes = 0;  // kernel accepted fewer bytes than asked
  long long send_us_sum = 0;
  long long send_us_count = 0;
  long long send_us_buckets[10] = {0};
  long long rtt_last_us = -1;
  double rtt_ewma_us = 0.0;
  long long rtt_samples = 0;
  // Shm-hop counters (docs/metrics.md#links): ring-handoff bytes and
  // the segment-handoff latency histogram, same bucket bounds as the
  // timed-send histogram so one `le` label set serves both.
  long long shm_bytes_out = 0, shm_bytes_in = 0;
  long long shm_handoffs = 0;
  long long shm_us_sum = 0;
  long long shm_us_count = 0;
  long long shm_us_buckets[10] = {0};
};

std::map<int, LinkStats> g_link_stats;  // guarded by g_fault_mu
std::atomic<bool> g_link_enabled{false};

long long LinkNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int LinkBucket(long long us) {
  for (int i = 0; i < kNetLinkBuckets - 1; ++i)
    if (us <= kNetLinkBucketUs[i]) return i;
  return kNetLinkBuckets - 1;
}

// One locked update per transport CALL (never per byte): bytes in/out,
// stall/short-write counts, and — when lat_us >= 0 — one timed-send
// histogram sample.  Unregistered fds (pre-registration rendezvous
// traffic, joiner handshakes) fall through untouched.
void LinkRecord(int fd, long long bytes_out, long long bytes_in,
                long long stalls, long long shorts, long long lat_us) {
  std::lock_guard<std::mutex> lk(g_fault_mu);
  auto it = g_fault_fds.find(fd);
  if (it == g_fault_fds.end() || it->second.peer < 0) return;
  LinkStats& s = g_link_stats[it->second.peer];
  s.bytes_out += bytes_out;
  s.bytes_in += bytes_in;
  s.stalls += stalls;
  s.short_writes += shorts;
  if (bytes_in > 0) ++s.recvs;
  if (lat_us >= 0) {
    ++s.sends;
    s.send_us_sum += lat_us;
    ++s.send_us_count;
    ++s.send_us_buckets[LinkBucket(lat_us)];
  }
}

bool ClauseMatches(const FaultClause& c, int me, int peer) {
  if (c.partition) {
    auto in = [](const std::vector<int>& g, int r) {
      for (int x : g) if (x == r) return true;
      return false;
    };
    return (in(c.group_a, me) && in(c.group_b, peer)) ||
           (in(c.group_b, me) && in(c.group_a, peer));
  }
  return (c.a == me && c.b == peer) || (c.b == me && c.a == peer);
}

int ResolveClause(int me, int peer) {
  for (size_t i = 0; i < g_fault_clauses.size(); ++i)
    if (ClauseMatches(g_fault_clauses[i], me, peer))
      return static_cast<int>(i);
  return -1;
}

bool ClauseArmed(const FaultClause& c) {
  return NowSec() - g_fault_t0 >= c.after_sec;
}

double NextRand01(uint32_t* state) {
  *state = *state * 1664525u + 1013904223u;
  return (*state >> 8) / double(1u << 24);
}

bool ParseRankCsv(const std::string& s, std::vector<int>* out) {
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    std::string tok = s.substr(pos, comma == std::string::npos
                                        ? std::string::npos
                                        : comma - pos);
    char* end = nullptr;
    long r = strtol(tok.c_str(), &end, 10);
    if (tok.empty() || *end != '\0' || r < 0) return false;
    out->push_back(static_cast<int>(r));
    if (comma == std::string::npos) break;
    pos = comma + 1;
  }
  return !out->empty();
}

bool ParseFaultClause(std::string body, FaultClause* c, std::string* err) {
  size_t after = body.rfind("@after=");
  if (after != std::string::npos) {
    char* end = nullptr;
    c->after_sec = strtod(body.c_str() + after + 7, &end);
    if (*end != '\0' || c->after_sec < 0) {
      *err = "bad @after in '" + body + "'";
      return false;
    }
    body = body.substr(0, after);
  }
  if (body.rfind("partition=", 0) == 0) {
    c->partition = true;
    c->drop = true;
    std::string groups = body.substr(10);
    size_t slash = groups.find('/');
    if (slash == std::string::npos ||
        !ParseRankCsv(groups.substr(0, slash), &c->group_a) ||
        !ParseRankCsv(groups.substr(slash + 1), &c->group_b)) {
      *err = "bad partition groups in '" + body + "'";
      return false;
    }
    return true;
  }
  if (body.rfind("link=", 0) != 0) {
    *err = "clause must start with link= or partition=: '" + body + "'";
    return false;
  }
  size_t colon = body.find(':', 5);
  if (colon == std::string::npos) {
    *err = "link clause missing ':action' in '" + body + "'";
    return false;
  }
  std::string pair = body.substr(5, colon - 5);
  size_t dash = pair.find('-');
  char* end = nullptr;
  long a = strtol(pair.c_str(), &end, 10);
  if (dash == std::string::npos || end != pair.c_str() + dash || a < 0) {
    *err = "bad link endpoints in '" + body + "'";
    return false;
  }
  long b = strtol(pair.c_str() + dash + 1, &end, 10);
  if (*end != '\0' || b < 0 || a == b) {
    *err = "bad link endpoints in '" + body + "'";
    return false;
  }
  c->a = static_cast<int>(a);
  c->b = static_cast<int>(b);
  std::string actions = body.substr(colon + 1);
  size_t pos = 0;
  while (pos <= actions.size()) {
    size_t bar = actions.find('|', pos);
    std::string act = actions.substr(
        pos, bar == std::string::npos ? std::string::npos : bar - pos);
    if (act == "drop") {
      c->drop = true;
    } else if (act.rfind("delay=", 0) == 0) {
      c->delay_ms = strtod(act.c_str() + 6, &end);
      if (*end != '\0' || c->delay_ms < 0) {
        *err = "bad delay in '" + body + "'";
        return false;
      }
    } else if (act.rfind("jitter=", 0) == 0) {
      c->jitter_ms = strtod(act.c_str() + 7, &end);
      if (*end != '\0' || c->jitter_ms < 0) {
        *err = "bad jitter in '" + body + "'";
        return false;
      }
    } else if (act.rfind("flaky=", 0) == 0) {
      c->flaky = strtod(act.c_str() + 6, &end);
      if (*end != '\0' || c->flaky < 0 || c->flaky > 1) {
        *err = "bad flaky probability in '" + body + "'";
        return false;
      }
    } else {
      *err = "unknown link action '" + act + "' in '" + body + "'";
      return false;
    }
    if (bar == std::string::npos) break;
    pos = bar + 1;
  }
  if (!c->drop && c->delay_ms == 0 && c->flaky == 0) {
    *err = "link clause with no effect in '" + body + "'";
    return false;
  }
  return true;
}

}  // namespace

bool NetFaultInit(const std::string& spec, int my_rank, std::string* err) {
  std::lock_guard<std::mutex> lk(g_fault_mu);
  g_fault_clauses.clear();
  g_fault_rank = my_rank;
  g_fault_t0 = NowSec();
  g_fault_seed = 2166136261u;
  for (char ch : spec) g_fault_seed = (g_fault_seed ^ (uint8_t)ch) * 16777619u;
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t semi = spec.find(';', pos);
    std::string body = spec.substr(
        pos, semi == std::string::npos ? std::string::npos : semi - pos);
    while (!body.empty() && body.front() == ' ') body.erase(body.begin());
    while (!body.empty() && body.back() == ' ') body.pop_back();
    if (!body.empty()) {
      FaultClause c;
      if (!ParseFaultClause(body, &c, err)) {
        g_fault_clauses.clear();
        g_fault_armed.store(false);
        return false;
      }
      c.text = body;  // full clause incl. @after, for typed messages
      g_fault_clauses.push_back(std::move(c));
    }
    if (semi == std::string::npos) break;
    pos = semi + 1;
  }
  // Re-resolve fds registered before a re-init against the fresh table.
  for (auto& kv : g_fault_fds)
    kv.second.clause = ResolveClause(g_fault_rank, kv.second.peer);
  g_fault_peers.clear();
  g_fault_armed.store(!g_fault_clauses.empty());
  return true;
}

bool NetFaultActive() {
  return g_fault_armed.load(std::memory_order_relaxed);
}

void NetFaultRegister(int fd, int peer_rank) {
  if (fd < 0) return;
  std::lock_guard<std::mutex> lk(g_fault_mu);
  FaultFd f;
  f.peer = peer_rank;
  f.clause = ResolveClause(g_fault_rank, peer_rank);
  // Seed from (spec, both endpoints) only — NOT the fd number — so a
  // rerun draws the identical chop/jitter sequence per link.
  int lo = std::min(g_fault_rank, peer_rank);
  int hi = std::max(g_fault_rank, peer_rank);
  f.rng = g_fault_seed ^ (static_cast<uint32_t>(lo) * 2654435761u) ^
          (static_cast<uint32_t>(hi) * 40503u) ^ 1u;
  g_fault_fds[fd] = f;
}

void NetFaultForget(int fd) {
  // Erase even when disarmed: a stale entry on a recycled fd number would
  // misattribute faults if a later NetFaultInit re-arms the table.
  if (fd < 0) return;
  std::lock_guard<std::mutex> lk(g_fault_mu);
  g_fault_fds.erase(fd);
}

bool NetFaultDrops(int fd) {
  if (!NetFaultActive()) return false;
  std::lock_guard<std::mutex> lk(g_fault_mu);
  auto it = g_fault_fds.find(fd);
  if (it == g_fault_fds.end() || it->second.clause < 0) return false;
  const FaultClause& c = g_fault_clauses[it->second.clause];
  return c.drop && ClauseArmed(c);
}

void NetFaultDelay(int fd) {
  if (!NetFaultActive()) return;
  double sleep_ms = 0.0;
  {
    std::lock_guard<std::mutex> lk(g_fault_mu);
    auto it = g_fault_fds.find(fd);
    if (it == g_fault_fds.end() || it->second.clause < 0) return;
    const FaultClause& c = g_fault_clauses[it->second.clause];
    if (c.delay_ms <= 0 || !ClauseArmed(c)) return;
    sleep_ms = c.delay_ms + c.jitter_ms * NextRand01(&it->second.rng);
  }
  std::this_thread::sleep_for(
      std::chrono::duration<double, std::milli>(sleep_ms));
}

size_t NetFaultChop(int fd) {
  if (!NetFaultActive()) return 0;
  size_t chop = 0;
  {
    std::lock_guard<std::mutex> lk(g_fault_mu);
    auto it = g_fault_fds.find(fd);
    if (it == g_fault_fds.end() || it->second.clause < 0) return 0;
    const FaultClause& c = g_fault_clauses[it->second.clause];
    if (c.flaky <= 0 || !ClauseArmed(c)) return 0;
    if (NextRand01(&it->second.rng) >= c.flaky) return 0;
    chop = 1 + static_cast<size_t>(NextRand01(&it->second.rng) * 511);
  }
  // The "flaky" stall: long enough to exercise the partial-write retry
  // paths, short enough that training completes (degradation, not fault).
  std::this_thread::sleep_for(std::chrono::microseconds(200));
  return chop;
}

int NetFaultQueryLink(int rank_a, int rank_b, std::string* text) {
  if (!NetFaultActive()) return 0;
  std::lock_guard<std::mutex> lk(g_fault_mu);
  int verdict = 0;
  for (const FaultClause& c : g_fault_clauses) {
    if (!ClauseMatches(c, rank_a, rank_b)) continue;
    // Arming time is irrelevant here: an @after clause that will fire
    // mid-run must shape the transport choice made at init.
    const int v = (c.drop || c.flaky > 0) ? 2 : 1;
    if (v > verdict) {
      verdict = v;
      if (text != nullptr) *text = c.text;
    }
  }
  return verdict;
}

void NetFaultDelayPeer(int peer_rank) {
  if (!NetFaultActive() || peer_rank < 0) return;
  double sleep_ms = 0.0;
  {
    std::lock_guard<std::mutex> lk(g_fault_mu);
    auto it = g_fault_peers.find(peer_rank);
    if (it == g_fault_peers.end()) {
      FaultFd f;
      f.peer = peer_rank;
      f.clause = ResolveClause(g_fault_rank, peer_rank);
      const int lo = std::min(g_fault_rank, peer_rank);
      const int hi = std::max(g_fault_rank, peer_rank);
      // Distinct stream from the fd-keyed registry (^2u vs ^1u) so the
      // shm jitter draw order never aliases a TCP lane's.
      f.rng = g_fault_seed ^ (static_cast<uint32_t>(lo) * 2654435761u) ^
              (static_cast<uint32_t>(hi) * 40503u) ^ 2u;
      it = g_fault_peers.emplace(peer_rank, f).first;
    }
    if (it->second.clause < 0) return;
    const FaultClause& c = g_fault_clauses[it->second.clause];
    if (c.delay_ms <= 0 || !ClauseArmed(c)) return;
    sleep_ms = c.delay_ms + c.jitter_ms * NextRand01(&it->second.rng);
  }
  std::this_thread::sleep_for(
      std::chrono::duration<double, std::milli>(sleep_ms));
}

bool ParseEndpoint(const std::string& ep, std::string* host, int* port) {
  size_t colon = ep.rfind(':');
  if (colon == std::string::npos || colon == 0 || colon + 1 >= ep.size())
    return false;
  *host = ep.substr(0, colon);
  char* end = nullptr;
  long p = strtol(ep.c_str() + colon + 1, &end, 10);
  if (*end != '\0' || p <= 0 || p > 65535) return false;
  *port = static_cast<int>(p);
  return true;
}

int Listen(const std::string& host, int port, std::string* err) {
  sockaddr_in addr;
  if (!ResolveAddr(host, port, &addr)) {
    *err = "cannot resolve " + host;
    return -1;
  }
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *err = strerror(errno);
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // Backlog sized for pod-scale rendezvous: at init every rank connects
  // to the rank-0 coordinator at once, and with the old backlog of 64 a
  // few-hundred-rank job hit accept-queue overflow — syncookies let the
  // client think it connected, then the server's unanswered final-ACK
  // retries RST it mid-handshake ("topology agreement exchange failed").
  // The kernel clamps to net.core.somaxconn.
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 4096) != 0) {
    *err = std::string("bind/listen ") + host + ":" + std::to_string(port) +
           ": " + strerror(errno);
    close(fd);
    return -1;
  }
  return fd;
}

int AcceptOne(int listen_fd, double timeout_sec, std::string* err) {
  struct pollfd p = {listen_fd, POLLIN, 0};
  int r = poll(&p, 1, static_cast<int>(timeout_sec * 1000));
  if (r <= 0) {
    *err = r == 0 ? "accept timeout" : strerror(errno);
    return -1;
  }
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd < 0) {
    *err = strerror(errno);
    return -1;
  }
  SetCommonOpts(fd);
  return fd;
}

int ConnectRetry(const std::string& host, int port, double timeout_sec,
                 std::string* err) {
  sockaddr_in addr;
  if (!ResolveAddr(host, port, &addr)) {
    *err = "cannot resolve " + host;
    return -1;
  }
  double deadline = NowSec() + timeout_sec;
  // Exponential backoff with jitter: 20ms doubling to a ~1s cap.  N ranks
  // hammering one late coordinator in 20ms lockstep both wastes CPU and
  // synchronizes the SYN bursts; the jitter (+/-25%, cheap LCG seeded per
  // call) de-correlates them.
  double delay_ms = 20.0;
  const double kMaxDelayMs = 1000.0;
  uint32_t jitter_state =
      static_cast<uint32_t>(NowSec() * 1e6) ^ (static_cast<uint32_t>(port) << 16);
  int attempts = 0;
  int last_errno = 0;
  while (true) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
      *err = strerror(errno);
      return -1;
    }
    ++attempts;
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      // TCP self-connect guard: when the target port sits in the
      // ephemeral range and the peer is not listening YET, the kernel
      // can pick the destination port as this socket's source port and
      // "succeed" via simultaneous open — the socket is connected to
      // ITSELF, the rendezvous hello echoes back, and the real peer
      // never hears from us (seen as one-in-N init failures of the
      // simulated-scale harness's loopback rendezvous storm).  Detect
      // the loop and retry; the self-connection's teardown frees the
      // port for the real listener.
      sockaddr_in self{}, peer{};
      socklen_t slen = sizeof(self), plen = sizeof(peer);
      if (getsockname(fd, reinterpret_cast<sockaddr*>(&self), &slen) == 0 &&
          getpeername(fd, reinterpret_cast<sockaddr*>(&peer), &plen) == 0 &&
          self.sin_port == peer.sin_port &&
          self.sin_addr.s_addr == peer.sin_addr.s_addr) {
        last_errno = ECONNREFUSED;
        close(fd);
      } else {
        SetCommonOpts(fd);
        return fd;
      }
    } else {
      last_errno = errno;
      close(fd);
    }
    if (NowSec() >= deadline) {
      *err = std::string("connect ") + host + ":" + std::to_string(port) +
             " timed out after " + std::to_string(attempts) +
             " attempts: " + strerror(last_errno);
      return -1;
    }
    jitter_state = jitter_state * 1664525u + 1013904223u;
    double jitter = 0.75 + 0.5 * (jitter_state >> 8) / double(1u << 24);
    double sleep_ms = delay_ms * jitter;
    double remaining_ms = (deadline - NowSec()) * 1000.0;
    if (sleep_ms > remaining_ms) sleep_ms = remaining_ms > 0 ? remaining_ms : 0;
    std::this_thread::sleep_for(
        std::chrono::duration<double, std::milli>(sleep_ms));
    delay_ms = std::min(delay_ms * 2.0, kMaxDelayMs);
  }
}

bool SendAll(int fd, const void* buf, size_t len) {
  size_t first_cap = 0;
  // The latency clock starts BEFORE the fault hooks: an injected
  // `link=A-B:delay=MS` sleep is part of what this link costs, and the
  // telemetry must see it the way a real slow route would look.
  const bool track = NetLinkEnabled();
  const long long t0 = track ? LinkNowUs() : 0;
  long long stalls = 0, shorts = 0;
  const size_t total = len;
  if (NetFaultActive()) {
    // A dropped link swallows the bytes but reports success: the sender
    // keeps running and the receiver sees pure silence (never EOF) — the
    // only observable is the heartbeat detector, exactly like a real
    // blackholed route.
    if (NetFaultDrops(fd)) return true;
    NetFaultDelay(fd);
    // Chop only the FIRST write of the call: one RNG draw per message,
    // which is what "per send" means in the spec grammar, and the retry
    // loop below transparently finishes the remainder.
    first_cap = NetFaultChop(fd);
  }
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    size_t want = len;
    if (first_cap > 0 && first_cap < want) want = first_cap;
    first_cap = 0;
    ssize_t n = send(fd, p, want, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR || errno == EAGAIN)) {
        if (n < 0 && errno == EAGAIN) ++stalls;
        continue;
      }
      return false;
    }
    if (static_cast<size_t>(n) < want) ++shorts;
    p += n;
    len -= static_cast<size_t>(n);
  }
  if (track)
    LinkRecord(fd, static_cast<long long>(total), 0, stalls, shorts,
               LinkNowUs() - t0);
  return true;
}

bool SendVec(int fd, const struct iovec* iov_in, int iovcnt) {
  size_t total = 0;
  for (int i = 0; i < iovcnt; ++i) total += iov_in[i].iov_len;
  if (total == 0) return true;
  const bool track = NetLinkEnabled();
  const long long t0 = track ? LinkNowUs() : 0;
  long long stalls = 0, shorts = 0;
  size_t first_cap = 0;
  if (NetFaultActive()) {
    if (NetFaultDrops(fd)) return true;  // blackhole, like SendAll
    NetFaultDelay(fd);
    first_cap = NetFaultChop(fd);
  }
  std::vector<struct iovec> iov(iov_in, iov_in + iovcnt);
  size_t idx = 0, left = total;
  while (left > 0) {
    while (idx < iov.size() && iov[idx].iov_len == 0) ++idx;
    ssize_t n;
    size_t asked;
    if (first_cap > 0) {
      // Chopped first write: emulate the flaky clause on the leading
      // iovec only; the loop below finishes the remainder gathered.
      asked = std::min(first_cap, iov[idx].iov_len);
      first_cap = 0;
      n = send(fd, iov[idx].iov_base, asked, MSG_NOSIGNAL);
    } else {
      asked = left;
      struct msghdr msg;
      memset(&msg, 0, sizeof(msg));
      msg.msg_iov = &iov[idx];
      msg.msg_iovlen = iov.size() - idx;
      n = sendmsg(fd, &msg, MSG_NOSIGNAL);
    }
    if (n <= 0) {
      if (n < 0 && (errno == EINTR || errno == EAGAIN)) {
        if (errno == EAGAIN) ++stalls;
        continue;
      }
      return false;
    }
    if (static_cast<size_t>(n) < asked) ++shorts;
    left -= static_cast<size_t>(n);
    size_t adv = static_cast<size_t>(n);
    while (adv > 0 && idx < iov.size()) {
      if (adv >= iov[idx].iov_len) {
        adv -= iov[idx].iov_len;
        iov[idx].iov_len = 0;
        ++idx;
      } else {
        iov[idx].iov_base = static_cast<char*>(iov[idx].iov_base) + adv;
        iov[idx].iov_len -= adv;
        adv = 0;
      }
    }
  }
  if (track)
    LinkRecord(fd, static_cast<long long>(total), 0, stalls, shorts,
               LinkNowUs() - t0);
  return true;
}

bool RecvAll(int fd, void* buf, size_t len) {
  const size_t total = len;
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  if (NetLinkEnabled())
    LinkRecord(fd, 0, static_cast<long long>(total), 0, 0, -1);
  return true;
}

bool PeerClosed(int fd) {
  if (fd < 0) return true;
  char probe;
  ssize_t r = recv(fd, &probe, 1, MSG_PEEK | MSG_DONTWAIT);
  if (r == 0) return true;                                // orderly EOF
  if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                errno == EINTR))
    return false;                                         // alive, just idle
  return r < 0;                                           // hard error
}

bool WaitReadable(int fd, double timeout_sec) {
  double deadline = NowSec() + timeout_sec;
  while (true) {
    double remaining = deadline - NowSec();
    if (remaining < 0) remaining = 0;
    struct pollfd p = {fd, POLLIN, 0};
    int r = poll(&p, 1, static_cast<int>(remaining * 1000));
    if (r > 0) return true;  // readable, error, or hup: let recv surface it
    if (r == 0) return false;
    if (errno != EINTR) return true;  // unexpected: defer to the recv path
  }
}

bool SendFrame(int fd, const std::vector<uint8_t>& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  uint8_t hdr[4] = {static_cast<uint8_t>(len), static_cast<uint8_t>(len >> 8),
                    static_cast<uint8_t>(len >> 16),
                    static_cast<uint8_t>(len >> 24)};
  // One gathered sendmsg instead of two sends: the 4-byte header and the
  // payload leave straight from their own buffers in a single syscall —
  // no stage copy and no header-only segment on the wire.
  struct iovec iov[2];
  iov[0].iov_base = hdr;
  iov[0].iov_len = sizeof(hdr);
  iov[1].iov_base = const_cast<uint8_t*>(payload.data());
  iov[1].iov_len = payload.size();
  return SendVec(fd, iov, payload.empty() ? 1 : 2);
}

bool RecvFrame(int fd, std::vector<uint8_t>* payload) {
  uint8_t hdr[4];
  if (!RecvAll(fd, hdr, 4)) return false;
  uint32_t len = static_cast<uint32_t>(hdr[0]) |
                 (static_cast<uint32_t>(hdr[1]) << 8) |
                 (static_cast<uint32_t>(hdr[2]) << 16) |
                 (static_cast<uint32_t>(hdr[3]) << 24);
  payload->resize(len);
  return len == 0 || RecvAll(fd, payload->data(), len);
}

bool RecvAvailable(int fd, std::vector<uint8_t>* buf) {
  uint8_t tmp[512];
  while (true) {
    ssize_t n = recv(fd, tmp, sizeof(tmp), MSG_DONTWAIT);
    if (n > 0) {
      buf->insert(buf->end(), tmp, tmp + n);
      continue;
    }
    if (n == 0) return false;  // EOF
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
    return false;
  }
}

bool Exchange(int send_fd, const void* sbuf, size_t slen, int recv_fd,
              void* rbuf, size_t rlen) {
  const char* sp = static_cast<const char*>(sbuf);
  char* rp = static_cast<char*>(rbuf);
  size_t sent = 0, recvd = 0;
  long long stalls = 0, shorts = 0;
  bool flaky_send = false;
  if (NetFaultActive()) {
    if (NetFaultDrops(send_fd)) sent = slen;  // blackhole the send leg
    if (sent < slen) {
      NetFaultDelay(send_fd);
      flaky_send = true;  // consult the chop table per send iteration
    }
  }
  // Same fd for both directions is fine: poll events are independent.
  while (sent < slen || recvd < rlen) {
    struct pollfd fds[2];
    int n = 0;
    int si = -1, ri = -1;
    if (sent < slen) {
      fds[n] = {send_fd, POLLOUT, 0};
      si = n++;
    }
    if (recvd < rlen) {
      fds[n] = {recv_fd, POLLIN, 0};
      ri = n++;
    }
    int r = poll(fds, static_cast<nfds_t>(n), 30000);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // 30s of total silence: peer is gone
    if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      // MSG_DONTWAIT: the fds are blocking sockets; without it this send
      // would block until the whole remaining segment is buffered, stalling
      // the recv leg and deadlocking the ring when segments exceed kernel
      // socket buffering (all ranks sending, none draining).
      size_t want = slen - sent;
      if (flaky_send) {
        size_t cap = NetFaultChop(send_fd);
        if (cap > 0 && cap < want) want = cap;
      }
      ssize_t w = send(send_fd, sp + sent, want,
                       MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (w < 0 && errno == EAGAIN) ++stalls;
      if (w > 0) {
        if (static_cast<size_t>(w) < want) ++shorts;
        sent += static_cast<size_t>(w);
      }
    }
    if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t g = recv(recv_fd, rp + recvd, rlen - recvd, 0);
      if (g == 0) return false;
      if (g < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (g > 0) recvd += static_cast<size_t>(g);
    }
  }
  // Bytes and stall counts only — the poll-multiplexed legs overlap, so a
  // wall-clock span here would measure the slower DIRECTION, not this
  // link's send cost (the timed samples come from SendAll callers).
  if (NetLinkEnabled()) {
    if (slen > 0)
      LinkRecord(send_fd, static_cast<long long>(slen), 0, stalls, shorts,
                 -1);
    if (rlen > 0) LinkRecord(recv_fd, 0, static_cast<long long>(rlen), 0, 0, -1);
  }
  return true;
}

bool ExchangeBi(int right_fd, const void* send_r, size_t send_r_len,
                void* recv_r, size_t recv_r_len, int left_fd,
                const void* send_l, size_t send_l_len, void* recv_l,
                size_t recv_l_len) {
  // Four independent legs over the two full-duplex neighbour sockets:
  // stream A flows rightward (send on right_fd, arrive on left_fd as
  // recv_l), stream B flows leftward (send on left_fd, arrive on right_fd
  // as recv_r).  One poll loop drives all four so both directions of both
  // links stay busy simultaneously — the bandwidth-doubling property of a
  // bidirectional ring.
  struct Leg {
    int fd;
    const char* sp = nullptr;
    char* rp = nullptr;
    size_t len, done = 0;
    long long stalls = 0, shorts = 0;  // send legs only
  };
  Leg sr{right_fd, static_cast<const char*>(send_r), nullptr, send_r_len};
  Leg sl{left_fd, static_cast<const char*>(send_l), nullptr, send_l_len};
  Leg rr{right_fd, nullptr, static_cast<char*>(recv_r), recv_r_len};
  Leg rl{left_fd, nullptr, static_cast<char*>(recv_l), recv_l_len};
  bool flaky = false;
  if (NetFaultActive()) {
    if (NetFaultDrops(right_fd)) sr.done = sr.len;  // blackholed rightward
    if (NetFaultDrops(left_fd)) sl.done = sl.len;   // blackholed leftward
    if (sr.done < sr.len) NetFaultDelay(right_fd);
    if (sl.done < sl.len) NetFaultDelay(left_fd);
    flaky = true;
  }
  auto pending = [](const Leg& l) { return l.done < l.len; };
  while (pending(sr) || pending(sl) || pending(rr) || pending(rl)) {
    struct pollfd fds[2];
    fds[0] = {right_fd, 0, 0};
    fds[1] = {left_fd, 0, 0};
    if (pending(sr)) fds[0].events |= POLLOUT;
    if (pending(rr)) fds[0].events |= POLLIN;
    if (pending(sl)) fds[1].events |= POLLOUT;
    if (pending(rl)) fds[1].events |= POLLIN;
    int r = poll(fds, 2, 30000);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // 30s of total silence: peer is gone
    auto drive_send = [flaky](Leg& l, short revents) -> bool {
      if (!(l.done < l.len) ||
          !(revents & (POLLOUT | POLLERR | POLLHUP)))
        return true;
      size_t want = l.len - l.done;
      if (flaky) {
        size_t cap = NetFaultChop(l.fd);
        if (cap > 0 && cap < want) want = cap;
      }
      ssize_t w = send(l.fd, l.sp + l.done, want,
                       MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (w < 0 && errno == EAGAIN) ++l.stalls;
      if (w > 0) {
        if (static_cast<size_t>(w) < want) ++l.shorts;
        l.done += static_cast<size_t>(w);
      }
      return true;
    };
    auto drive_recv = [](Leg& l, short revents) -> bool {
      if (!(l.done < l.len) || !(revents & (POLLIN | POLLERR | POLLHUP)))
        return true;
      ssize_t g = recv(l.fd, l.rp + l.done, l.len - l.done, MSG_DONTWAIT);
      if (g == 0) return false;
      if (g < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (g > 0) l.done += static_cast<size_t>(g);
      return true;
    };
    if (!drive_send(sr, fds[0].revents) || !drive_recv(rr, fds[0].revents) ||
        !drive_send(sl, fds[1].revents) || !drive_recv(rl, fds[1].revents))
      return false;
  }
  // One folded update per fd (out + in together; no latency sample — the
  // four legs overlap, see Exchange).
  if (NetLinkEnabled()) {
    LinkRecord(right_fd, static_cast<long long>(sr.len),
               static_cast<long long>(rr.len), sr.stalls, sr.shorts, -1);
    LinkRecord(left_fd, static_cast<long long>(sl.len),
               static_cast<long long>(rl.len), sl.stalls, sl.shorts, -1);
  }
  return true;
}

void CloseFd(int fd) {
  if (fd < 0) return;
  NetFaultForget(fd);
  close(fd);
}

void ShutdownFd(int fd) {
  if (fd >= 0) shutdown(fd, SHUT_RDWR);
}

// Bucket bounds chosen for a TCP control/data plane: sub-100µs loopback
// sends up through multi-ms injected (or real DCN) delays; the last
// bucket is +inf.
const long long kNetLinkBucketUs[] = {50,   100,  250,   500,  1000,
                                      2500, 5000, 10000, 50000};
const int kNetLinkBuckets = 10;
static_assert(sizeof(kNetLinkBucketUs) / sizeof(kNetLinkBucketUs[0]) ==
                  kNetLinkBuckets - 1,
              "bucket bounds must be kNetLinkBuckets - 1 entries");

void NetLinkInit(bool enabled) {
  g_link_enabled.store(enabled, std::memory_order_relaxed);
}

bool NetLinkEnabled() {
  return g_link_enabled.load(std::memory_order_relaxed);
}

void NetLinkRecordShm(int peer_rank, long long bytes_out, long long bytes_in,
                      long long handoff_us) {
  if (peer_rank < 0 || !NetLinkEnabled()) return;
  std::lock_guard<std::mutex> lk(g_fault_mu);
  LinkStats& s = g_link_stats[peer_rank];
  s.shm_bytes_out += bytes_out;
  s.shm_bytes_in += bytes_in;
  if (bytes_out > 0) ++s.shm_handoffs;
  if (handoff_us >= 0) {
    s.shm_us_sum += handoff_us;
    ++s.shm_us_count;
    ++s.shm_us_buckets[LinkBucket(handoff_us)];
  }
}

void NetLinkRecordRtt(int peer_rank, long long rtt_us) {
  if (peer_rank < 0 || rtt_us < 0 || !NetLinkEnabled()) return;
  std::lock_guard<std::mutex> lk(g_fault_mu);
  LinkStats& s = g_link_stats[peer_rank];
  s.rtt_last_us = rtt_us;
  ++s.rtt_samples;
  // EWMA (alpha 0.2): smooth enough to ride out scheduler jitter, fresh
  // enough that a developing slow link moves it within a few beats.
  s.rtt_ewma_us = s.rtt_samples == 1
                      ? static_cast<double>(rtt_us)
                      : s.rtt_ewma_us + 0.2 * (rtt_us - s.rtt_ewma_us);
}

long long NetLinkSendsTotal() {
  std::lock_guard<std::mutex> lk(g_fault_mu);
  long long total = 0;
  for (const auto& kv : g_link_stats) total += kv.second.sends;
  return total;
}

std::vector<NetLinkLatencyTotal> NetLinkLatencyTotals() {
  std::lock_guard<std::mutex> lk(g_fault_mu);
  std::vector<NetLinkLatencyTotal> out;
  out.reserve(g_link_stats.size());
  for (const auto& kv : g_link_stats)
    out.push_back({kv.first, kv.second.send_us_sum, kv.second.send_us_count,
                   kv.second.rtt_last_us});
  return out;
}

std::string NetLinkInfo() {
  std::lock_guard<std::mutex> lk(g_fault_mu);
  std::string out = NetLinkEnabled() ? "1|" : "0|";
  bool first = true;
  for (const auto& kv : g_link_stats) {
    const LinkStats& s = kv.second;
    if (!first) out += ';';
    first = false;
    out += std::to_string(kv.first) + ":" + std::to_string(s.bytes_out) +
           ":" + std::to_string(s.bytes_in) + ":" + std::to_string(s.sends) +
           ":" + std::to_string(s.recvs) + ":" + std::to_string(s.stalls) +
           ":" + std::to_string(s.short_writes) + ":" +
           std::to_string(s.send_us_sum) + ":" +
           std::to_string(s.send_us_count) + ":";
    for (int i = 0; i < kNetLinkBuckets; ++i) {
      if (i) out += ',';
      out += std::to_string(s.send_us_buckets[i]);
    }
    out += ":" + std::to_string(s.rtt_last_us) + ":" +
           std::to_string(static_cast<long long>(s.rtt_ewma_us + 0.5)) +
           ":" + std::to_string(s.rtt_samples);
    out += ":" + std::to_string(s.shm_bytes_out) + ":" +
           std::to_string(s.shm_bytes_in) + ":" +
           std::to_string(s.shm_handoffs) + ":" +
           std::to_string(s.shm_us_sum) + ":" +
           std::to_string(s.shm_us_count) + ":";
    for (int i = 0; i < kNetLinkBuckets; ++i) {
      if (i) out += ',';
      out += std::to_string(s.shm_us_buckets[i]);
    }
    // The data-plane label: ring handoffs mean this peer's collective
    // hops ride shm (the TCP bytes that remain are rendezvous/heartbeat
    // control traffic, which always stays on the socket).
    out += std::string(":") +
           (s.shm_bytes_out + s.shm_bytes_in > 0 ? "shm" : "tcp");
  }
  return out;
}

}  // namespace hvdtpu
