// Compact binary wire format for the coordinator protocol.  Plays the role of
// the reference's FlatBuffers MPIRequest/MPIResponse schema
// (/root/reference/horovod/common/wire/mpi_message.fbs:36-100,
//  /root/reference/horovod/common/mpi_message.{h,cc}) but hand-rolled:
// little-endian scalars + length-prefixed strings, no external codegen.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

// Dtype codes -- shared with Python (horovod_tpu/common/dtypes.py).
enum DataType : uint8_t {
  HVD_UINT8 = 0,
  HVD_INT8 = 1,
  HVD_INT32 = 2,
  HVD_INT64 = 3,
  HVD_FLOAT16 = 4,
  HVD_FLOAT32 = 5,
  HVD_FLOAT64 = 6,
  HVD_BFLOAT16 = 7,
  HVD_BOOL = 8,
  HVD_UINT16 = 9,
};

enum OpType : uint8_t {
  OP_ALLREDUCE = 0,
  OP_ALLGATHER = 1,
  OP_BROADCAST = 2,
  // Negotiation-only: agree on order + stamp completion, move no data.
  // The XLA plane's metadata-cache fast path (jax/eager_mesh.py) submits
  // these instead of repeating the "__xp.*" metadata allreduce once every
  // rank holds the cached agreement (docs/performance.md).
  OP_NOOP = 3,
  // Point-to-point plane (docs/pipeline.md): a send/recv pair announces
  // the SAME tensor name from exactly two ranks, each naming the other in
  // Request.p2p_peer; the coordinator matches them into one RESP_SENDRECV
  // — the readiness contract collectives enforce across the world,
  // narrowed to a pair.
  OP_SEND = 4,
  OP_RECV = 5,
};

// Status codes -- shared with Python.
enum StatusCode : int32_t {
  ST_OK = 0,
  ST_UNKNOWN = 1,
  ST_PRECONDITION = 2,
  ST_ABORTED = 3,
  ST_INVALID = 4,
  ST_PENDING = 5,
  // Coordinated-abort statuses (fault tolerance, docs/fault-tolerance.md):
  // a peer rank died (control-socket EOF) or a collective stalled past
  // HVD_TPU_COLLECTIVE_TIMEOUT_SEC.  Both carry a message naming the
  // missing ranks / stalled tensors; Python maps them to
  // RanksDownError / CollectiveTimeoutError.
  ST_RANKS_DOWN = 6,
  ST_TIMEOUT = 7,
  // Elastic membership (docs/fault-tolerance.md#elastic-membership): the
  // job reshaped (a rank died and the survivors continued, or a standby
  // joined) and the collective carrying this status was cancelled at the
  // reshape barrier.  RETRYABLE: Python maps it to MembershipChangedError;
  // hvd.run_elastic re-enters agreement and resyncs state by root
  // broadcast instead of killing the job.
  ST_RESHAPE = 8,
};

// Wire-compression modes (docs/performance.md#wire-compression): what an
// fp32 allreduce bucket's payload is narrowed to on the wire.  Negotiated
// per bucket by the rank-0 coordinator (a `compression` field on the
// Response) from the job-wide HVD_TPU_COMPRESSION agreement, so every
// rank compresses/decompresses the same buckets the same way.  Shared
// with Python (horovod_tpu/common/config.py).
enum CompressionMode : uint8_t {
  COMP_NONE = 0,
  COMP_BF16 = 1,      // fp32 -> bfloat16 on the wire (2x fewer bytes)
  COMP_FP8 = 2,       // fp32 -> fp8-e4m3fn, saturating (4x fewer bytes)
};

size_t DataTypeSize(uint8_t dtype);
const char* DataTypeName(uint8_t dtype);
const char* OpName(uint8_t op);
const char* CompressionName(uint8_t mode);

// One rank's readiness announcement for one named tensor.
struct Request {
  int32_t rank = 0;
  uint8_t op = OP_ALLREDUCE;
  uint8_t dtype = HVD_FLOAT32;
  int32_t root_rank = -1;  // broadcast only
  std::string name;
  std::vector<int64_t> dims;
  // Point-to-point plane (OP_SEND/OP_RECV only): the counterpart rank this
  // announcement must pair with, and the sender/receiver-agreed channel
  // tag disambiguating concurrent transfers between the same pair.  -1 /
  // 0 on collectives.
  int32_t p2p_peer = -1;
  int32_t p2p_tag = 0;
  // Stage-group scoping (docs/pipeline.md#stage-groups): the sorted dense
  // ranks this collective is restricted to (the DP dimension within a
  // pipeline stage).  Empty = whole world (every pre-existing op).
  // Carried per-request rather than as persistent engine state so a
  // reshape barrier — which clears caches and renumbers ranks — can never
  // leave a stale membership armed anywhere.
  std::vector<int32_t> stage_ranks;
};

// One cache slot's announcements folded across a node by its
// sub-coordinator (docs/performance.md#control-plane-scaling): the ranks
// that announced the slot this tick, each with its announce timestamp
// (µs, mapped onto rank 0's clock by the sub-coordinator's PR-3 clock
// offset) so rank 0's last-to-announce straggler verdicts still name the
// true rank behind the aggregation, not the sub-coordinator.
struct BitGroup {
  uint32_t slot = 0;
  std::vector<int32_t> ranks;
  std::vector<int64_t> announce_us;  // parallel to ranks
};

struct RequestList {
  bool shutdown = false;
  std::vector<Request> requests;
  // Response-cache announcements (docs/performance.md): slot indices of
  // already-negotiated collectives this rank re-submitted unchanged.  A
  // few bytes per op instead of a string-named Request — the steady-state
  // fast path.  Caches mutate in broadcast response-list order on every
  // rank, so a slot index names the same collective everywhere.
  std::vector<uint32_t> cache_bits;
  // --- Coordinator-tree aggregate extensions (docs/performance.md
  // #control-plane-scaling).  A sub-coordinator (each host's
  // local-rank-0) folds its node's per-rank frames into ONE aggregate
  // frame per tick; rank 0 therefore holds O(hosts) control sockets and
  // processes O(hosts) frames per tick instead of O(ranks).  Leaf frames
  // leave all of these empty.
  // Announce timestamps parallel to `requests` (rank-0 clock µs); empty
  // = stamp on arrival (the direct/star behavior).
  std::vector<int64_t> announce_us;
  // Cache-bit announcements folded per slot across the node.
  std::vector<BitGroup> bit_groups;
  // Ranks whose frame this aggregate folds in (liveness accounting: rank
  // 0's last-frame-tick postmortem bookkeeping stays per TRUE rank).
  std::vector<int32_t> frames_from;
  // Worker deaths observed at the sub-coordinator (control-socket EOF):
  // forwarded so rank 0's coordinated abort names the true dead rank.
  std::vector<int32_t> dead_ranks;
  // Ranks of this node that left the decentralized steady state this
  // frame (miss fallback) — rank 0 resumes broadcasting only once every
  // rank has exited.
  std::vector<int32_t> steady_exits;
  // THIS sender left steady state with this frame (leaf form of
  // steady_exits); epoch/pos locate the miss for postmortem dumps.
  uint8_t steady_exit = 0;
  int64_t steady_epoch = 0;
  int64_t steady_pos = 0;
  // Elastic membership epoch this frame was built against
  // (docs/fault-tolerance.md#elastic-membership).  A mid-steady reshape
  // revocation breaks the strict send-one-wait-one alternation, so a
  // fallback frame built before the barrier can arrive after it; the
  // coordinator drops any frame whose epoch is older than its own
  // (cache bits would name cleared slots, announces would double-count
  // into the new membership's table).  Static jobs stay at 0 == 0.
  int64_t membership_epoch = 0;
  // Out-of-band heartbeat report (docs/fault-tolerance.md
  // #failure-detection): this frame exists ONLY to deliver `dead_ranks`
  // observed by the data-plane heartbeat detector and carries no
  // announcements.  It rides the control socket BETWEEN regular tick
  // frames, so the coordinator (and every sub-coordinator relay)
  // processes it and keeps waiting for the sender's real frame — the
  // send-one-wait-one alternation is preserved.
  bool hb_report = false;
};

enum ResponseType : uint8_t {
  RESP_ALLREDUCE = 0,
  RESP_ALLGATHER = 1,
  RESP_BROADCAST = 2,
  RESP_ERROR = 3,
  RESP_NOOP = 4,  // negotiation-only (OP_NOOP): stamp completion, no data
  // Matched send/recv pair (docs/pipeline.md): broadcast to EVERY rank so
  // response caches mutate in lockstep, executed only by the two ranks
  // named in p2p_src/p2p_dst.
  RESP_SENDRECV = 5,
};

// Coordinator verdict: either an (optionally fused) operation every rank must
// now execute in lockstep, or a typed error for one tensor.
struct Response {
  uint8_t type = RESP_ALLREDUCE;
  std::vector<std::string> names;  // >1 => fused allreduce
  std::string error_message;
  // Allgather only: dim-0 size contributed by each rank, indexed by rank.
  std::vector<int64_t> rank_dim0;
  // Allreduce only: the wire-compression verdict for this bucket
  // (CompressionMode), chosen by the rank-0 coordinator per bucket-size
  // class (bucket payload bytes >= HVD_TPU_COMPRESSION_MIN_BYTES) and
  // broadcast so every rank packs/unpacks the same wire format.  Cache
  // replays recompute it locally from the same lockstep-mutated state
  // (engine.cc ProcessCacheHits), so fresh and replayed buckets agree.
  uint8_t compression = COMP_NONE;
  // Point-to-point plane (RESP_SENDRECV only): the matched pair and tag.
  // Compression (above) applies to the inter-stage hop exactly as to an
  // allreduce bucket: the coordinator stamps the verdict, the sender
  // narrows, the receiver widens.
  int32_t p2p_src = -1;
  int32_t p2p_dst = -1;
  int32_t p2p_tag = 0;
  // Slot metadata for partial-participation ops (RESP_SENDRECV and
  // stage-scoped RESP_ALLREDUCE): dtype + dims of the negotiated tensor,
  // so ranks OUTSIDE the pair/group — which hold no table entry — can
  // still Put an identical response-cache slot at the same index
  // (docs/performance.md's lockstep-mutation contract; without this the
  // bit protocol would desynchronize on the first p2p op).
  uint8_t p2p_dtype = 0;
  std::vector<int64_t> p2p_dims;
  // Stage-group scoping for RESP_ALLREDUCE (empty = whole world); echoes
  // the agreed Request.stage_ranks so replays and non-members see the
  // membership without holding a request.
  std::vector<int32_t> stage_ranks;
};

struct ResponseList {
  bool shutdown = false;
  // Coordinated abort (distinct from a clean shutdown): non-zero when the
  // coordinator detected a dead rank (ST_RANKS_DOWN) or a collective
  // stalled past the hard deadline (ST_TIMEOUT).  Every rank poisons its
  // pending ops with this status + message and exits its loop.
  int32_t abort_code = 0;
  std::string abort_message;
  std::vector<Response> responses;
  // Cache slots every rank announced: replay the stored response for each,
  // in order, before executing `responses` (identical order everywhere).
  std::vector<uint32_t> cache_hits;
  // Online autotuning (docs/performance.md#autotuning): when present, the
  // coordinator's ParameterManager proposed new engine parameters this
  // tick.  Every rank applies them BEFORE replaying this list's cache
  // hits, so fusion-plan changes land at the same tick boundary
  // everywhere — the lockstep-mutation contract the response cache
  // established.  `tuned_frozen` marks the search's final verdict;
  // `tuned_window` is the coordinator's completed-window count.
  bool tuned_present = false;
  bool tuned_frozen = false;
  int64_t tuned_fusion_threshold = 0;
  int64_t tuned_cycle_time_us = 0;
  int64_t tuned_window = 0;
  // Wire-compression mode proposed with the tuned params (the third
  // autotune axis): applied in the same lockstep as fusion/cycle, so the
  // compression decision function mutates at one tick boundary everywhere.
  uint8_t tuned_compression = COMP_NONE;
  // Two-level cross-node algorithm boundary (the fourth autotune axis,
  // HVD_TPU_CROSS_ALGO_THRESHOLD): hierarchical allreduce buckets whose
  // payload is under this many bytes take the latency-bound
  // recursive-doubling (tree) cross-node exchange instead of the
  // bandwidth-optimal ring.  Broadcast with the tuned params so every
  // rank's per-bucket ring-vs-tree decision flips at one tick boundary.
  int64_t tuned_cross_algo_threshold = 0;
  // Elastic membership reshape (docs/fault-tolerance.md): when present,
  // this tick IS the reshape barrier.  The list carries the complete new
  // membership — for each new dense rank its previous rank (-1 for a
  // freshly admitted standby) and its data endpoint — so every receiver
  // derives its own new rank by finding itself (survivors by old rank,
  // joiners by endpoint), plus the engine parameters the new membership
  // must agree on from tick one: the job-wide cache capacity and the
  // currently applied tuned params (caches and the autotune search are
  // reset at the barrier, so these are the fresh baseline everywhere,
  // joiners included).  `reshape_lost` names the ranks (previous-epoch
  // numbering) that died and triggered a shrink; empty on pure grows.
  bool reshape_present = false;
  int64_t membership_epoch = 0;
  int64_t reshape_cache_capacity = 0;
  int64_t reshape_fusion_threshold = 0;
  int64_t reshape_cycle_time_us = 0;
  // Wire-compression re-agreement across the barrier: the new membership
  // (admitted standbys included) adopts the currently applied mode and
  // min-bytes floor, the same way it adopts cache capacity — a joiner's
  // own env must not make it pack buckets differently from survivors.
  uint8_t reshape_compression = COMP_NONE;
  int64_t reshape_compression_min_bytes = 0;
  // The currently applied ring-vs-tree boundary (the fourth autotune
  // axis) crosses the barrier with the other tuned params: a joiner's
  // env must not give it a different cross-algo verdict than survivors,
  // even though reshapes force the flat ring today — the re-agreement
  // keeps hvd_tpu_autotune_cross_algo_threshold identical everywhere.
  int64_t reshape_cross_algo_threshold = 0;
  std::vector<int32_t> member_old_ranks;      // index = new dense rank
  std::vector<std::string> member_endpoints;  // index = new dense rank
  std::vector<int32_t> reshape_lost;
  // Decentralized steady state (docs/performance.md
  // #control-plane-scaling): when present, the coordinator observed the
  // cache-hit slot stream repeat `steady_pattern` identically
  // HVD_TPU_STEADY_THRESHOLD times at quiesced cycle boundaries.  Every
  // rank arms self-clocked replay after processing this list: it replays
  // the pattern's stored responses locally, epoch by epoch, with ZERO
  // control-plane frames per cycle, falling back to full negotiation on
  // any miss.  `steady_groups` carries the observed per-tick grouping of
  // the last cycle (sizes summing to the pattern length) so replayed
  // buckets fuse identically on every rank regardless of local drain
  // timing.
  bool steady_present = false;
  std::vector<uint32_t> steady_pattern;
  std::vector<uint32_t> steady_groups;
  // The first broadcast after a steady window closed (all ranks fell
  // back): informational marker for flight/timeline symmetry — the
  // coordinator's pattern detector restarts at this list.
  bool steady_revoke = false;
};

// Data-plane heartbeat frame (docs/fault-tolerance.md#failure-detection):
// a fixed 16-byte liveness beacon exchanged between ring neighbours over
// dedicated sockets on the data listeners, off the engine tick, so a busy
// local ring never starves liveness.  A whole-process freeze (SIGSTOP, GC
// pause, kernel wedge) stops the beacons without closing the socket —
// the silence socket EOF can never report.  `epoch` pins the membership
// the beacon was sent under; a beacon from a previous epoch is dropped
// like a stale control frame.
struct HeartbeatFrame {
  uint32_t magic = 0x48564254;  // "HVBT"
  uint32_t sender_rank = 0;
  uint32_t epoch = 0;
  uint32_t seq = 0;
};

// Suspect-gossip variant of the beacon (same 16-byte layout, this magic,
// and `seq` reinterpreted as the SUSPECT rank): a rank that has flagged a
// silent peer repeats the accusation to its live neighbours every beat
// interval, and receivers re-gossip, so a suspicion hops around the ring
// to rank 0 even when the frozen rank sits between them — the data-plane
// analogue of the control plane's dead_ranks relay, needed mid-steady
// when zero control frames flow.
constexpr uint32_t kSuspectMagic = 0x48564253;  // "HVBS"

// Echo variant (same 16-byte layout, this magic): a rank that receives a
// beacon bounces it straight back on the same full-duplex beat socket
// with the magic swapped, preserving sender_rank / epoch / seq.  The
// original sender matches `seq` against its send-timestamp ring and folds
// the round trip into the per-link RTT estimate (net.h NetLinkRecordRtt)
// — continuous link telemetry riding the existing beacons, no extra
// frames on the data or control planes and no wire-format growth.
constexpr uint32_t kEchoMagic = 0x48564245;  // "HVBE"

constexpr size_t kHeartbeatFrameBytes = 16;

// Fixed-size little-endian encode/decode (no length prefix: the frame is
// its own framing, consumed in 16-byte chunks off a byte stream).
// ParseHeartbeat accepts all three magics (beacon, suspect gossip, echo);
// the caller dispatches on hb->magic.
void SerializeHeartbeat(const HeartbeatFrame& hb, uint8_t out[16]);
bool ParseHeartbeat(const uint8_t in[16], HeartbeatFrame* hb);

std::vector<uint8_t> SerializeRequestList(const RequestList& rl);
bool ParseRequestList(const std::vector<uint8_t>& buf, RequestList* rl);
std::vector<uint8_t> SerializeResponseList(const ResponseList& rl);
bool ParseResponseList(const std::vector<uint8_t>& buf, ResponseList* rl);

}  // namespace hvdtpu
