#include "wire.h"

#include <cstring>

namespace hvdtpu {

size_t DataTypeSize(uint8_t dtype) {
  switch (dtype) {
    case HVD_UINT8:
    case HVD_INT8:
    case HVD_BOOL:
      return 1;
    case HVD_FLOAT16:
    case HVD_BFLOAT16:
    case HVD_UINT16:
      return 2;
    case HVD_INT32:
    case HVD_FLOAT32:
      return 4;
    case HVD_INT64:
    case HVD_FLOAT64:
      return 8;
    default:
      return 0;
  }
}

const char* DataTypeName(uint8_t dtype) {
  switch (dtype) {
    case HVD_UINT8: return "uint8";
    case HVD_INT8: return "int8";
    case HVD_INT32: return "int32";
    case HVD_INT64: return "int64";
    case HVD_FLOAT16: return "float16";
    case HVD_FLOAT32: return "float32";
    case HVD_FLOAT64: return "float64";
    case HVD_BFLOAT16: return "bfloat16";
    case HVD_BOOL: return "bool";
    case HVD_UINT16: return "uint16";
    default: return "<unknown dtype>";
  }
}

const char* OpName(uint8_t op) {
  switch (op) {
    case OP_ALLREDUCE: return "allreduce";
    case OP_ALLGATHER: return "allgather";
    case OP_BROADCAST: return "broadcast";
    case OP_NOOP: return "cached-negotiation";
    case OP_SEND: return "send";
    case OP_RECV: return "recv";
    default: return "<unknown op>";
  }
}

const char* CompressionName(uint8_t mode) {
  switch (mode) {
    case COMP_NONE: return "none";
    case COMP_BF16: return "bf16";
    case COMP_FP8: return "fp8";
    default: return "<unknown compression>";
  }
}

namespace {

class Writer {
 public:
  std::vector<uint8_t> buf;
  void U8(uint8_t v) { buf.push_back(v); }
  void I32(int32_t v) {
    for (int i = 0; i < 4; ++i) buf.push_back((static_cast<uint32_t>(v) >> (8 * i)) & 0xff);
  }
  void U32(uint32_t v) {
    for (int i = 0; i < 4; ++i) buf.push_back((v >> (8 * i)) & 0xff);
  }
  void I64(int64_t v) {
    for (int i = 0; i < 8; ++i) buf.push_back((static_cast<uint64_t>(v) >> (8 * i)) & 0xff);
  }
  void Str(const std::string& s) {
    U32(static_cast<uint32_t>(s.size()));
    buf.insert(buf.end(), s.begin(), s.end());
  }
};

class Reader {
 public:
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  Reader(const std::vector<uint8_t>& b) : p(b.data()), end(b.data() + b.size()) {}
  bool Need(size_t n) {
    if (static_cast<size_t>(end - p) < n) { ok = false; return false; }
    return true;
  }
  uint8_t U8() { if (!Need(1)) return 0; return *p++; }
  uint32_t U32() {
    if (!Need(4)) return 0;
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= static_cast<uint32_t>(*p++) << (8 * i);
    return v;
  }
  int32_t I32() { return static_cast<int32_t>(U32()); }
  int64_t I64() {
    if (!Need(8)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v |= static_cast<uint64_t>(*p++) << (8 * i);
    return static_cast<int64_t>(v);
  }
  std::string Str() {
    uint32_t n = U32();
    if (!Need(n)) return "";
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

}  // namespace

std::vector<uint8_t> SerializeRequestList(const RequestList& rl) {
  Writer w;
  w.U8(rl.shutdown ? 1 : 0);
  w.U32(static_cast<uint32_t>(rl.requests.size()));
  for (const auto& r : rl.requests) {
    w.I32(r.rank);
    w.U8(r.op);
    w.U8(r.dtype);
    w.I32(r.root_rank);
    w.Str(r.name);
    w.U8(static_cast<uint8_t>(r.dims.size()));
    for (int64_t d : r.dims) w.I64(d);
    w.I32(r.p2p_peer);
    w.I32(r.p2p_tag);
    w.U32(static_cast<uint32_t>(r.stage_ranks.size()));
    for (int32_t sr : r.stage_ranks) w.I32(sr);
  }
  w.U32(static_cast<uint32_t>(rl.cache_bits.size()));
  for (uint32_t b : rl.cache_bits) w.U32(b);
  // Coordinator-tree aggregate section.  announce_us is either empty or
  // parallel to requests; serialize the actual length so the parse side
  // can restore the "no timestamps" (direct-star) form exactly.
  w.U32(static_cast<uint32_t>(rl.announce_us.size()));
  for (int64_t ts : rl.announce_us) w.I64(ts);
  w.U32(static_cast<uint32_t>(rl.bit_groups.size()));
  for (const auto& g : rl.bit_groups) {
    w.U32(g.slot);
    w.U32(static_cast<uint32_t>(g.ranks.size()));
    for (size_t i = 0; i < g.ranks.size(); ++i) {
      w.I32(g.ranks[i]);
      w.I64(i < g.announce_us.size() ? g.announce_us[i] : -1);
    }
  }
  w.U32(static_cast<uint32_t>(rl.frames_from.size()));
  for (int32_t r : rl.frames_from) w.I32(r);
  w.U32(static_cast<uint32_t>(rl.dead_ranks.size()));
  for (int32_t r : rl.dead_ranks) w.I32(r);
  w.U32(static_cast<uint32_t>(rl.steady_exits.size()));
  for (int32_t r : rl.steady_exits) w.I32(r);
  w.U8(rl.steady_exit);
  w.I64(rl.steady_epoch);
  w.I64(rl.steady_pos);
  w.I64(rl.membership_epoch);
  w.U8(rl.hb_report ? 1 : 0);
  return std::move(w.buf);
}

bool ParseRequestList(const std::vector<uint8_t>& buf, RequestList* rl) {
  Reader rd(buf);
  rl->shutdown = rd.U8() != 0;
  uint32_t n = rd.U32();
  rl->requests.clear();
  rl->requests.reserve(n);
  for (uint32_t i = 0; i < n && rd.ok; ++i) {
    Request r;
    r.rank = rd.I32();
    r.op = rd.U8();
    r.dtype = rd.U8();
    r.root_rank = rd.I32();
    r.name = rd.Str();
    uint8_t nd = rd.U8();
    for (uint8_t j = 0; j < nd; ++j) r.dims.push_back(rd.I64());
    r.p2p_peer = rd.I32();
    r.p2p_tag = rd.I32();
    uint32_t nsr = rd.U32();
    for (uint32_t j = 0; j < nsr && rd.ok; ++j)
      r.stage_ranks.push_back(rd.I32());
    rl->requests.push_back(std::move(r));
  }
  rl->cache_bits.clear();
  uint32_t nb = rd.U32();
  for (uint32_t i = 0; i < nb && rd.ok; ++i)
    rl->cache_bits.push_back(rd.U32());
  rl->announce_us.clear();
  uint32_t nts = rd.U32();
  for (uint32_t i = 0; i < nts && rd.ok; ++i)
    rl->announce_us.push_back(rd.I64());
  rl->bit_groups.clear();
  uint32_t ng = rd.U32();
  for (uint32_t i = 0; i < ng && rd.ok; ++i) {
    BitGroup g;
    g.slot = rd.U32();
    uint32_t nr = rd.U32();
    for (uint32_t j = 0; j < nr && rd.ok; ++j) {
      g.ranks.push_back(rd.I32());
      g.announce_us.push_back(rd.I64());
    }
    rl->bit_groups.push_back(std::move(g));
  }
  rl->frames_from.clear();
  uint32_t nf = rd.U32();
  for (uint32_t i = 0; i < nf && rd.ok; ++i)
    rl->frames_from.push_back(rd.I32());
  rl->dead_ranks.clear();
  uint32_t nd = rd.U32();
  for (uint32_t i = 0; i < nd && rd.ok; ++i)
    rl->dead_ranks.push_back(rd.I32());
  rl->steady_exits.clear();
  uint32_t nse = rd.U32();
  for (uint32_t i = 0; i < nse && rd.ok; ++i)
    rl->steady_exits.push_back(rd.I32());
  rl->steady_exit = rd.U8();
  rl->steady_epoch = rd.I64();
  rl->steady_pos = rd.I64();
  rl->membership_epoch = rd.I64();
  rl->hb_report = rd.U8() != 0;
  return rd.ok;
}

void SerializeHeartbeat(const HeartbeatFrame& hb, uint8_t out[16]) {
  Writer w;
  w.U32(hb.magic);
  w.U32(hb.sender_rank);
  w.U32(hb.epoch);
  w.U32(hb.seq);
  memcpy(out, w.buf.data(), kHeartbeatFrameBytes);
}

bool ParseHeartbeat(const uint8_t in[16], HeartbeatFrame* hb) {
  std::vector<uint8_t> buf(in, in + kHeartbeatFrameBytes);
  Reader rd(buf);
  hb->magic = rd.U32();
  hb->sender_rank = rd.U32();
  hb->epoch = rd.U32();
  hb->seq = rd.U32();
  return rd.ok &&
         (hb->magic == HeartbeatFrame().magic || hb->magic == kSuspectMagic ||
          hb->magic == kEchoMagic);
}

std::vector<uint8_t> SerializeResponseList(const ResponseList& rl) {
  Writer w;
  w.U8(rl.shutdown ? 1 : 0);
  w.I32(rl.abort_code);
  w.Str(rl.abort_message);
  w.U32(static_cast<uint32_t>(rl.responses.size()));
  for (const auto& r : rl.responses) {
    w.U8(r.type);
    w.U32(static_cast<uint32_t>(r.names.size()));
    for (const auto& nm : r.names) w.Str(nm);
    w.Str(r.error_message);
    w.U32(static_cast<uint32_t>(r.rank_dim0.size()));
    for (int64_t d : r.rank_dim0) w.I64(d);
    w.U8(r.compression);
    w.I32(r.p2p_src);
    w.I32(r.p2p_dst);
    w.I32(r.p2p_tag);
    w.U8(r.p2p_dtype);
    w.U32(static_cast<uint32_t>(r.p2p_dims.size()));
    for (int64_t d : r.p2p_dims) w.I64(d);
    w.U32(static_cast<uint32_t>(r.stage_ranks.size()));
    for (int32_t sr : r.stage_ranks) w.I32(sr);
  }
  w.U32(static_cast<uint32_t>(rl.cache_hits.size()));
  for (uint32_t h : rl.cache_hits) w.U32(h);
  w.U8((rl.tuned_present ? 1 : 0) | (rl.tuned_frozen ? 2 : 0));
  if (rl.tuned_present) {
    w.I64(rl.tuned_fusion_threshold);
    w.I64(rl.tuned_cycle_time_us);
    w.I64(rl.tuned_window);
    w.U8(rl.tuned_compression);
    w.I64(rl.tuned_cross_algo_threshold);
  }
  w.U8(rl.reshape_present ? 1 : 0);
  if (rl.reshape_present) {
    w.I64(rl.membership_epoch);
    w.I64(rl.reshape_cache_capacity);
    w.I64(rl.reshape_fusion_threshold);
    w.I64(rl.reshape_cycle_time_us);
    w.U8(rl.reshape_compression);
    w.I64(rl.reshape_compression_min_bytes);
    w.I64(rl.reshape_cross_algo_threshold);
    w.U32(static_cast<uint32_t>(rl.member_old_ranks.size()));
    for (size_t i = 0; i < rl.member_old_ranks.size(); ++i) {
      w.I32(rl.member_old_ranks[i]);
      w.Str(rl.member_endpoints[i]);
    }
    w.U32(static_cast<uint32_t>(rl.reshape_lost.size()));
    for (int32_t r : rl.reshape_lost) w.I32(r);
  }
  w.U8((rl.steady_present ? 1 : 0) | (rl.steady_revoke ? 2 : 0));
  if (rl.steady_present) {
    w.U32(static_cast<uint32_t>(rl.steady_pattern.size()));
    for (uint32_t s : rl.steady_pattern) w.U32(s);
    w.U32(static_cast<uint32_t>(rl.steady_groups.size()));
    for (uint32_t g : rl.steady_groups) w.U32(g);
  }
  return std::move(w.buf);
}

bool ParseResponseList(const std::vector<uint8_t>& buf, ResponseList* rl) {
  Reader rd(buf);
  rl->shutdown = rd.U8() != 0;
  rl->abort_code = rd.I32();
  rl->abort_message = rd.Str();
  uint32_t n = rd.U32();
  rl->responses.clear();
  rl->responses.reserve(n);
  for (uint32_t i = 0; i < n && rd.ok; ++i) {
    Response r;
    r.type = rd.U8();
    uint32_t nn = rd.U32();
    for (uint32_t j = 0; j < nn; ++j) r.names.push_back(rd.Str());
    r.error_message = rd.Str();
    uint32_t ns = rd.U32();
    for (uint32_t j = 0; j < ns; ++j) r.rank_dim0.push_back(rd.I64());
    r.compression = rd.U8();
    r.p2p_src = rd.I32();
    r.p2p_dst = rd.I32();
    r.p2p_tag = rd.I32();
    r.p2p_dtype = rd.U8();
    uint32_t npd = rd.U32();
    for (uint32_t j = 0; j < npd && rd.ok; ++j)
      r.p2p_dims.push_back(rd.I64());
    uint32_t ngr = rd.U32();
    for (uint32_t j = 0; j < ngr && rd.ok; ++j)
      r.stage_ranks.push_back(rd.I32());
    rl->responses.push_back(std::move(r));
  }
  rl->cache_hits.clear();
  uint32_t nh = rd.U32();
  for (uint32_t i = 0; i < nh && rd.ok; ++i)
    rl->cache_hits.push_back(rd.U32());
  uint8_t tuned_flags = rd.U8();
  rl->tuned_present = (tuned_flags & 1) != 0;
  rl->tuned_frozen = (tuned_flags & 2) != 0;
  if (rl->tuned_present) {
    rl->tuned_fusion_threshold = rd.I64();
    rl->tuned_cycle_time_us = rd.I64();
    rl->tuned_window = rd.I64();
    rl->tuned_compression = rd.U8();
    rl->tuned_cross_algo_threshold = rd.I64();
  }
  rl->member_old_ranks.clear();
  rl->member_endpoints.clear();
  rl->reshape_lost.clear();
  rl->reshape_present = rd.U8() != 0;
  if (rl->reshape_present) {
    rl->membership_epoch = rd.I64();
    rl->reshape_cache_capacity = rd.I64();
    rl->reshape_fusion_threshold = rd.I64();
    rl->reshape_cycle_time_us = rd.I64();
    rl->reshape_compression = rd.U8();
    rl->reshape_compression_min_bytes = rd.I64();
    rl->reshape_cross_algo_threshold = rd.I64();
    uint32_t nm = rd.U32();
    for (uint32_t i = 0; i < nm && rd.ok; ++i) {
      rl->member_old_ranks.push_back(rd.I32());
      rl->member_endpoints.push_back(rd.Str());
    }
    uint32_t nl = rd.U32();
    for (uint32_t i = 0; i < nl && rd.ok; ++i)
      rl->reshape_lost.push_back(rd.I32());
  }
  rl->steady_pattern.clear();
  rl->steady_groups.clear();
  uint8_t steady_flags = rd.U8();
  rl->steady_present = (steady_flags & 1) != 0;
  rl->steady_revoke = (steady_flags & 2) != 0;
  if (rl->steady_present) {
    uint32_t np = rd.U32();
    for (uint32_t i = 0; i < np && rd.ok; ++i)
      rl->steady_pattern.push_back(rd.U32());
    uint32_t ngr = rd.U32();
    for (uint32_t i = 0; i < ngr && rd.ok; ++i)
      rl->steady_groups.push_back(rd.U32());
  }
  return rd.ok;
}

}  // namespace hvdtpu
